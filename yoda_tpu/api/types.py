"""Core API types: the per-node TPU metrics CR and the pod model.

``TpuNodeMetrics`` is the TPU-native replacement for the reference's SCV CRD:
one cluster-scoped object per node, named after the node (the reference Gets
it by node name, reference pkg/yoda/scheduler.go:70). The field mapping from
the SCV schema (inferred at reference pkg/yoda/filter/filter.go:13-58,
collection/collection.go:59-78, score/algorithm.go:72-87):

    Scv.Status.CardNumber      -> len(TpuNodeMetrics.chips)
    Scv.Status.CardList        -> TpuNodeMetrics.chips
    Scv.Status.FreeMemorySum   -> TpuNodeMetrics.hbm_free_sum
    Scv.Status.TotalMemorySum  -> TpuNodeMetrics.hbm_total_sum
    Card.Health                -> TpuChip.health
    Card.FreeMemory (MB)       -> TpuChip.hbm_free (bytes)
    Card.TotalMemory (MB)      -> TpuChip.hbm_total (bytes)
    Card.Clock (MHz)           -> TpuChip.clock_mhz
    Card.Bandwidth             -> TpuChip.hbm_bandwidth_gbps
    Card.Core                  -> TpuChip.tflops_bf16
    Card.Power                 -> TpuChip.power_w

Net-new fields with no reference analog (required by the topology-aware gang
scheduler): ``generation``, ``topology_coords``, ``slice_id``, ``accel_type``,
and ``last_updated_unix`` (staleness detection — the reference never checks
freshness, see SURVEY.md §5 failure-detection row).
"""

from __future__ import annotations

import itertools
import logging
import time
from dataclasses import dataclass, field, asdict
from typing import Any, Iterable, Mapping, Sequence

HEALTHY = "Healthy"

# Rank for ">= generation" admission semantics. The reference demanded an
# EXACT clock match in Filter (card.Clock == clock, reference
# pkg/yoda/filter/filter.go:57) while its own collection/score used >=
# (collection.go:46, algorithm.go:49) — so a pod asking for clock 5705 was
# rejected by nodes with strictly faster cards. We keep one ordering,
# "at least this generation", everywhere.
GENERATION_RANK = {"v2": 2, "v3": 3, "v4": 4, "v5e": 5, "v5p": 6, "v6e": 7}

GROUP = "scheduler.yoda-tpu.dev"
VERSION = "v1"
KIND = "TpuNodeMetrics"

# Pod annotation carrying the scheduler's arrival-order sequence (FIFO
# tie-break that survives restart/relist; annotations persist arbitrary keys
# on real API servers, unlike unknown bare metadata fields).
SEQ_ANNOTATION = f"{GROUP}/creation-seq"

# The extended-resource name GKE TPU node pools expose; pods request chips
# through container resource limits on it (the label API's real-world twin).
TPU_RESOURCE = "google.com/tpu"


# Decimal/binary suffixes K8s integer quantities may carry. Extended
# resources must be whole numbers, so fractional ("0.5", "500m") forms are
# invalid for google.com/tpu and rejected below.
_QTY_SUFFIX = {
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
}


def _tpu_limit_of(spec: "Mapping[str, Any]") -> int:
    """Sum the containers' google.com/tpu limits, accepting the integer
    Kubernetes quantity notations ('4', '2k', '1Ki'). Unparseable values
    are logged and skipped — loudly, not silently (the repo's
    no-silent-zero rule): on a real cluster the API server validates
    quantities, so this only fires on hand-written fixtures."""
    total = 0
    for c in spec.get("containers", []) or []:
        raw = (c.get("resources", {}) or {}).get("limits", {}).get(TPU_RESOURCE)
        if raw is None:
            continue
        s = str(raw).strip()
        mult = 1
        for suffix, m in _QTY_SUFFIX.items():
            if s.endswith(suffix):
                s, mult = s[: -len(suffix)], m
                break
        try:
            total += int(s) * mult
        except ValueError:
            logging.getLogger("yoda_tpu.api").warning(
                "ignoring unparseable %s quantity %r", TPU_RESOURCE, raw
            )
            continue
    return total


def _resource_requests_of(spec: "Mapping[str, Any]") -> tuple[int, int]:
    """(cpu millicores, memory bytes) the pod effectively requests —
    upstream NodeResourcesFit accounting: per container, requests fall
    back to that container's limits; the pod total is
    max(sum(regular + restartable-init containers), peak of the ordered
    init phase) — sidecar init containers (restartPolicy: Always) keep
    running alongside the regular set so they join the concurrent sum,
    while each one-shot init container runs WITH the sidecars started
    before it (upstream's ordered scan: a one-shot is charged its own
    request plus the sidecar requests accumulated so far). Pod
    ``spec.overhead`` (RuntimeClass) is added on top, as upstream does.
    Unparseable values are logged and counted as 0 (the API server
    validates quantities on real clusters; our strictness budget is spent
    on tpu/* labels)."""
    from yoda_tpu.api.quantity import QuantityError, parse_cpu, parse_quantity

    def one(c: Mapping[str, Any]) -> tuple[int, int]:
        res = c.get("resources") or {}
        req = res.get("requests") or {}
        lim = res.get("limits") or {}
        # PER-RESOURCE fallback (upstream defaulting): a resource absent
        # from requests takes that resource's limit — not the whole dict.
        cpu_raw = req.get("cpu", lim.get("cpu"))
        mem_raw = req.get("memory", lim.get("memory"))
        cpu = mem = 0
        log = logging.getLogger("yoda_tpu.api")
        if cpu_raw is not None:
            try:
                cpu = parse_cpu(str(cpu_raw))
            except QuantityError as e:
                log.warning("ignoring unparseable cpu request: %s", e)
        if mem_raw is not None:
            try:
                # k8s memory quantities: a bare number is BYTES.
                mem = parse_quantity(str(mem_raw), default_unit=1)
            except QuantityError as e:
                log.warning("ignoring unparseable memory request: %s", e)
        return cpu, mem

    regular = [one(c) for c in spec.get("containers") or []]
    # Ordered init-phase scan (upstream): sidecars accumulate as they
    # start; each one-shot init runs concurrently with the sidecars
    # declared BEFORE it, so its charge is request + accumulated sidecars.
    side_cpu = side_mem = 0        # sidecars started so far
    init_cpu = init_mem = 0        # peak of the init phase
    for c in spec.get("initContainers") or []:
        ccpu, cmem = one(c)
        if c.get("restartPolicy") == "Always":
            side_cpu += ccpu
            side_mem += cmem
        else:
            init_cpu = max(init_cpu, side_cpu + ccpu)
            init_mem = max(init_mem, side_mem + cmem)
    cpu = max(sum(c for c, _ in regular) + side_cpu, init_cpu)
    mem = max(sum(m for _, m in regular) + side_mem, init_mem)
    o_cpu, o_mem = one({"resources": {"requests": spec.get("overhead") or {}}})
    return cpu + o_cpu, mem + o_mem


@dataclass
class TpuChip:
    """One TPU chip on a host — the analog of one SCV ``Card``."""

    index: int
    health: str = HEALTHY
    hbm_free: int = 0          # bytes
    hbm_total: int = 0         # bytes
    clock_mhz: int = 0
    hbm_bandwidth_gbps: int = 0
    tflops_bf16: int = 0
    power_w: int = 0
    # True when hbm_free/hbm_total were read from live hardware counters
    # (PJRT memory_stats or the libtpu metrics service) rather than derived
    # from the spec table + label accounting. Provenance for operators, and
    # the agent's input for classifying unattributable usage into the
    # node-level ``external_used_chips`` count the scheduler's reservation
    # corrections key on (NativeTpuAgent._external_used).
    hw_read: bool = False
    # Tensorcore duty cycle [0, 100] from the libtpu metrics service
    # (agent --libtpu-metrics; tpu-info's utilization column). Purely
    # observational — aggregated on /metrics as the fleet-mean gauge
    # yoda_tpu_duty_cycle_avg_pct (per-chip values live in the CR) for
    # operators chasing underutilized fleets; the scheduler never filters
    # or scores on it (a busy chip is already excluded by its HBM usage
    # under the exclusive-chip model). None = source not available.
    duty_cycle_pct: float | None = None

    @property
    def healthy(self) -> bool:
        return self.health == HEALTHY


@dataclass
class TpuNodeMetrics:
    """Per-node TPU metrics CR, published by the node agent (one per node,
    named after the node — mirrors the SCV Get-by-node-name contract,
    reference pkg/yoda/scheduler.go:70)."""

    name: str
    chips: list[TpuChip] = field(default_factory=list)
    generation: str = "v5e"
    accel_type: str = ""                      # e.g. "v5p-16"
    slice_id: str = ""                        # multi-host slice this node belongs to
    topology_coords: tuple[int, int, int] = (0, 0, 0)  # host coords within slice
    last_updated_unix: float = 0.0
    resource_version: int = 0
    # Which collection path produced these values (agent provenance, e.g.
    # "env", "device-files", "jax-runtime+memstats") — lets operators tell
    # hardware-read metrics from spec-table fallbacks (VERDICT r2 #4).
    source: str = ""
    # Hardware-read used chips whose consumption the agent could NOT
    # attribute to any running pod on the node at scrape time — an
    # external tenant / foreign process. The scheduler must treat these
    # as occupied-by-nobody: they absorb no accountant reservation
    # (filter_plugin.invisible_reservations) and earn no stale-freed
    # credit. Always 0 for spec-table agents (their usage is label
    # attribution by construction, so every used chip is pod-backed).
    external_used_chips: int = 0

    @property
    def chip_count(self) -> int:
        return len(self.chips)

    @property
    def hbm_free_sum(self) -> int:
        return sum(c.hbm_free for c in self.chips)

    @property
    def hbm_total_sum(self) -> int:
        return sum(c.hbm_total for c in self.chips)

    @property
    def generation_rank(self) -> int:
        return GENERATION_RANK.get(self.generation.strip().lower(), 0)

    def healthy_chips(self) -> list[TpuChip]:
        return [c for c in self.chips if c.healthy]

    def fresh(self, *, max_age_s: float, now: float | None = None) -> bool:
        """Staleness check (net-new vs reference; SURVEY.md §5)."""
        now = time.time() if now is None else now
        return (now - self.last_updated_unix) <= max_age_s

    def values_equal(self, other: "TpuNodeMetrics") -> bool:
        """Equality on every schedulability-relevant field — everything
        except the publish timestamp, resource version, and the purely
        observational per-chip duty cycle (a continuously fluctuating
        telemetry value the scheduler never filters or scores on: leaving
        it relevant would classify EVERY heartbeat as a real change and
        reintroduce the per-heartbeat rebuild storm the elision exists to
        prevent). Otherwise derived from the dataclass so a FUTURE field
        defaults to RELEVANT (consumers: the informer's heartbeat
        classification and the fleet-array incremental diff — a hand-kept
        field list would silently classify real changes as heartbeats)."""
        import dataclasses

        def neutral(t: "TpuNodeMetrics") -> "TpuNodeMetrics":
            return dataclasses.replace(
                t,
                last_updated_unix=0.0,
                resource_version=0,
                chips=[
                    dataclasses.replace(c, duty_cycle_pct=None)
                    for c in t.chips
                ],
            )

        return neutral(self) == neutral(other)

    # --- CR (de)serialization, used by the fake/real API server paths ---

    def to_obj(self) -> dict[str, Any]:
        return {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": KIND,
            "metadata": {
                "name": self.name,
                "resourceVersion": str(self.resource_version),
            },
            "status": {
                "generation": self.generation,
                "accelType": self.accel_type,
                "sliceId": self.slice_id,
                "topologyCoords": list(self.topology_coords),
                "lastUpdatedUnix": self.last_updated_unix,
                "source": self.source,
                "chipCount": self.chip_count,
                "hbmFreeSum": self.hbm_free_sum,
                "hbmTotalSum": self.hbm_total_sum,
                "externalUsedChips": self.external_used_chips,
                "chips": [asdict(c) for c in self.chips],
            },
        }

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "TpuNodeMetrics":
        st = obj.get("status", {})
        return cls(
            name=obj["metadata"]["name"],
            chips=[TpuChip(**c) for c in st.get("chips", [])],
            generation=st.get("generation", "v5e"),
            accel_type=st.get("accelType", ""),
            slice_id=st.get("sliceId", ""),
            topology_coords=tuple(st.get("topologyCoords", (0, 0, 0))),
            last_updated_unix=st.get("lastUpdatedUnix", 0.0),
            resource_version=int(obj["metadata"].get("resourceVersion", "0")),
            source=st.get("source", ""),
            external_used_chips=st.get("externalUsedChips", 0),
        )


@dataclass(frozen=True)
class Taint:
    """A v1.Taint (spec.taints entry on a Node)."""

    key: str
    value: str = ""
    effect: str = "NoSchedule"   # NoSchedule | PreferNoSchedule | NoExecute


@dataclass(frozen=True)
class Toleration:
    """A v1.Toleration (spec.tolerations entry on a Pod)."""

    key: str = ""                # empty key + Exists tolerates everything
    operator: str = "Equal"      # Equal | Exists
    value: str = ""
    effect: str = ""             # empty matches every effect

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return not self.key or self.key == taint.key
        return self.key == taint.key and self.value == taint.value

    def to_obj(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.key:
            out["key"] = self.key
        out["operator"] = self.operator
        if self.operator == "Equal":
            out["value"] = self.value
        if self.effect:
            out["effect"] = self.effect
        return out

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "Toleration":
        return cls(
            key=obj.get("key", ""),
            operator=obj.get("operator", "Equal"),
            value=obj.get("value", ""),
            effect=obj.get("effect", ""),
        )


@dataclass(frozen=True)
class NodeSelectorRequirement:
    """One matchExpressions entry of a v1.NodeSelectorTerm. Operator
    semantics mirror upstream labels.Selector: NotIn and DoesNotExist also
    match nodes MISSING the key; Gt/Lt compare single integer values;
    unknown operators fail closed."""

    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        v = labels.get(self.key)
        op = self.operator
        if op == "In":
            return v is not None and v in self.values
        if op == "NotIn":
            return v is None or v not in self.values
        if op == "Exists":
            return v is not None
        if op == "DoesNotExist":
            return v is None
        if op in ("Gt", "Lt"):
            if v is None or not self.values:
                return False
            try:
                have, want = int(v), int(self.values[0])
            except ValueError:
                return False
            return have > want if op == "Gt" else have < want
        return False

    def to_obj(self) -> dict[str, Any]:
        out: dict[str, Any] = {"key": self.key, "operator": self.operator}
        if self.values:
            out["values"] = list(self.values)
        return out

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "NodeSelectorRequirement":
        return cls(
            key=obj.get("key", ""),
            operator=obj.get("operator", ""),
            values=tuple(obj.get("values") or ()),
        )


@dataclass(frozen=True)
class NodeSelectorTerm:
    """A v1.NodeSelectorTerm: matchExpressions and matchFields AND
    together within the term; terms OR together at the affinity level.
    Upstream semantics: an EMPTY term matches no objects, and the only
    valid matchFields key is ``metadata.name`` (evaluated against the
    node's name); anything else fails closed."""

    match_expressions: tuple[NodeSelectorRequirement, ...] = ()
    match_fields: tuple[NodeSelectorRequirement, ...] = ()

    def matches(self, labels: Mapping[str, str], node_name: str = "") -> bool:
        if not self.match_expressions and not self.match_fields:
            return False  # upstream: an empty term selects nothing
        if not all(r.matches(labels) for r in self.match_expressions):
            return False
        for f in self.match_fields:
            if f.key != "metadata.name":
                return False  # the only upstream-valid field key
            if not f.matches({"metadata.name": node_name}):
                return False
        return True

    def to_obj(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.match_expressions:
            out["matchExpressions"] = [
                r.to_obj() for r in self.match_expressions
            ]
        if self.match_fields:
            out["matchFields"] = [r.to_obj() for r in self.match_fields]
        return out

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "NodeSelectorTerm":
        return cls(
            match_expressions=tuple(
                NodeSelectorRequirement.from_obj(r)
                for r in obj.get("matchExpressions") or ()
            ),
            match_fields=tuple(
                NodeSelectorRequirement.from_obj(r)
                for r in obj.get("matchFields") or ()
            ),
        )


def _host_ports_of(spec: "Mapping[str, Any]") -> tuple[tuple[int, str, str], ...]:
    """(hostPort, protocol, hostIP) triples claimed by the pod's containers
    — upstream NodePorts accounting (regular + restartable init containers;
    one-shot init containers release their ports before the pod runs, but
    upstream counts all containers conservatively and so do we)."""
    out: list[tuple[int, str, str]] = []
    for c in list(spec.get("containers") or ()) + list(
        spec.get("initContainers") or ()
    ):
        for p in c.get("ports") or ():
            hp = p.get("hostPort")
            if not hp:
                continue
            out.append(
                (int(hp), p.get("protocol") or "TCP", p.get("hostIP") or "0.0.0.0")
            )
    return tuple(out)


def host_ports_conflict(
    a: tuple[int, str, str], b: tuple[int, str, str]
) -> bool:
    """Upstream NodePorts conflict rule: same protocol + port, and the
    hostIPs overlap (equal, or either side is the 0.0.0.0 wildcard)."""
    pa, prota, ipa = a
    pb, protb, ipb = b
    return (
        pa == pb
        and prota == protb
        and (ipa == ipb or ipa == "0.0.0.0" or ipb == "0.0.0.0")
    )


@dataclass
class K8sPvc:
    """The scheduler-relevant slice of a v1.PersistentVolumeClaim — minimal
    volume awareness (the reference inherited upstream's VolumeBinding and
    volume-zone filters, reference pkg/register/register.go:10):

    - ``selected_node``: the ``volume.kubernetes.io/selected-node``
      annotation the volume binder writes for WaitForFirstConsumer claims —
      once set, pods using the claim may only land there.
    - ``zone``: the claim's ``topology.kubernetes.io/zone`` label (the
      minimal stand-in for the bound PV's node-affinity zone): nodes
      labeled with a DIFFERENT zone are rejected.
    - ``access_modes``: ``spec.accessModes`` — the upstream
      VolumeRestrictions inputs: a ``ReadWriteOnce`` claim already mounted
      by pods on some node forces co-location there (single-node
      attachment); ``ReadWriteOncePod`` additionally forbids any second
      pod at all.
    """

    name: str
    namespace: str = "default"
    selected_node: str | None = None
    zone: str | None = None
    access_modes: tuple[str, ...] = ()
    # spec.volumeName — the bound PersistentVolume. When the PV watch is
    # live, the filter resolves this to the PV's REAL spec.nodeAffinity
    # (superseding the zone-label stand-in above).
    volume_name: str | None = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def to_obj(self) -> dict[str, Any]:
        md: dict[str, Any] = {"name": self.name, "namespace": self.namespace}
        if self.selected_node:
            md["annotations"] = {
                "volume.kubernetes.io/selected-node": self.selected_node
            }
        if self.zone:
            md["labels"] = {"topology.kubernetes.io/zone": self.zone}
        out: dict[str, Any] = {
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": md,
        }
        spec: dict[str, Any] = {}
        if self.access_modes:
            spec["accessModes"] = list(self.access_modes)
        if self.volume_name:
            spec["volumeName"] = self.volume_name
        if spec:
            out["spec"] = spec
        return out

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "K8sPvc":
        md = obj.get("metadata", {})
        spec = obj.get("spec") or {}
        return cls(
            name=md["name"],
            namespace=md.get("namespace", "default"),
            selected_node=(md.get("annotations") or {}).get(
                "volume.kubernetes.io/selected-node"
            ),
            zone=(md.get("labels") or {}).get("topology.kubernetes.io/zone"),
            access_modes=tuple(spec.get("accessModes") or ()),
            volume_name=spec.get("volumeName") or None,
        )


@dataclass
class K8sPv:
    """The scheduler-relevant slice of a v1.PersistentVolume: its REAL
    ``spec.nodeAffinity`` (a local volume's node pin, a regional disk's
    zone set) and the claim it is bound to. Closes the admitted r4 gap
    ("the zone is read off the claim, not the bound PV" — PARITY.md): the
    reference inherited the full upstream VolumeBinding filter
    (pkg/register/register.go:10), whose hard predicate is exactly the
    bound PV's node affinity."""

    name: str  # cluster-scoped
    # spec.nodeAffinity.required.nodeSelectorTerms — terms OR, a term's
    # expressions AND (the NodeSelectorTerm type used by pod nodeAffinity).
    node_affinity: tuple["NodeSelectorTerm", ...] = ()
    claim_ref: str | None = None  # "namespace/name" of the bound claim
    # spec.csi.driver — which attach limit (K8sNode.attach_limits) this
    # volume counts against (upstream NodeVolumeLimits). None: not a
    # CSI volume, exempt from attach counting.
    driver: str | None = None

    def allows_node(self, node: "K8sNode | None") -> tuple[bool, str]:
        """Hard VolumeBinding predicate. Fail-closed when the PV
        constrains but the Node object is unknown (the pod_admits_on
        convention: scheduling onto an unlabeled mystery node would
        strand the workload next to a volume it cannot mount)."""
        if not self.node_affinity:
            return True, ""
        if node is None:
            return False, (
                f"pv {self.name} has node affinity but the node object "
                "is unknown"
            )
        if any(
            term.matches(node.labels, node.name) for term in self.node_affinity
        ):
            return True, ""
        return False, f"node fails pv {self.name}'s node affinity"

    def to_obj(self) -> dict[str, Any]:
        spec: dict[str, Any] = {}
        if self.node_affinity:
            spec["nodeAffinity"] = {
                "required": {
                    "nodeSelectorTerms": [
                        t.to_obj() for t in self.node_affinity
                    ]
                }
            }
        if self.claim_ref:
            ns, _, name = self.claim_ref.partition("/")
            spec["claimRef"] = {"namespace": ns, "name": name}
        if self.driver:
            spec["csi"] = {"driver": self.driver}
        return {
            "apiVersion": "v1",
            "kind": "PersistentVolume",
            "metadata": {"name": self.name},
            "spec": spec,
        }

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "K8sPv":
        spec = obj.get("spec") or {}
        terms = (
            ((spec.get("nodeAffinity") or {}).get("required") or {})
            .get("nodeSelectorTerms") or ()
        )
        ref = spec.get("claimRef") or None
        return cls(
            name=obj["metadata"]["name"],
            node_affinity=tuple(NodeSelectorTerm.from_obj(t) for t in terms),
            claim_ref=(
                f"{ref.get('namespace', 'default')}/{ref['name']}"
                if ref and ref.get("name")
                else None
            ),
            driver=(spec.get("csi") or {}).get("driver") or None,
        )


@dataclass
class K8sPdb:
    """The scheduler-relevant slice of a policy/v1 PodDisruptionBudget.

    Upstream DefaultPreemption (inherited by the reference via
    pkg/register/register.go:10) prefers candidate victim sets that
    violate no PDB; this type carries what that check needs: the pod
    selector and the disruption allowance. ``disruptions_allowed`` is
    ``status.disruptionsAllowed`` when the disruption controller has
    published it — the authoritative number; otherwise the allowance is
    derived from spec against the CURRENT matching-pod count (an
    approximation of the controller's expectedPods, adequate for victim
    *preference* — the eviction API remains the enforcement point).

    policy/v1 selector semantics: an empty selector ({}) matches every
    pod in the namespace; an absent selector matches none (modeled as
    ``selector=None``)."""

    name: str
    namespace: str = "default"
    selector: "Any | None" = None            # affinity.LabelSelector | None
    min_available: "int | str | None" = None      # int or "N%"
    max_unavailable: "int | str | None" = None    # int or "N%"
    disruptions_allowed: int | None = None        # status, when published

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def matches(self, pod: "PodSpec") -> bool:
        if pod.namespace != self.namespace or self.selector is None:
            return False
        return self.selector.matches(pod.labels)

    def allowed_disruptions(self, matching_running: int) -> int:
        """How many matching pods may be evicted right now. Percentage
        fields scale against ``matching_running`` (minAvailable rounds
        up, maxUnavailable rounds down — upstream's conservative
        directions)."""
        if self.disruptions_allowed is not None:
            return max(int(self.disruptions_allowed), 0)

        def scaled(v, *, round_up: bool) -> int:
            if isinstance(v, str) and v.endswith("%"):
                pct = int(v[:-1])
                exact = matching_running * pct / 100.0
                return int(-(-exact // 1)) if round_up else int(exact)
            return int(v)

        if self.max_unavailable is not None:
            return max(
                min(scaled(self.max_unavailable, round_up=False), matching_running),
                0,
            )
        if self.min_available is not None:
            return max(
                matching_running - scaled(self.min_available, round_up=True), 0
            )
        return matching_running  # no constraint declared

    def to_obj(self) -> dict[str, Any]:
        spec: dict[str, Any] = {}
        if self.selector is not None:
            spec["selector"] = self.selector.to_obj()
        if self.min_available is not None:
            spec["minAvailable"] = self.min_available
        if self.max_unavailable is not None:
            spec["maxUnavailable"] = self.max_unavailable
        out: dict[str, Any] = {
            "apiVersion": "policy/v1",
            "kind": "PodDisruptionBudget",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": spec,
        }
        if self.disruptions_allowed is not None:
            out["status"] = {"disruptionsAllowed": self.disruptions_allowed}
        return out

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "K8sPdb":
        # Deferred import: affinity builds on this module's selector types.
        from yoda_tpu.api.affinity import LabelSelector

        md = obj.get("metadata", {})
        spec = obj.get("spec") or {}
        status = obj.get("status") or {}
        return cls(
            name=md["name"],
            namespace=md.get("namespace", "default"),
            selector=LabelSelector.from_obj(spec.get("selector")),
            min_available=spec.get("minAvailable"),
            max_unavailable=spec.get("maxUnavailable"),
            disruptions_allowed=status.get("disruptionsAllowed"),
        )


@dataclass
class K8sNamespace:
    """The scheduler-relevant slice of a v1.Namespace: its labels, which
    pod-affinity ``namespaceSelector`` terms select over (api.affinity).
    The reference's upstream scheduler watched namespaces for the same
    reason."""

    name: str
    labels: dict[str, str] = field(default_factory=dict)

    def to_obj(self) -> dict[str, Any]:
        return {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {"name": self.name, "labels": dict(self.labels)},
        }

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "K8sNamespace":
        return cls(
            name=obj["metadata"]["name"],
            labels=dict(obj.get("metadata", {}).get("labels", {})),
        )


@dataclass
class K8sNode:
    """The scheduler-relevant slice of a v1.Node.

    The reference never reads Node objects itself, but its upstream
    snapshot carries them (reference pkg/yoda/scheduler.go:101), so cordon
    (spec.unschedulable), NoSchedule taints, and node deletion are honored
    for free there. This type restores that awareness first-party: the
    cluster backends watch /api/v1/nodes and the informer folds these into
    each NodeInfo."""

    name: str
    unschedulable: bool = False
    taints: list[Taint] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    # status.conditions[type=Ready]: False when the node controller
    # reports the kubelet unreachable/NotReady. Deliberately NOT part of
    # pod_admits_on — readiness policy belongs to the node health
    # monitor (yoda_tpu/nodehealth), which fences and repairs; hard
    # admission here would silently drop a whole failure-handling layer.
    ready: bool = True
    # status.allocatable, parsed (0 = undeclared -> that resource is not
    # enforced): the upstream NodeResourcesFit inputs. TPU chips are NOT
    # tracked here — the TpuNodeMetrics CR is the authority for those.
    alloc_cpu_milli: int = 0
    alloc_memory: int = 0
    alloc_pods: int = 0
    # status.images flattened to image-name -> sizeBytes (every name/tag
    # of an image maps to its size) — the ImageLocality scoring input
    # (plugins/yoda/image_locality.py). Empty = kubelet reports none.
    images: dict[str, int] = field(default_factory=dict)
    # status.allocatable "attachable-volumes-*" keys: limit-key suffix ->
    # max attachable volumes (upstream NodeVolumeLimits inputs, e.g.
    # "csi-pd.csi.storage.gke.io" -> 127). A K8sPv's driver counts
    # against the "csi-<driver>" (or bare "<driver>") key. Empty = no
    # declared limits, the filter is not enforced.
    attach_limits: dict[str, int] = field(default_factory=dict)

    def to_obj(self) -> dict[str, Any]:
        spec: dict[str, Any] = {}
        if self.unschedulable:
            spec["unschedulable"] = True
        if self.taints:
            spec["taints"] = [
                {"key": t.key, "value": t.value, "effect": t.effect}
                for t in self.taints
            ]
        out: dict[str, Any] = {
            "apiVersion": "v1",
            "kind": "Node",
            "metadata": {"name": self.name, "labels": dict(self.labels)},
            "spec": spec,
        }
        alloc: dict[str, str] = {}
        if self.alloc_cpu_milli:
            alloc["cpu"] = f"{self.alloc_cpu_milli}m"
        if self.alloc_memory:
            alloc["memory"] = str(self.alloc_memory)
        if self.alloc_pods:
            alloc["pods"] = str(self.alloc_pods)
        for suffix, limit in sorted(self.attach_limits.items()):
            alloc[f"attachable-volumes-{suffix}"] = str(limit)
        status: dict[str, Any] = {}
        if alloc:
            status["allocatable"] = alloc
        if self.images:
            status["images"] = [
                {"names": [name], "sizeBytes": size}
                for name, size in sorted(self.images.items())
            ]
        if not self.ready:
            # Emitted only when NotReady so ready nodes round-trip to the
            # same minimal object they always did.
            status["conditions"] = [{"type": "Ready", "status": "False"}]
        if status:
            out["status"] = status
        return out

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "K8sNode":
        from yoda_tpu.api.quantity import QuantityError, parse_cpu, parse_quantity

        spec = obj.get("spec", {})
        alloc = (obj.get("status") or {}).get("allocatable") or {}
        cpu = mem = pods = 0
        log = logging.getLogger("yoda_tpu.api")
        # Per-field: one bad field must not drop the others (and the
        # warning must be truthful about WHICH field is unenforced).
        if "cpu" in alloc:
            try:
                cpu = parse_cpu(str(alloc["cpu"]))
            except QuantityError:
                log.warning(
                    "node %s: unparseable allocatable cpu %r; not enforcing",
                    obj["metadata"]["name"], alloc["cpu"],
                )
        if "memory" in alloc:
            try:
                mem = parse_quantity(str(alloc["memory"]), default_unit=1)
            except QuantityError:
                log.warning(
                    "node %s: unparseable allocatable memory %r; not "
                    "enforcing", obj["metadata"]["name"], alloc["memory"],
                )
        if "pods" in alloc:
            try:
                pods = int(str(alloc["pods"]).strip())
            except ValueError:
                log.warning(
                    "node %s: unparseable allocatable pods %r; not enforcing",
                    obj["metadata"]["name"], alloc["pods"],
                )
        images: dict[str, int] = {}
        for img in (obj.get("status") or {}).get("images") or ():
            size = int(img.get("sizeBytes") or 0)
            for name in img.get("names") or ():
                images[name] = size
        attach_limits: dict[str, int] = {}
        for key, value in alloc.items():
            if not key.startswith("attachable-volumes-"):
                continue
            try:
                attach_limits[key[len("attachable-volumes-"):]] = int(
                    str(value).strip()
                )
            except ValueError:
                log.warning(
                    "node %s: unparseable %s %r; not enforcing",
                    obj["metadata"]["name"], key, value,
                )
        return cls(
            name=obj["metadata"]["name"],
            unschedulable=bool(spec.get("unschedulable", False)),
            taints=[
                Taint(
                    key=t.get("key", ""),
                    value=t.get("value", ""),
                    effect=t.get("effect", "NoSchedule"),
                )
                for t in spec.get("taints", [])
            ],
            labels=dict(obj.get("metadata", {}).get("labels", {})),
            alloc_cpu_milli=cpu,
            alloc_memory=mem,
            alloc_pods=pods,
            images=images,
            attach_limits=attach_limits,
            ready=not any(
                c.get("type") == "Ready"
                and str(c.get("status", "True")) == "False"
                for c in (obj.get("status") or {}).get("conditions") or ()
            ),
        )


def node_admits_pod(
    node: "K8sNode | None",
    tolerations: Sequence[Toleration],
    node_selector: Mapping[str, str] | None = None,
    node_affinity: Sequence[NodeSelectorTerm] = (),
) -> tuple[bool, str]:
    """Cordon + taint + nodeSelector + required-node-affinity admission:
    can the pod be placed on the node at all?

    Mirrors what upstream kube-scheduler's NodeUnschedulable,
    TaintToleration, and NodeAffinity plugins give the reference for free
    via its snapshot (reference pkg/yoda/scheduler.go:101). ``node is
    None`` (no Node object known — e.g. a fake-cluster test without node
    records) admits UNLESS the pod has a selector/affinity constraint:
    the scheduler is the enforcement point for those (kubelet does not
    re-check them), so an unverifiable constraint must reject, not pass
    vacuously. Only hard taint effects reject: NoSchedule / NoExecute;
    PreferNoSchedule (and preferred affinity) are scoring concerns, not
    filters."""
    if node_selector and (
        node is None
        or any(node.labels.get(k) != v for k, v in node_selector.items())
    ):
        return False, (
            "node labels do not match the pod's nodeSelector"
            if node is not None
            else "pod has a nodeSelector but the node object is unknown"
        )
    if node_affinity:
        # Terms OR; a term's matchExpressions AND (upstream semantics).
        if node is None:
            return False, (
                "pod has required node affinity but the node object is "
                "unknown"
            )
        if not any(t.matches(node.labels, node.name) for t in node_affinity):
            return False, (
                "node labels do not match the pod's required node affinity"
            )
    if node is None:
        return True, ""
    if node.unschedulable:
        return False, "node is cordoned (spec.unschedulable)"
    for taint in node.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return False, f"node has untolerated taint {taint.key}:{taint.effect}"
    return True, ""


def pod_admits_on(node: "K8sNode | None", pod: "PodSpec") -> tuple[bool, str]:
    """:func:`node_admits_pod` with the pod's own constraint set — the
    form every scheduler-side caller wants (filter, batch admission
    vector, gang planning, preemption eligibility)."""
    return node_admits_pod(
        node, pod.tolerations, pod.node_selector, pod.node_affinity
    )


def untolerated_soft_taints(node: "K8sNode | None", pod: "PodSpec") -> int:
    """How many PreferNoSchedule taints on the node the pod does NOT
    tolerate — the soft companion to the hard taint filter (upstream
    TaintToleration's scoring half). 0 when no Node object is known."""
    if node is None:
        return 0
    return sum(
        1
        for taint in node.taints
        if taint.effect == "PreferNoSchedule"
        and not any(t.tolerates(taint) for t in pod.tolerations)
    )


def preferred_affinity_score(node: "K8sNode | None", pod: "PodSpec") -> int:
    """Soft steering: [0, 100] fraction of the pod's
    preferredDuringSchedulingIgnoredDuringExecution term weights this node
    satisfies (upstream NodeAffinity scoring). 0 when the pod declares no
    preferences or the node object is unknown — soft constraints degrade
    gracefully, unlike the hard ones, which fail closed."""
    prefs = pod.preferred_node_affinity
    if not prefs or node is None:
        return 0
    total = sum(w for w, _ in prefs)
    if total <= 0:
        return 0
    matched = sum(
        w for w, t in prefs if t.matches(node.labels, node.name)
    )
    return matched * 100 // total


_pod_seq = itertools.count()


@dataclass
class PodSpec:
    """Minimal pod model: everything the scheduler reads off a v1.Pod.

    The reference reads only pod name and labels (reference
    pkg/yoda/filter/filter.go:12,19,36; sort/sort.go:13) plus the node's
    already-placed pods' labels for allocation scoring
    (score/algorithm.go:77-80).
    """

    name: str
    namespace: str = "default"
    labels: dict[str, str] = field(default_factory=dict)
    scheduler_name: str = "yoda-tpu"
    node_name: str | None = None
    phase: str = "Pending"
    uid: str = ""
    tolerations: list[Toleration] = field(default_factory=list)
    # spec.nodeSelector — how unmodified GKE TPU workloads steer onto node
    # pools (cloud.google.com/gke-tpu-accelerator / -topology node labels).
    # Enforced by node_admits_pod against K8sNode.labels: the scheduler is
    # the selector's enforcement point.
    node_selector: dict[str, str] = field(default_factory=dict)
    # spec.affinity.nodeAffinity.requiredDuringSchedulingIgnoredDuring
    # Execution.nodeSelectorTerms — the hard-affinity terms (OR of terms,
    # AND within a term).
    node_affinity: tuple[NodeSelectorTerm, ...] = ()
    # preferredDuringSchedulingIgnoredDuringExecution — (weight, term)
    # pairs, scored by preferred_affinity_score (soft steering).
    preferred_node_affinity: tuple[tuple[int, NodeSelectorTerm], ...] = ()
    # spec.affinity.podAffinity / podAntiAffinity (api.affinity module):
    # required terms filter, preferred terms score, existing pods'
    # anti-affinity is enforced symmetrically. The reference inherited
    # these from the upstream default plugins it ran alongside
    # (deploy/yoda-scheduler.yaml:15-27 adds yoda to the defaults).
    pod_affinity: tuple = ()          # tuple[PodAffinityTerm, ...]
    pod_anti_affinity: tuple = ()     # tuple[PodAffinityTerm, ...]
    preferred_pod_affinity: tuple = ()       # tuple[(int, PodAffinityTerm)]
    preferred_pod_anti_affinity: tuple = ()  # tuple[(int, PodAffinityTerm)]
    # spec.topologySpreadConstraints (api.affinity.TopologySpreadConstraint).
    topology_spread: tuple = ()
    # Sum of the containers' google.com/tpu resource limits — how
    # unmodified GKE TPU workloads request chips (requests.pod_request uses
    # it as the chip count when no tpu/chips label is present).
    tpu_resource_limit: int = 0
    # Effective cpu (millicores) / memory (bytes) requests across the
    # pod's containers (_resource_requests_of: per-container requests fall
    # back to limits; init containers contribute their max). Enforced
    # against K8sNode allocatable by node_fits_resources — the upstream
    # NodeResourcesFit half the reference inherited.
    cpu_milli_request: int = 0
    memory_request: int = 0
    # spec.priority — what the admission controller resolves from
    # priorityClassName; the fallback when no tpu/priority label is set
    # (upstream preemption orders by this field).
    spec_priority: int = 0
    # status.nominatedNodeName — written by preemption when victims were
    # evicted to make room (upstream parity: kubectl's NOMINATED NODE
    # column; other components see the earmarked capacity).
    nominated_node_name: str | None = None
    # spec.preemptionPolicy — "Never" pods queue at their priority but
    # must not trigger evictions (upstream PriorityClass preemptionPolicy).
    preemption_policy: str = "PreemptLowerPriority"
    # spec.schedulingGates — gate names; while non-empty the pod must NOT
    # be scheduled (upstream PodSchedulingReadiness: how Kueue and quota
    # controllers hold pods until admission).
    scheduling_gates: tuple[str, ...] = ()
    # spec.containers[].ports[].hostPort occupations as (port, protocol,
    # hostIP) — the upstream NodePorts filter the reference inherited
    # (reference pkg/register/register.go:10 runs the full default plugin
    # set): two pods claiming a conflicting host port cannot share a node
    # (host_ports_conflict).
    host_ports: tuple[tuple[int, str, str], ...] = ()
    # spec.volumes[].persistentVolumeClaim.claimName — minimal volume
    # awareness (upstream VolumeBinding/volume-zone parity, VERDICT r3):
    # pod placement honors the claim's selected-node annotation and zone
    # label (filter_plugin.node_fits_volumes against the PVC watch).
    pvc_names: tuple[str, ...] = ()
    # spec.containers[].image (regular containers, upstream ImageLocality's
    # scoring inputs — init containers run once and are not scored).
    container_images: tuple[str, ...] = ()
    creation_seq: int = field(default_factory=lambda: next(_pod_seq))

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}#{self.creation_seq}"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def to_obj(self) -> dict[str, Any]:
        spec: dict[str, Any] = {
            "schedulerName": self.scheduler_name,
            "nodeName": self.node_name,
        }
        if self.tolerations:
            spec["tolerations"] = [t.to_obj() for t in self.tolerations]
        if self.node_selector:
            spec["nodeSelector"] = dict(self.node_selector)
        affinity: dict[str, Any] = {}
        if self.node_affinity or self.preferred_node_affinity:
            na: dict[str, Any] = {}
            if self.node_affinity:
                na["requiredDuringSchedulingIgnoredDuringExecution"] = {
                    "nodeSelectorTerms": [
                        t.to_obj() for t in self.node_affinity
                    ]
                }
            if self.preferred_node_affinity:
                na["preferredDuringSchedulingIgnoredDuringExecution"] = [
                    {"weight": w, "preference": t.to_obj()}
                    for w, t in self.preferred_node_affinity
                ]
            affinity["nodeAffinity"] = na
        for key, req, pref in (
            ("podAffinity", self.pod_affinity, self.preferred_pod_affinity),
            (
                "podAntiAffinity",
                self.pod_anti_affinity,
                self.preferred_pod_anti_affinity,
            ),
        ):
            if not req and not pref:
                continue
            block: dict[str, Any] = {}
            if req:
                block["requiredDuringSchedulingIgnoredDuringExecution"] = [
                    t.to_obj() for t in req
                ]
            if pref:
                block["preferredDuringSchedulingIgnoredDuringExecution"] = [
                    {"weight": w, "podAffinityTerm": t.to_obj()}
                    for w, t in pref
                ]
            affinity[key] = block
        if affinity:
            spec["affinity"] = affinity
        if self.topology_spread:
            spec["topologySpreadConstraints"] = [
                c.to_obj() for c in self.topology_spread
            ]
        if self.spec_priority:
            spec["priority"] = self.spec_priority
        if self.preemption_policy != "PreemptLowerPriority":
            spec["preemptionPolicy"] = self.preemption_policy
        if self.scheduling_gates:
            spec["schedulingGates"] = [
                {"name": g} for g in self.scheduling_gates
            ]
        if self.pvc_names:
            spec["volumes"] = [
                {"name": f"vol-{i}", "persistentVolumeClaim": {"claimName": c}}
                for i, c in enumerate(self.pvc_names)
            ]
        if (
            self.tpu_resource_limit
            or self.cpu_milli_request
            or self.memory_request
            or self.host_ports
            or self.container_images
        ):
            resources: dict[str, Any] = {}
            if self.tpu_resource_limit:
                resources["limits"] = {
                    TPU_RESOURCE: str(self.tpu_resource_limit)
                }
            requests: dict[str, str] = {}
            if self.cpu_milli_request:
                requests["cpu"] = f"{self.cpu_milli_request}m"
            if self.memory_request:
                requests["memory"] = str(self.memory_request)
            if requests:
                resources["requests"] = requests
            container: dict[str, Any] = {"name": "main", "resources": resources}
            if self.container_images:
                container["image"] = self.container_images[0]
            if self.host_ports:
                container["ports"] = [
                    {"hostPort": p, "protocol": proto, "hostIP": ip}
                    for p, proto, ip in self.host_ports
                ]
            containers = [container]
            for i, image in enumerate(self.container_images[1:]):
                containers.append({"name": f"c{i + 1}", "image": image})
            spec["containers"] = containers
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "labels": dict(self.labels),
                "uid": self.uid,
                # Arrival-order sequence, preserved across (de)serialization so
                # FIFO tie-breaks survive a scheduler restart / relist. An
                # annotation (not a bare metadata field) so real API servers
                # persist it; absent it, relists fall back to the
                # creationTimestamp ordering in the list path.
                "annotations": {SEQ_ANNOTATION: str(self.creation_seq)},
            },
            "spec": spec,
            "status": (
                {"phase": self.phase, "nominatedNodeName": self.nominated_node_name}
                if self.nominated_node_name
                else {"phase": self.phase}
            ),
        }

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "PodSpec":
        md = obj["metadata"]
        spec = obj.get("spec", {})
        kwargs = {}
        restored = md.get("annotations", {}).get(SEQ_ANNOTATION)
        if restored is not None:
            try:
                restored = int(restored)
            except ValueError:
                restored = None
        if restored is not None:
            kwargs["creation_seq"] = restored
            # Keep the global counter ahead of restored sequences so pods
            # created after a restart/relist still sort behind older pods.
            global _pod_seq
            nxt = next(_pod_seq)
            if restored >= nxt:
                _pod_seq = itertools.count(restored + 1)
            else:
                _pod_seq = itertools.count(nxt)
        # Deferred import: affinity builds on this module's selector types.
        from yoda_tpu.api.affinity import (
            parse_pod_affinity,
            parse_topology_spread,
        )

        pa, paa, ppa, ppaa = parse_pod_affinity(spec)
        cpu_req, mem_req = _resource_requests_of(spec)
        return cls(
            name=md["name"],
            namespace=md.get("namespace", "default"),
            pod_affinity=pa,
            pod_anti_affinity=paa,
            preferred_pod_affinity=ppa,
            preferred_pod_anti_affinity=ppaa,
            topology_spread=parse_topology_spread(spec),
            labels=dict(md.get("labels", {})),
            scheduler_name=spec.get("schedulerName", "yoda-tpu"),
            node_name=spec.get("nodeName"),
            phase=obj.get("status", {}).get("phase", "Pending"),
            nominated_node_name=obj.get("status", {}).get("nominatedNodeName"),
            uid=md.get("uid", ""),
            tolerations=[
                Toleration.from_obj(t) for t in spec.get("tolerations", [])
            ],
            node_selector=dict(spec.get("nodeSelector") or {}),
            node_affinity=tuple(
                NodeSelectorTerm.from_obj(t)
                for t in (
                    ((spec.get("affinity") or {}).get("nodeAffinity") or {})
                    .get("requiredDuringSchedulingIgnoredDuringExecution")
                    or {}
                ).get("nodeSelectorTerms")
                or ()
            ),
            preferred_node_affinity=tuple(
                (
                    int(p.get("weight") or 0),
                    NodeSelectorTerm.from_obj(p.get("preference") or {}),
                )
                for p in ((spec.get("affinity") or {}).get("nodeAffinity") or {}).get(
                    "preferredDuringSchedulingIgnoredDuringExecution"
                )
                or ()
            ),
            tpu_resource_limit=_tpu_limit_of(spec),
            cpu_milli_request=cpu_req,
            memory_request=mem_req,
            spec_priority=int(spec.get("priority") or 0),
            preemption_policy=(
                spec.get("preemptionPolicy") or "PreemptLowerPriority"
            ),
            scheduling_gates=tuple(
                g.get("name", "") for g in spec.get("schedulingGates") or ()
            ),
            host_ports=_host_ports_of(spec),
            pvc_names=tuple(
                v["persistentVolumeClaim"]["claimName"]
                for v in spec.get("volumes") or ()
                if v.get("persistentVolumeClaim", {}).get("claimName")
            ),
            container_images=tuple(
                c["image"]
                for c in spec.get("containers") or ()
                if c.get("image")
            ),
            **kwargs,
        )


def make_node(
    name: str,
    *,
    chips: int = 4,
    hbm_per_chip: int = 16 << 30,
    hbm_free_per_chip: int | None = None,
    generation: str = "v5e",
    clock_mhz: int = 940,
    hbm_bandwidth_gbps: int = 819,
    tflops_bf16: int = 197,
    power_w: int = 170,
    slice_id: str = "",
    topology_coords: tuple[int, int, int] = (0, 0, 0),
    accel_type: str = "",
    unhealthy: Iterable[int] = (),
    now: float | None = None,
) -> TpuNodeMetrics:
    """Convenience constructor used by the fake publisher and tests."""
    free = hbm_per_chip if hbm_free_per_chip is None else hbm_free_per_chip
    bad = set(unhealthy)
    return TpuNodeMetrics(
        name=name,
        generation=generation,
        accel_type=accel_type or f"{generation}-{chips}",
        slice_id=slice_id,
        topology_coords=topology_coords,
        last_updated_unix=time.time() if now is None else now,
        chips=[
            TpuChip(
                index=i,
                health=("Unhealthy" if i in bad else HEALTHY),
                hbm_free=free,
                hbm_total=hbm_per_chip,
                clock_mhz=clock_mhz,
                hbm_bandwidth_gbps=hbm_bandwidth_gbps,
                tflops_bf16=tflops_bf16,
                power_w=power_w,
            )
            for i in range(chips)
        ],
    )
