"""API types: the TpuNodeMetrics CR schema, pod model, and pod-label requests.

This package is the replacement for the reference's external SCV CRD
(``github.com/NJUPT-ISL/SCV/api/v1``, reference go.mod:6) whose schema is
inferred from field usage in reference pkg/yoda/filter/filter.go:13-58 and
pkg/yoda/collection/collection.go:59-78.
"""

from yoda_tpu.api.quantity import parse_quantity, QuantityError
from yoda_tpu.api.types import (
    TpuChip,
    TpuNodeMetrics,
    PodSpec,
    HEALTHY,
    GENERATION_RANK,
    make_node,
)
from yoda_tpu.api.requests import (
    GangSpec,
    LabelParseError,
    TpuRequest,
    parse_request,
    pod_request,
    parse_topology,
)

__all__ = [
    "parse_quantity",
    "QuantityError",
    "TpuChip",
    "TpuNodeMetrics",
    "PodSpec",
    "HEALTHY",
    "GENERATION_RANK",
    "make_node",
    "GangSpec",
    "TpuRequest",
    "LabelParseError",
    "parse_request",
    "pod_request",
    "parse_topology",
]
