"""Pod-label request parsing: the user-facing constraint API.

The reference's entire user API is four pod labels (reference
readme.md:27-69, SURVEY.md §1 "User-facing API surface"):

    scv/number    -> tpu/chips       chips required on the node
    scv/memory    -> tpu/hbm         free HBM required PER CHIP (quantity)
    scv/clock     -> tpu/clock       minimum chip clock, MHz (>= semantics —
                                     the reference filtered on EXACT equality,
                                     filter/filter.go:57, rejecting faster
                                     cards; fixed here)
    scv/priority  -> tpu/priority    scheduling-queue priority (higher first)

Net-new labels (no reference analog; mandated by BASELINE.json north star):

    tpu/generation   minimum TPU generation, e.g. "v5e" (ordered by
                     GENERATION_RANK)
    tpu/gang         gang name: all pods sharing it are placed atomically
    tpu/gang-size    number of pods in the gang
                     (coscheduling compat: pod-group.scheduling.sigs.k8s.io/
                     name + /min-available and scheduling.x-k8s.io/pod-group
                     alias these two; explicit tpu/* labels win)
    tpu/topology     ICI slice shape "AxBxC" (hosts), e.g. "2x2x2"
    tpu/multislice   number of tpu/topology blocks the gang spans (the
                     Multislice pattern: ICI within each block, DCN
                     between blocks); gang size = multislice x prod(dims)

Parsing is strict: a malformed label raises ``LabelParseError`` and the pod is
reported Unschedulable with the message, instead of the reference's
silent-zero behavior (filter/filter.go:60-74, SURVEY.md §3.4 quirk 3).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Mapping

from yoda_tpu.api.quantity import (
    QuantityError,
    parse_int,
    parse_quantity,
    parse_signed_int,
)
from yoda_tpu.api.types import GENERATION_RANK

# Label keys.
CHIPS = "tpu/chips"
HBM = "tpu/hbm"
CLOCK = "tpu/clock"
GENERATION = "tpu/generation"
PRIORITY = "tpu/priority"
GANG = "tpu/gang"
GANG_SIZE = "tpu/gang-size"
# Compat aliases for workloads written for the sig-scheduling coscheduling
# plugin: its PodGroup label conventions map onto gangs (min-available =
# all-or-nothing size). Explicit tpu/* labels win over aliases.
PG_NAME_LITE = "pod-group.scheduling.sigs.k8s.io/name"
PG_MIN_LITE = "pod-group.scheduling.sigs.k8s.io/min-available"
PG_NAME = "scheduling.x-k8s.io/pod-group"
TOPOLOGY = "tpu/topology"
MULTISLICE = "tpu/multislice"
# Elastic gangs (goodput-driven rebalancing): the gang may run with any
# member count in [min-members, max-members]; tpu/gang-size remains the
# DESIRED size. The background rebalancer shrinks an elastic gang toward
# min-members under contention (surplus members park) and grows it toward
# max-members into free capacity. Plain gangs only — a topology gang's
# size is pinned by its ICI block shape.
MIN_MEMBERS = "tpu/min-members"
MAX_MEMBERS = "tpu/max-members"


class LabelParseError(ValueError):
    """A tpu/* label failed strict validation."""


@dataclass(frozen=True)
class GangSpec:
    name: str
    size: int
    topology: tuple[int, ...] | None = None  # hosts per ICI dimension
    # Number of disjoint `topology` blocks the gang spans (Multislice:
    # data parallelism over DCN between blocks, ICI within each).
    # size == slices x prod(topology) when topology is set.
    slices: int = 1
    # Elastic bounds (tpu/min-members / tpu/max-members): None = rigid.
    # When set, the gang runs whole at any EFFECTIVE size in
    # [min_size, max_size]; the rebalancer owns the effective size
    # (GangPlugin.set_effective_size) and `size` stays the desired one.
    min_size: int | None = None
    max_size: int | None = None

    @property
    def hosts(self) -> int:
        return self.size

    @property
    def elastic(self) -> bool:
        return self.min_size is not None or self.max_size is not None

    @property
    def floor(self) -> int:
        """Smallest member count the gang may run at."""
        return self.min_size if self.min_size is not None else self.size

    @property
    def ceiling(self) -> int:
        """Largest member count the gang may grow to."""
        return self.max_size if self.max_size is not None else self.size


@dataclass(frozen=True)
class TpuRequest:
    """Parsed, validated scheduling constraints for one pod."""

    chips: int | None = None          # None: no explicit count (see effective_chips)
    hbm_per_chip: int = 0             # bytes of free HBM required per chip
    min_clock_mhz: int = 0
    min_generation_rank: int = 0
    priority: int = 0
    gang: GangSpec | None = None

    @property
    def effective_chips(self) -> int:
        """Chip count used for per-chip checks. The reference defaults to one
        qualifying card when ``scv/number`` is absent (filter/filter.go:14-15:
        requires CardNumber > 0, number = 1)."""
        return 1 if self.chips is None else self.chips

    @property
    def wants_tpu(self) -> bool:
        """True when the pod expresses any TPU constraint at all."""
        return (
            self.chips is not None
            or self.hbm_per_chip > 0
            or self.min_clock_mhz > 0
            or self.min_generation_rank > 0
            or self.gang is not None
        )


def parse_topology(text: str) -> tuple[int, ...]:
    """Parse ``"AxBxC"`` (1–3 dims) into a host-count-per-dimension tuple."""
    parts = text.strip().lower().split("x")
    if not 1 <= len(parts) <= 3:
        raise LabelParseError(f"{TOPOLOGY} must have 1-3 dims, got {text!r}")
    try:
        dims = tuple(parse_int(p, field=TOPOLOGY) for p in parts)
    except QuantityError as e:
        raise LabelParseError(f"malformed {TOPOLOGY} {text!r}") from e
    if any(d < 1 for d in dims):
        raise LabelParseError(f"{TOPOLOGY} dims must be >= 1, got {text!r}")
    return dims


def gang_name_label(labels: Mapping[str, str]) -> tuple[str | None, str]:
    """(gang name, the label key it came from) — the ONE place alias
    resolution lives. Every reader of gang membership (parse_request, the
    gang plugin's watch handler, preemption's bound-member pinning) must go
    through this, or pods ganged only via the coscheduling alias labels
    become invisible to that reader."""
    if GANG in labels:
        return labels[GANG], GANG
    for alias in (PG_NAME_LITE, PG_NAME):
        if alias in labels:
            return labels[alias], alias
    return None, GANG


def gang_name_of(labels: Mapping[str, str]) -> str | None:
    """The pod's gang name (alias-aware, stripped), or None."""
    raw, _ = gang_name_label(labels)
    if raw is None:
        return None
    return raw.strip() or None


def parse_request(
    labels: Mapping[str, str], *, tpu_limit: int = 0, spec_priority: int = 0
) -> TpuRequest:
    """Parse a pod's labels into a ``TpuRequest``. Strict: raises
    ``LabelParseError`` on any malformed ``tpu/*`` value.

    ``tpu_limit`` carries the pod's ``google.com/tpu`` container resource
    limit (the way unmodified GKE TPU workloads request chips — no
    reference analog, the reference was label-only): it becomes the chip
    count when no ``tpu/chips`` label is present; an explicit label wins."""
    try:
        chips = parse_int(labels[CHIPS], field=CHIPS) if CHIPS in labels else None
        hbm = parse_quantity(labels[HBM]) if HBM in labels else 0
        clock = parse_int(labels[CLOCK], field=CLOCK) if CLOCK in labels else 0
    except QuantityError as e:
        raise LabelParseError(str(e)) from e
    if chips is None and tpu_limit > 0:
        chips = tpu_limit

    gen_rank = 0
    if GENERATION in labels:
        gen = labels[GENERATION].strip().lower()
        if gen not in GENERATION_RANK:
            raise LabelParseError(
                f"unknown {GENERATION} {labels[GENERATION]!r}; "
                f"expected one of {sorted(GENERATION_RANK)}"
            )
        gen_rank = GENERATION_RANK[gen]

    priority = spec_priority
    if PRIORITY in labels:
        # Queue priority may be negative (the reference's strconv.Atoi accepts
        # negatives, sort/sort.go:14) — parse as a signed int, but strictly.
        try:
            priority = parse_signed_int(labels[PRIORITY], field=PRIORITY)
        except QuantityError as e:
            raise LabelParseError(str(e)) from e

    # Coscheduling-compat aliases resolve to the tpu/* fields; explicit
    # tpu/* labels win (an unmodified PodGroup workload gangs correctly,
    # a migrated one can override).
    gang_raw, gang_key = gang_name_label(labels)
    size_raw = labels.get(GANG_SIZE)
    size_key = GANG_SIZE
    if size_raw is None and PG_MIN_LITE in labels:
        size_raw, size_key = labels[PG_MIN_LITE], PG_MIN_LITE

    gang = None
    if (
        gang_raw is not None
        or size_raw is not None
        or TOPOLOGY in labels
        or MULTISLICE in labels
        or MIN_MEMBERS in labels
        or MAX_MEMBERS in labels
    ):
        if gang_raw is None:
            present = [
                k
                for k in (size_key, TOPOLOGY, MULTISLICE, MIN_MEMBERS, MAX_MEMBERS)
                if k in labels
            ]
            raise LabelParseError(
                f"{'/'.join(present)} require {GANG} "
                f"(or the {PG_NAME_LITE} / {PG_NAME} alias)"
            )
        name = gang_raw.strip()
        if not name:
            raise LabelParseError(f"{gang_key} must be non-empty")
        topology = parse_topology(labels[TOPOLOGY]) if TOPOLOGY in labels else None
        n_slices = 1
        if MULTISLICE in labels:
            if topology is None:
                raise LabelParseError(f"{MULTISLICE} requires {TOPOLOGY}")
            try:
                n_slices = parse_int(labels[MULTISLICE], field=MULTISLICE)
            except QuantityError as e:
                raise LabelParseError(str(e)) from e
            if n_slices < 1:
                raise LabelParseError(f"{MULTISLICE} must be >= 1")
        if size_raw is not None:
            try:
                size = parse_int(size_raw, field=size_key)
            except QuantityError as e:
                raise LabelParseError(str(e)) from e
            if size < 1:
                raise LabelParseError(f"{size_key} must be >= 1")
        elif topology is not None:
            size = n_slices * math.prod(topology)
        else:
            raise LabelParseError(
                f"{gang_key} requires {GANG_SIZE} (or {PG_MIN_LITE}) "
                f"or {TOPOLOGY}"
            )
        if topology is not None:
            expected = n_slices * math.prod(topology)
            if expected != size:
                what = f"{TOPOLOGY} {labels[TOPOLOGY]!r}"
                if MULTISLICE in labels:
                    what += f" x {MULTISLICE} {n_slices}"
                raise LabelParseError(
                    f"{what} implies {expected} hosts but {GANG_SIZE} is {size}"
                )
        min_size = max_size = None
        if MIN_MEMBERS in labels or MAX_MEMBERS in labels:
            if topology is not None:
                raise LabelParseError(
                    f"{MIN_MEMBERS}/{MAX_MEMBERS} apply to plain gangs only "
                    f"(a {TOPOLOGY} gang's size is pinned by its ICI block)"
                )
            if MIN_MEMBERS in labels:
                try:
                    min_size = parse_int(labels[MIN_MEMBERS], field=MIN_MEMBERS)
                except QuantityError as e:
                    raise LabelParseError(str(e)) from e
                if not 1 <= min_size <= size:
                    raise LabelParseError(
                        f"{MIN_MEMBERS} must be in [1, {GANG_SIZE}={size}], "
                        f"got {min_size}"
                    )
            if MAX_MEMBERS in labels:
                try:
                    max_size = parse_int(labels[MAX_MEMBERS], field=MAX_MEMBERS)
                except QuantityError as e:
                    raise LabelParseError(str(e)) from e
                if max_size < size:
                    raise LabelParseError(
                        f"{MAX_MEMBERS} must be >= {GANG_SIZE}={size}, "
                        f"got {max_size}"
                    )
        gang = GangSpec(
            name=name, size=size, topology=topology, slices=n_slices,
            min_size=min_size, max_size=max_size,
        )

    return TpuRequest(
        chips=chips,
        hbm_per_chip=hbm,
        min_clock_mhz=clock,
        min_generation_rank=gen_rank,
        priority=priority,
        gang=gang,
    )


def pod_request(pod) -> TpuRequest:
    """Parse a pod's scheduling constraints: ``tpu/*`` labels plus the GKE
    ``google.com/tpu`` container resource limit as the chip-count fallback
    (api.types.PodSpec.tpu_resource_limit). Use this — not bare
    ``parse_request(pod.labels)`` — wherever a whole pod is in hand, so
    label pods and resource-limit pods are accounted identically.

    Memoized per pod object (TpuRequest is frozen): snapshot pods stored by
    the informer are re-parsed every scheduling cycle (scoring, accounting,
    claims, fleet lowering) — those repeats hit the memo. Watch events
    decode fresh PodSpec objects, so they always miss. In-place label edits
    are re-detected by the input-key comparison, so the memo can never
    serve stale constraints."""
    key = (
        tuple(sorted(pod.labels.items())),
        getattr(pod, "tpu_resource_limit", 0),
        getattr(pod, "spec_priority", 0),
    )
    memo = getattr(pod, "_req_memo", None)
    if memo is not None and memo[0] == key:
        return memo[1]
    req = parse_request(pod.labels, tpu_limit=key[1], spec_priority=key[2])
    try:
        pod._req_memo = (key, req)
    except Exception:  # noqa: BLE001 — slots/frozen pods just skip the memo
        pass
    return req
