"""Inter-pod affinity/anti-affinity and topology-spread constraints.

The reference scheduler wrapped the whole upstream kube-scheduler
(reference pkg/register/register.go:10), so pods it scheduled got the
default plugin set's InterPodAffinity and PodTopologySpread behavior for
free alongside the yoda plugin (reference deploy/yoda-scheduler.yaml:15-27
enables yoda *in addition to* the defaults). This module restores those
first-party, on the same evaluation model upstream uses:

- **Required pod affinity**: the candidate node must share a topology
  domain (same value of ``topologyKey`` in node labels) with at least one
  existing pod matching the term's label selector. Upstream's first-pod
  rule applies: a term that matches NO existing pod anywhere, but whose
  selector matches the incoming pod itself (in its own namespace), is
  treated as satisfied — otherwise the first replica of a
  self-affinitizing group could never schedule.
- **Required pod anti-affinity**: the candidate node must NOT share a
  topology domain with any existing pod matching the term. A node without
  the topology key belongs to no domain and never conflicts (upstream
  semantics).
- **Anti-affinity symmetry**: an EXISTING pod's required anti-affinity
  terms also repel the incoming pod (upstream checks both directions;
  without this, "spread me" pods are only protected against later
  arrivals, not earlier ones).
- **Preferred terms** contribute a signed weight sum for scoring — in
  BOTH directions, as upstream InterPodAffinity scores: the incoming
  pod's own preferred terms over existing pods, and existing pods'
  preferred (anti-)affinity terms matching the incoming pod, each
  credited/debited in the existing pod's topology domain.
- **Topology spread**: ``maxSkew``/``topologyKey``/``whenUnsatisfiable``
  over the pods matching the constraint's selector in the incoming pod's
  namespace. ``DoNotSchedule`` filters; ``ScheduleAnyway`` scores.

Scope notes (documented divergences from upstream):

- Only pods on nodes the scheduler snapshots (TPU nodes) are visible; pods
  on non-TPU nodes neither satisfy affinity nor trigger anti-affinity.
- In-flight (reserved-but-unbound) pods ARE visible when the caller feeds
  them via the ``pending`` argument (gang members parked at Permit —
  GangPlugin.pending_placements); without that feed, enforcement is
  against bound pods only.
- ``minDomains`` is supported (DoNotSchedule constraints: global min is
  0 while fewer eligible domains exist). ``namespaceSelector`` IS supported
  (union with the explicit namespaces list, upstream semantics), resolved
  against the Namespace watch. A non-empty selector over a namespace with
  no data is treated DIRECTIONALLY: out of scope for affinity/preferred
  terms (the pod just waits — safe), but IN scope for required
  anti-affinity and its symmetry check (unknown namespaces still repel:
  a hard separation constraint must not silently fail open).

Evaluators are built once per (pod, scheduling cycle) — O(pods x terms)
precomputation — and answer per-node queries from dict lookups, keeping
the per-node cost O(terms) on the hot path (SURVEY.md §3.2's hot-loop
discipline).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Iterable, Mapping

from yoda_tpu.api.types import K8sNode, NodeSelectorRequirement, PodSpec

if TYPE_CHECKING:  # the evaluators take duck-typed snapshot/NodeInfo views
    from yoda_tpu.framework.interfaces import NodeInfo, Snapshot


@dataclass(frozen=True)
class LabelSelector:
    """A v1.LabelSelector. Upstream semantics: an EMPTY selector (present
    but with no requirements) matches everything; an ABSENT selector is
    represented by ``None`` at the use site and matches nothing."""

    match_labels: tuple[tuple[str, str], ...] = ()
    match_expressions: tuple[NodeSelectorRequirement, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        if any(labels.get(k) != v for k, v in self.match_labels):
            return False
        return all(r.matches(labels) for r in self.match_expressions)

    def to_obj(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        if self.match_labels:
            out["matchLabels"] = dict(self.match_labels)
        if self.match_expressions:
            out["matchExpressions"] = [
                r.to_obj() for r in self.match_expressions
            ]
        return out

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any] | None) -> "LabelSelector | None":
        if obj is None:
            return None
        return cls(
            match_labels=tuple(sorted((obj.get("matchLabels") or {}).items())),
            match_expressions=tuple(
                NodeSelectorRequirement.from_obj(r)
                for r in obj.get("matchExpressions") or ()
            ),
        )


@dataclass(frozen=True)
class PodAffinityTerm:
    """A v1.PodAffinityTerm: selector over pods + the topology key that
    defines co-location. Namespace scoping (upstream semantics): the
    explicit ``namespaces`` list and the namespaces selected by
    ``namespace_selector`` (over Namespace LABELS) are UNIONED; when both
    are unset, the owner pod's namespace applies. An EMPTY (no-requirement)
    namespace_selector selects every namespace."""

    topology_key: str
    selector: LabelSelector | None = None
    namespaces: tuple[str, ...] = ()
    namespace_selector: LabelSelector | None = None

    def allows_namespace(
        self,
        other_ns: str,
        owner_namespace: str,
        ns_labels: Mapping[str, Mapping[str, str]] | None = None,
        *,
        assume_unknown: bool = False,
    ) -> bool:
        """Is ``other_ns`` within this term's namespace scope?
        ``ns_labels`` maps namespace name -> labels (from the Namespace
        watch); an empty selector needs no data. For a non-empty selector
        over a namespace with no data, ``assume_unknown`` decides: False
        (default) treats it as out of scope — the safe direction for
        AFFINITY, where a false negative just holds the pod — while
        anti-affinity callers pass True so unknown namespaces still REPEL
        (a false negative there would co-locate workloads a hard
        constraint separates)."""
        if not self.namespaces and self.namespace_selector is None:
            return other_ns == owner_namespace
        if other_ns in self.namespaces:
            return True
        sel = self.namespace_selector
        if sel is None:
            return False
        if not sel.match_labels and not sel.match_expressions:
            return True  # empty selector: all namespaces (upstream)
        labels = (ns_labels or {}).get(other_ns)
        if labels is None:
            return assume_unknown
        return sel.matches(labels)

    def matches_pod(
        self,
        other: PodSpec,
        owner_namespace: str,
        ns_labels: Mapping[str, Mapping[str, str]] | None = None,
        *,
        assume_unknown: bool = False,
    ) -> bool:
        if self.selector is None:
            return False  # absent selector matches no objects (upstream)
        return self.allows_namespace(
            other.namespace,
            owner_namespace,
            ns_labels,
            assume_unknown=assume_unknown,
        ) and self.selector.matches(other.labels)

    def to_obj(self) -> dict[str, Any]:
        out: dict[str, Any] = {"topologyKey": self.topology_key}
        if self.selector is not None:
            out["labelSelector"] = self.selector.to_obj()
        if self.namespaces:
            out["namespaces"] = list(self.namespaces)
        if self.namespace_selector is not None:
            out["namespaceSelector"] = self.namespace_selector.to_obj()
        return out

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "PodAffinityTerm":
        return cls(
            topology_key=obj.get("topologyKey", ""),
            selector=LabelSelector.from_obj(obj.get("labelSelector")),
            namespaces=tuple(obj.get("namespaces") or ()),
            namespace_selector=LabelSelector.from_obj(
                obj.get("namespaceSelector")
            ),
        )


@dataclass(frozen=True)
class TopologySpreadConstraint:
    """A v1.TopologySpreadConstraint (selector-scoped skew over topology
    domains). ``when_unsatisfiable`` is DoNotSchedule (hard) or
    ScheduleAnyway (soft). ``match_label_keys`` narrows the selector to
    pods sharing the incoming pod's values for those keys (upstream: a
    Deployment sets pod-template-hash there so each rollout spreads
    independently); keys absent from the incoming pod's labels are
    ignored, matching upstream."""

    max_skew: int
    topology_key: str
    when_unsatisfiable: str = "DoNotSchedule"
    selector: LabelSelector | None = None
    match_label_keys: tuple[str, ...] = ()
    # minDomains (DoNotSchedule only, upstream): while fewer eligible
    # domains exist than this, the global minimum is treated as 0 so new
    # pods keep spreading into new domains instead of stacking.
    min_domains: int = 0

    def effective_selector(
        self, pod_labels: Mapping[str, str]
    ) -> "LabelSelector | None":
        """The selector with match_label_keys folded in, ANDed as
        additional ``In`` requirements against the incoming pod's own
        values (upstream appends requirements — on a collision with the
        base selector the result matches NOTHING, it never overrides)."""
        if not self.match_label_keys or self.selector is None:
            return self.selector
        extra = tuple(
            NodeSelectorRequirement(key=k, operator="In", values=(pod_labels[k],))
            for k in self.match_label_keys
            if k in pod_labels
        )
        if not extra:
            return self.selector
        return LabelSelector(
            match_labels=self.selector.match_labels,
            match_expressions=self.selector.match_expressions + extra,
        )

    def to_obj(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "maxSkew": self.max_skew,
            "topologyKey": self.topology_key,
            "whenUnsatisfiable": self.when_unsatisfiable,
        }
        if self.selector is not None:
            out["labelSelector"] = self.selector.to_obj()
        if self.match_label_keys:
            out["matchLabelKeys"] = list(self.match_label_keys)
        if self.min_domains:
            out["minDomains"] = self.min_domains
        return out

    @classmethod
    def from_obj(cls, obj: Mapping[str, Any]) -> "TopologySpreadConstraint":
        return cls(
            max_skew=int(obj.get("maxSkew") or 1),
            topology_key=obj.get("topologyKey", ""),
            when_unsatisfiable=obj.get("whenUnsatisfiable", "DoNotSchedule"),
            selector=LabelSelector.from_obj(obj.get("labelSelector")),
            match_label_keys=tuple(obj.get("matchLabelKeys") or ()),
            min_domains=int(obj.get("minDomains") or 0),
        )


# --- v1.Pod spec parsing helpers (used by PodSpec.from_obj) ---


def parse_pod_affinity(
    spec: Mapping[str, Any],
) -> tuple[
    tuple[PodAffinityTerm, ...],
    tuple[PodAffinityTerm, ...],
    tuple[tuple[int, PodAffinityTerm], ...],
    tuple[tuple[int, PodAffinityTerm], ...],
]:
    """(required affinity, required anti-affinity, preferred affinity,
    preferred anti-affinity) from a v1.Pod spec mapping."""
    aff = spec.get("affinity") or {}

    def _required(block: Mapping[str, Any]) -> tuple[PodAffinityTerm, ...]:
        return tuple(
            PodAffinityTerm.from_obj(t)
            for t in block.get("requiredDuringSchedulingIgnoredDuringExecution")
            or ()
        )

    def _preferred(
        block: Mapping[str, Any],
    ) -> tuple[tuple[int, PodAffinityTerm], ...]:
        return tuple(
            (
                int(p.get("weight") or 0),
                PodAffinityTerm.from_obj(p.get("podAffinityTerm") or {}),
            )
            for p in block.get("preferredDuringSchedulingIgnoredDuringExecution")
            or ()
        )

    pa = aff.get("podAffinity") or {}
    paa = aff.get("podAntiAffinity") or {}
    return _required(pa), _required(paa), _preferred(pa), _preferred(paa)


def parse_topology_spread(
    spec: Mapping[str, Any],
) -> tuple[TopologySpreadConstraint, ...]:
    return tuple(
        TopologySpreadConstraint.from_obj(c)
        for c in spec.get("topologySpreadConstraints") or ()
    )


# --- evaluation ---


def _node_labels(ni: "NodeInfo") -> Mapping[str, str]:
    return ni.node.labels if ni.node is not None else {}


def pod_has_inter_pod_terms(pod: PodSpec) -> bool:
    return bool(
        pod.pod_affinity
        or pod.pod_anti_affinity
        or pod.preferred_pod_affinity
        or pod.preferred_pod_anti_affinity
    )


def fleet_has_inter_pod_terms(infos: Iterable["NodeInfo"]) -> bool:
    """Any bound pod anywhere declaring required anti-affinity OR preferred
    (anti-)affinity terms — the trigger for building an evaluator even when
    the incoming pod has no terms of its own (required-anti symmetry filter
    + symmetric preferred scoring). Callers cache this per snapshot
    version so term-free fleets pay nothing per cycle."""
    return any(
        p.pod_anti_affinity
        or p.preferred_pod_affinity
        or p.preferred_pod_anti_affinity
        for ni in infos
        for p in ni.pods
    )


@dataclass
class InterPodEvaluator:
    """Per-(pod, cycle) inter-pod affinity oracle.

    Precomputes, from one pass over the snapshot's bound pods:

    - per required-affinity term: the set of topology values whose domain
      contains a matching pod (``_ok_values``), or the self-match flag;
    - per required-anti-affinity term: the set of forbidden values;
    - symmetry: (key, value) domains forbidden by EXISTING pods'
      anti-affinity terms that match the incoming pod;
    - per preferred term: value sets for the signed score;
    - symmetric preferences: signed weight per (key, value) domain from
      EXISTING pods' preferred (anti-)affinity terms matching the
      incoming pod (upstream scores both directions).

    Per-node queries are then O(terms) dict lookups.
    """

    pod: PodSpec
    _ok_values: list[set[str]] = field(default_factory=list)
    _self_satisfied: list[bool] = field(default_factory=list)
    _bad_values: list[set[str]] = field(default_factory=list)
    _symmetry_bad: set[tuple[str, str]] = field(default_factory=set)
    _pref_values: list[tuple[int, str, set[str]]] = field(default_factory=list)
    _sym_pref: dict[tuple[str, str], int] = field(default_factory=dict)

    @classmethod
    def build(
        cls,
        snapshot: "Snapshot",
        pod: PodSpec,
        *,
        check_symmetry: bool = True,
        pending: Iterable[tuple[str, PodSpec]] = (),
    ) -> "InterPodEvaluator":
        """``pending``: (node name, pod spec) pairs for pods RESERVED on a
        node but not yet bound — gang members parked at Permit
        (GangPlugin.pending_placements). They are folded in exactly like
        bound pods (their domain comes from the assigned node's labels), so
        sibling cycles see each other's in-flight placements; entries whose
        uid already appears in the snapshot (bind raced the read) are
        skipped."""
        ev = cls(pod)
        ns_labels = snapshot.namespaces
        n_aff = len(pod.pod_affinity)
        ev._ok_values = [set() for _ in range(n_aff)]
        ev._bad_values = [set() for _ in range(len(pod.pod_anti_affinity))]
        # signed weight, topology key, matching values
        ev._pref_values = [
            (w, t.topology_key, set()) for w, t in pod.preferred_pod_affinity
        ] + [
            (-w, t.topology_key, set())
            for w, t in pod.preferred_pod_anti_affinity
        ]
        pref_terms = [t for _, t in pod.preferred_pod_affinity] + [
            t for _, t in pod.preferred_pod_anti_affinity
        ]
        any_term_matched = [False] * n_aff

        def _fold(labels: Mapping[str, str], other: PodSpec) -> None:
            for i, term in enumerate(pod.pod_affinity):
                if term.matches_pod(other, pod.namespace, ns_labels):
                    any_term_matched[i] = True
                    v = labels.get(term.topology_key)
                    if v is not None:
                        ev._ok_values[i].add(v)
            for j, term in enumerate(pod.pod_anti_affinity):
                if term.matches_pod(
                    other, pod.namespace, ns_labels, assume_unknown=True
                ):
                    v = labels.get(term.topology_key)
                    if v is not None:
                        ev._bad_values[j].add(v)
            for k, term in enumerate(pref_terms):
                if term.matches_pod(other, pod.namespace, ns_labels):
                    v = labels.get(term.topology_key)
                    if v is not None:
                        ev._pref_values[k][2].add(v)
            if check_symmetry and other.pod_anti_affinity:
                for term in other.pod_anti_affinity:
                    if term.matches_pod(
                        pod, other.namespace, ns_labels, assume_unknown=True
                    ):
                        v = labels.get(term.topology_key)
                        if v is not None:
                            ev._symmetry_bad.add((term.topology_key, v))
            # Symmetric preferred scoring (upstream InterPodAffinity): an
            # existing pod's preferred terms matching THIS pod add or
            # subtract weight in the existing pod's domain.
            for sign, terms in (
                (1, other.preferred_pod_affinity),
                (-1, other.preferred_pod_anti_affinity),
            ):
                for w, term in terms:
                    if term.matches_pod(pod, other.namespace, ns_labels):
                        v = labels.get(term.topology_key)
                        if v is not None:
                            dom = (term.topology_key, v)
                            ev._sym_pref[dom] = (
                                ev._sym_pref.get(dom, 0) + sign * w
                            )

        pending = tuple(pending)
        seen_uids: set[str] = set()
        for ni in snapshot.infos():
            labels = _node_labels(ni)
            for other in ni.pods:
                if pending:
                    seen_uids.add(other.uid)
                if other.uid == pod.uid:
                    continue  # a relisted copy of the pod itself never
                    # satisfies its own affinity (upstream parity)
                _fold(labels, other)
        for host, other in pending:
            if other.uid == pod.uid or other.uid in seen_uids:
                continue
            if host in snapshot:
                _fold(_node_labels(snapshot.get(host)), other)
        # Upstream first-pod rule: a required-affinity term matching no
        # existing pod anywhere is satisfied iff the incoming pod matches
        # its own term — the group's first member bootstraps the domain.
        ev._self_satisfied = [
            (not any_term_matched[i])
            and term.matches_pod(pod, pod.namespace, ns_labels)
            for i, term in enumerate(pod.pod_affinity)
        ]
        return ev

    @property
    def trivial(self) -> bool:
        """True when no per-node check or score could ever fire."""
        return (
            not self.pod.pod_affinity
            and not self.pod.pod_anti_affinity
            and not self._symmetry_bad
            and not self._pref_values
            and not self._sym_pref
        )

    @property
    def has_preferences(self) -> bool:
        """True when some node could receive a nonzero preference() —
        scoring fast-paths gate on this, not on evaluator existence (an
        evaluator built only for the symmetry check has no preferences)."""
        return bool(self._pref_values) or bool(self._sym_pref)

    def required_affinity_feasible(self, ni: "NodeInfo") -> bool:
        """Just the required-AFFINITY half of :meth:`feasible`. Within a
        cycle, eviction can only REMOVE matching pods — an ok-domain set
        never grows — so preemption uses this to skip nodes the preemptor
        could never land on no matter what is evicted (anti-affinity /
        symmetry / spread conflicts are deliberately NOT checked here:
        eviction can cure those)."""
        labels = _node_labels(ni)
        for i, term in enumerate(self.pod.pod_affinity):
            v = labels.get(term.topology_key)
            if self._self_satisfied[i]:
                if v is None:  # keyless node: the group could never join
                    return False
                continue
            if v is None or v not in self._ok_values[i]:
                return False
        return True

    def feasible(self, ni: "NodeInfo") -> tuple[bool, str]:
        labels = _node_labels(ni)
        for i, term in enumerate(self.pod.pod_affinity):
            v = labels.get(term.topology_key)
            if self._self_satisfied[i]:
                # Deliberate divergence from upstream (which drops the term
                # entirely): the bootstrapping pod must still land on a node
                # that HAS the topology key — a keyless node belongs to no
                # domain, so the group's later members could never join it
                # (a gang bootstrapping onto a keyless host would wedge).
                if v is None:
                    return False, (
                        f"node lacks topology key {term.topology_key!r} "
                        "required by the pod's own affinity group"
                    )
                continue
            if v is None or v not in self._ok_values[i]:
                return False, (
                    "no pod matching required pod affinity in the node's "
                    f"{term.topology_key!r} domain"
                )
        for j, term in enumerate(self.pod.pod_anti_affinity):
            v = labels.get(term.topology_key)
            if v is not None and v in self._bad_values[j]:
                return False, (
                    "required pod anti-affinity conflicts with a pod in the "
                    f"node's {term.topology_key!r} domain"
                )
        for key, bad in self._symmetry_bad:
            if labels.get(key) == bad:
                return False, (
                    "an existing pod's required anti-affinity repels this "
                    f"pod from the node's {key!r} domain"
                )
        return True, ""

    def preference(self, ni: "NodeInfo") -> int:
        """Signed sum of preferred term weights this node satisfies: the
        pod's own terms plus the symmetric contribution from existing
        pods' preferred terms (both directions, upstream parity)."""
        if not self._pref_values and not self._sym_pref:
            return 0
        labels = _node_labels(ni)
        total = 0
        for w, key, values in self._pref_values:
            v = labels.get(key)
            if v is not None and v in values:
                total += w
        for (key, value), w in self._sym_pref.items():
            if labels.get(key) == value:
                total += w
        return total


@dataclass
class SpreadEvaluator:
    """Per-(pod, cycle) topology-spread oracle.

    For each constraint, counts pods matching its selector (in the
    incoming pod's namespace) per topology domain, over nodes eligible for
    the pod (nodeSelector + required node affinity, upstream's
    domain-eligibility rule) that carry the topology key. Skew for placing
    on domain ``v`` is ``count[v] + 1 - min(counts)``.
    """

    pod: PodSpec
    # per constraint: (constraint, counts by value, min count over domains)
    _per: list[tuple[TopologySpreadConstraint, dict[str, int], int]] = field(
        default_factory=list
    )

    @staticmethod
    def _domain_eligible(ni: "NodeInfo", pod: PodSpec) -> bool:
        """Upstream's domain-eligibility rule: only the pod's own node
        steering (nodeSelector + required node affinity) decides which
        domains "exist" for balancing — taints and cordon deliberately
        excluded (upstream default)."""
        if not pod.node_selector and not pod.node_affinity:
            return True
        if ni.node is None:
            return False
        labels = ni.node.labels
        if any(labels.get(k) != v for k, v in pod.node_selector.items()):
            return False
        if pod.node_affinity and not any(
            t.matches(labels, ni.node.name) for t in pod.node_affinity
        ):
            return False
        return True

    @classmethod
    def build(
        cls,
        snapshot: "Snapshot",
        pod: PodSpec,
        *,
        pending: Iterable[tuple[str, PodSpec]] = (),
    ) -> "SpreadEvaluator":
        """``pending`` as in :meth:`InterPodEvaluator.build`: reserved-but-
        unbound pods counted in their assigned node's domains."""
        ev = cls(pod)
        if not pod.topology_spread:
            return ev
        pending = tuple(pending)
        counted: list[dict[str, int]] = [{} for _ in pod.topology_spread]
        seen_uids: set[str] = set()
        # match_label_keys folded into each constraint's selector once
        # (pins the count to pods sharing the incoming pod's values).
        selectors = [
            c.effective_selector(pod.labels) for c in pod.topology_spread
        ]

        def _count(ni: "NodeInfo", others: Iterable[PodSpec]) -> None:
            labels = _node_labels(ni)
            for c_i, c in enumerate(pod.topology_spread):
                v = labels.get(c.topology_key)
                if v is None:
                    continue
                counts = counted[c_i]
                counts.setdefault(v, 0)
                sel = selectors[c_i]
                for other in others:
                    if other.uid == pod.uid:
                        continue
                    if other.namespace != pod.namespace:
                        continue
                    if sel is not None and sel.matches(other.labels):
                        counts[v] += 1

        for ni in snapshot.infos():
            if pending:
                seen_uids.update(p.uid for p in ni.pods)
            if not cls._domain_eligible(ni, pod):
                continue
            _count(ni, ni.pods)
        for host, other in pending:
            if other.uid in seen_uids or host not in snapshot:
                continue
            ni = snapshot.get(host)
            if cls._domain_eligible(ni, pod):
                _count(ni, (other,))
        ev._per = [
            (c, counts, min(counts.values()) if counts else 0)
            for c, counts in zip(pod.topology_spread, counted)
        ]
        return ev

    @property
    def trivial(self) -> bool:
        return not self._per

    @property
    def has_soft(self) -> bool:
        """Any ScheduleAnyway constraint — the only kind :meth:`score`
        considers, so scoring fast-paths gate on this."""
        return any(
            c.when_unsatisfiable == "ScheduleAnyway" for c, _, _ in self._per
        )

    @property
    def has_hard(self) -> bool:
        return any(
            c.when_unsatisfiable == "DoNotSchedule" for c, _, _ in self._per
        )

    def feasible(self, ni: "NodeInfo") -> tuple[bool, str]:
        labels = _node_labels(ni)
        for c, counts, lo in self._per:
            if c.when_unsatisfiable != "DoNotSchedule":
                continue
            v = labels.get(c.topology_key)
            if v is None:
                return False, (
                    f"node lacks topology key {c.topology_key!r} required "
                    "by a DoNotSchedule spread constraint"
                )
            if c.min_domains and len(counts) < c.min_domains:
                lo = 0  # upstream minDomains: under-populated domain set
            if counts.get(v, 0) + 1 - lo > c.max_skew:
                return False, (
                    f"placing here would exceed maxSkew={c.max_skew} over "
                    f"{c.topology_key!r}"
                )
        return True, ""

    def score(self, ni: "NodeInfo") -> int:
        """[0, 100] balance score, averaged over the soft (ScheduleAnyway)
        constraints only — upstream PodTopologySpread's scorer ignores
        DoNotSchedule constraints (those already filtered): 100 = the
        emptiest domain, 0 = the fullest."""
        total = 0
        n = 0
        for c, counts, lo in self._per:
            if c.when_unsatisfiable != "ScheduleAnyway":
                continue
            v = _node_labels(ni).get(c.topology_key)
            n += 1
            if v is None or not counts:
                continue
            hi = max(counts.values())
            if hi <= lo:
                total += 100
            else:
                total += 100 * (hi - counts.get(v, 0)) // (hi - lo)
        return total // n if n else 0
