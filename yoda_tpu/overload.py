"""Overload brownout ladder + config hot-reload (ISSUE 15).

Every failure domain so far assumed the *cluster* breaks while the
scheduler stays comfortable; this module covers the scheduler itself
under overload, and reconfiguration without a restart:

- :class:`OverloadMonitor` — a strict four-level brownout ladder
  (NOMINAL -> ELEVATED -> BROWNOUT -> SHED) driven by direct pressure
  gauges (queue depth, ingest batch backlog, serve-cycle p99 wall) plus
  the SLO engine's multi-window burn-rate alert. Degradation is ordered
  features-before-correctness:

  * ELEVATED pauses the rebalancer / node-health repair passes (their
    gates read :meth:`repairs_paused`) and drops lifecycle-trace
    sampling to 0 — observability and optimization yield first;
  * BROWNOUT additionally caps per-tenant admission through the DRF
    queue's quota path (:meth:`quota_verdict`, a token bucket per
    tenant on the monitor clock);
  * SHED additionally parks new non-prod-tier arrivals at pop time with
    an ``overload-shed`` why-pending verdict (:meth:`shed_verdict` via
    ``SchedulingQueue.shed_fn``). Bound gangs are never touched and no
    watch event is ever dropped; shed pods sit in the unresolvable pool
    and requeue the moment the ladder steps down.

  Step-up climbs ONE level per evaluation (strict order); step-down is
  debounced — pressure must stay below the current level's entry
  threshold for ``step_down_hold_s`` — so flapping load cannot thrash
  features. One monitor is shared across every serve loop that shares a
  metrics registry (profile stacks, shard lanes), exactly like the
  tracer and the SLO engine.

- :class:`ConfigReloader` + :class:`LiveConfig` — the hot-reload surface
  behind SIGHUP and the ConfigMap-watch (cli.py): a candidate config is
  diffed against the running one (``SchedulerConfig.diff``), each
  changed knob classified reloadable-live / resize / requires-drain /
  immutable; reloadable knobs apply atomically through
  ``standalone.apply_reloadable`` (each consumer re-reads its live
  attribute), ``shard_count`` goes through ``ShardSet.resize``, and
  everything else is reported with its old value kept — a reload can
  never half-apply.

Lock discipline: the verdict hooks (:meth:`shed_verdict`,
:meth:`quota_verdict`) run under the scheduling-queue lock — they touch
only the monitor's own state. Signal collection (:meth:`evaluate`)
runs on the monitor's background thread and takes component locks one
at a time, never while holding its own.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import replace as _dc_replace
from typing import Callable

from yoda_tpu.api.requests import LabelParseError, pod_request
from yoda_tpu.config import SchedulerConfig

log = logging.getLogger("yoda_tpu.overload")

#: The ladder, strict order. Indexes are the yoda_overload_level gauge.
LEVELS = ("NOMINAL", "ELEVATED", "BROWNOUT", "SHED")
NOMINAL, ELEVATED, BROWNOUT, SHED = range(4)

#: Pressure thresholds for entering each level (pressure is the max of
#: the normalized signals; 1.0 = a signal at its configured high-water
#: mark). Module constants, not knobs: the knobs scale the signals.
ENTER_AT = (0.0, 1.0, 2.0, 4.0)


def _priority_of(pod) -> int:
    try:
        return pod_request(pod).priority
    except LabelParseError:
        # Malformed labels park through the normal unresolvable path
        # anyway; under SHED they are non-prod by definition.
        return 0


class OverloadMonitor:
    """The brownout ladder. Built once per shared metrics registry
    (standalone._metrics_from_config) and wired by build_stack: queues
    and ingestors register as pressure sources, the tracer / latency
    histogram / SLO engine attach for the feature-pause and burn
    signals, and the repair-loop gates compose :meth:`repairs_paused`.
    """

    def __init__(
        self,
        *,
        queue_high: int = 10000,
        ingest_high: int = 50000,
        cycle_ms_high: float = 250.0,
        step_down_hold_s: float = 15.0,
        brownout_admit_per_s: float = 10.0,
        shed_priority_floor: int = 10,
        period_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.queue_high = int(queue_high)
        self.ingest_high = int(ingest_high)
        self.cycle_ms_high = float(cycle_ms_high)
        self.step_down_hold_s = float(step_down_hold_s)
        self.brownout_admit_per_s = float(brownout_admit_per_s)
        self.shed_priority_floor = int(shed_priority_floor)
        self.period_s = float(period_s)
        self.clock = clock
        # Current ladder position: a bare int read lock-free by the
        # verdict hooks (CPython attribute reads are atomic; a stale
        # read costs one extra pop-time verdict, never correctness).
        self.level_idx = NOMINAL
        self.transitions = 0
        self.shed_total = 0          # bumped by the queue's on_shed hook
        self.evaluations = 0
        self.last_pressure = 0.0
        self._below_since: float | None = None
        self._lock = threading.Lock()
        # Pressure sources / feature handles (build_stack wiring).
        self._queues: list = []
        self._ingestors: list = []
        self.tracer = None
        self.latency = None          # yoda_scheduling_latency histogram
        self.slo = None
        self._base_sample_rate: float | None = None
        # BROWNOUT token buckets: tenant -> [tokens, last_refill].
        self._buckets: dict[str, list] = {}

    # --- wiring -------------------------------------------------------

    def add_queue(self, queue) -> None:
        with self._lock:
            if queue not in self._queues:
                self._queues.append(queue)

    def remove_queue(self, queue) -> None:
        with self._lock:
            if queue in self._queues:
                self._queues.remove(queue)

    def add_ingestor(self, batcher) -> None:
        with self._lock:
            if batcher not in self._ingestors:
                self._ingestors.append(batcher)

    def remove_ingestor(self, batcher) -> None:
        with self._lock:
            if batcher in self._ingestors:
                self._ingestors.remove(batcher)

    def attach(self, *, tracer=None, latency=None, slo=None) -> None:
        if tracer is not None:
            self.tracer = tracer
        if latency is not None:
            self.latency = latency
        if slo is not None:
            self.slo = slo

    # --- the feature gates (read by other components) -----------------

    @property
    def level(self) -> str:
        return LEVELS[self.level_idx]

    def repairs_paused(self) -> bool:
        """True at ELEVATED and above: the rebalancer and node-health
        repair passes yield their cycles to the serve loops (their
        gate_fn composes this). Event-time signals (deletions, ghost
        releases) stay live — only the background passes pause."""
        return self.level_idx >= ELEVATED

    def shed_verdict(self, pod) -> "str | None":
        """SHED only: the why-pending message for a NON-prod-tier pod
        that must park instead of scheduling, else None. Called by the
        queue under its lock per popped entry — own-state reads only.
        Deterministic in (labels, level): every member of a
        (tier-homogeneous) gang gets the same answer, so gangs shed
        whole; the mid-Permit guard lives in the standalone wiring."""
        if self.level_idx < SHED:
            return None
        if _priority_of(pod) >= self.shed_priority_floor:
            return None
        return (
            "overload shed: scheduler at SHED "
            f"(pressure {self.last_pressure:.2f}); non-prod arrival "
            "parked until the ladder steps down"
        )

    def note_shed(self) -> None:
        """One draw shed (the queue's on_shed hook, under its lock)."""
        with self._lock:
            self.shed_total += 1

    def quota_verdict(self, tenant: str) -> "str | None":
        """BROWNOUT and above: per-tenant admission cap through the DRF
        quota path. A scheduling draw consumes one token from the
        tenant's bucket (refilled at ``brownout_admit_per_s`` on the
        monitor clock, burst = one second's worth); an empty bucket
        parks the draw with a quota verdict until it refills or the
        ladder steps down. Called under the queue lock — dict math on
        the monitor's own state only."""
        if self.level_idx < BROWNOUT:
            return None
        now = self.clock()
        rate = self.brownout_admit_per_s
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = [rate, now]
            tokens = min(b[0] + (now - b[1]) * rate, rate)
            b[1] = now
            if tokens >= 1.0:
                b[0] = tokens - 1.0
                return None
            b[0] = tokens
        return (
            f"overload brownout: tenant {tenant or '(default)'} admission "
            f"capped at {rate:g}/s until pressure subsides"
        )

    # --- signals ------------------------------------------------------

    def pressure(self) -> "dict[str, float]":
        """The normalized pressure signals (1.0 = at the high-water
        mark) and their max. Takes component locks one at a time; never
        called with the monitor lock held."""
        with self._lock:
            queues = list(self._queues)
            ingestors = list(self._ingestors)
        signals: dict[str, float] = {}
        if self.queue_high > 0 and queues:
            depth = 0
            for q in queues:
                fn = getattr(q, "overload_depth", None)
                depth += fn() if fn is not None else len(q)
            signals["queue"] = depth / self.queue_high
        if self.ingest_high > 0 and ingestors:
            backlog = sum(b.backlog() for b in ingestors)
            signals["ingest"] = backlog / self.ingest_high
        if self.cycle_ms_high > 0 and self.latency is not None:
            p99_ms = self.latency.quantile(0.99, phase="total") * 1e3
            signals["cycle"] = p99_ms / self.cycle_ms_high
        if self.slo is not None and getattr(self.slo, "enabled", False):
            try:
                fast, slow = self.slo.burn_snapshot()
                threshold = getattr(self.slo, "burn_threshold", 0.0)
                if threshold > 0 and fast >= threshold and slow >= threshold:
                    # A firing burn alert is BROWNOUT-grade pressure on
                    # its own: the error budget is being spent now.
                    signals["burn"] = ENTER_AT[BROWNOUT]
            except Exception:  # noqa: BLE001 — a sick engine must not wedge the ladder
                pass
        signals["max"] = max(
            (v for k, v in signals.items() if k != "max"), default=0.0
        )
        return signals

    # --- the ladder ---------------------------------------------------

    def evaluate(self, now: "float | None" = None) -> str:
        """One ladder tick: read the signals, step up at most one level
        (strict order), step down one level only after
        ``step_down_hold_s`` of sustained calm. Returns the level."""
        now = self.clock() if now is None else now
        p = self.pressure()["max"]
        self.last_pressure = p
        self.evaluations += 1
        step_down_to = None
        with self._lock:
            idx = self.level_idx
            target = NOMINAL
            for lvl in (ELEVATED, BROWNOUT, SHED):
                if p >= ENTER_AT[lvl]:
                    target = lvl
            if target > idx:
                self._transition_locked(idx + 1)
                self._below_since = None
            elif idx > NOMINAL and p < ENTER_AT[idx]:
                if self._below_since is None:
                    self._below_since = now
                elif now - self._below_since >= self.step_down_hold_s:
                    self._transition_locked(idx - 1)
                    step_down_to = self.level_idx
                    # The hold restarts per step: dropping from SHED to
                    # NOMINAL takes three sustained-calm windows.
                    self._below_since = now
            else:
                self._below_since = None
            queues = list(self._queues)
        if step_down_to is not None:
            # Shed/brownout-parked entries re-enter the active queue NOW
            # (not at the next cluster event): the ladder stepping down
            # IS the capacity event they were waiting for.
            for q in queues:
                try:
                    q.move_all_to_active()
                except Exception:  # noqa: BLE001 — one sick queue must not wedge the rest
                    log.exception("overload step-down reactivation failed")
        return self.level

    def _transition_locked(self, new_idx: int) -> None:
        old = self.level_idx
        self.level_idx = new_idx
        self.transitions += 1
        if old < ELEVATED <= new_idx and self.tracer is not None:
            # Feature pause, step 1: tracing yields first. The base rate
            # is restored (or a reloaded value applied) on the way down.
            self._base_sample_rate = self.tracer.sample_rate
            self.tracer.sample_rate = 0.0
        elif new_idx < ELEVATED <= old and self.tracer is not None:
            if self._base_sample_rate is not None:
                self.tracer.sample_rate = self._base_sample_rate
                self._base_sample_rate = None
        if new_idx < BROWNOUT <= old:
            self._buckets.clear()
        log.warning(
            "overload ladder: %s -> %s (pressure %.2f)",
            LEVELS[old], LEVELS[new_idx], self.last_pressure,
        )

    def set_base_sample_rate(self, rate: float) -> None:
        """Hot-reload entry for ``trace_sample_rate``: applied to the
        tracer now, or remembered for the step-down restore while the
        ladder has sampling paused."""
        with self._lock:
            if self.level_idx >= ELEVATED and self.tracer is not None:
                self._base_sample_rate = rate
            elif self.tracer is not None:
                self.tracer.sample_rate = rate

    def run_forever(self, stop: threading.Event) -> None:
        """The background evaluation loop (cli thread). ``period_s`` is
        re-read per tick — it is a reloadable knob."""
        while not stop.is_set():
            period = self.period_s
            if period <= 0:
                if stop.wait(1.0):
                    return
                continue
            if stop.wait(period):
                return
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 — the ladder must survive its own bugs
                log.exception("overload evaluation failed; will retry")


# --- config hot-reload ------------------------------------------------------


class LiveConfig:
    """The swap-atomic holder for the running SchedulerConfig: readers
    take ``current`` (one attribute read — CPython reference reads are
    atomic), the reloader swaps it under the lock and bumps
    ``generation``."""

    def __init__(self, config: SchedulerConfig) -> None:
        self._lock = threading.Lock()
        self._config = config
        self.generation = 0

    @property
    def current(self) -> SchedulerConfig:
        return self._config

    def replace(self, config: SchedulerConfig) -> None:
        with self._lock:
            self._config = config
            self.generation += 1


class ConfigReloader:
    """SIGHUP / ConfigMap-watch reload driver (cli.py owns the triggers).

    ``load_fn`` produces the candidate SchedulerConfig (raises on a bad
    file — the running config is kept and the error reported, never a
    half-parsed apply). ``apply_fn`` is ``standalone.apply_reloadable``
    bound to the live stacks; ``resize_fn`` (sharded mode only) is
    ``ShardSet.resize``. Each reload returns a report dict naming what
    was applied, what needs a drain, what was refused as immutable."""

    def __init__(
        self,
        load_fn: Callable[[], SchedulerConfig],
        live: LiveConfig,
        apply_fn: Callable[[SchedulerConfig], None],
        *,
        resize_fn: "Callable[[int], dict] | None" = None,
    ) -> None:
        self.load_fn = load_fn
        self.live = live
        self.apply_fn = apply_fn
        self.resize_fn = resize_fn
        self._lock = threading.Lock()
        self.reloads = 0

    def reload(self) -> dict:
        with self._lock:
            try:
                candidate = self.load_fn()
            except Exception as e:  # noqa: BLE001 — keep serving on the old config
                log.error("config reload failed to load: %s", e)
                return {"error": str(e), "applied": [], "requires_drain": [],
                        "immutable": [], "resized": None}
            current = self.live.current
            diff = current.diff(candidate)
            applied = sorted(k for k, c in diff.items() if c == "reloadable")
            drain = sorted(
                k for k, c in diff.items() if c == "requires-drain"
            )
            immutable = sorted(k for k, c in diff.items() if c == "immutable")
            resized = None
            effective = current
            if applied:
                effective = _dc_replace(
                    effective,
                    **{k: getattr(candidate, k) for k in applied},
                )
            if diff.get("shard_count") == "resize":
                if self.resize_fn is not None:
                    try:
                        resized = self.resize_fn(candidate.shard_count)
                        effective = _dc_replace(
                            effective, shard_count=candidate.shard_count
                        )
                    except Exception as e:  # noqa: BLE001 — a failed resize keeps the old topology
                        log.exception("live shard resize failed")
                        return {
                            "error": f"resize failed: {e}",
                            "applied": [], "requires_drain": drain,
                            "immutable": immutable, "resized": None,
                        }
                else:
                    drain = sorted({*drain, "shard_count"})
            self.live.replace(effective)
            if applied:
                # Atomic in the operator-visible sense: every consumer
                # reads its knob live, and the apply runs before the
                # report returns — no window serves a mix of files.
                self.apply_fn(effective)
            self.reloads += 1
            report = {
                "applied": applied,
                "requires_drain": drain,
                "immutable": immutable,
                "resized": resized,
                "error": None,
            }
            if applied or drain or immutable or resized:
                log.info(
                    "config reload: applied=%s requires-drain=%s "
                    "immutable(kept)=%s resized=%s",
                    applied, drain, immutable,
                    (resized or {}).get("shards") if resized else None,
                )
            return report
