"""Scheduler configuration knobs.

The reference hard-coded its scoring weights as compile-time consts
(reference pkg/yoda/score/algorithm.go:17-27) and decoded-but-ignored its
plugin args (scheduler.go:38-41,55-58). Here the weights and operational
knobs are real configuration (SURVEY.md §5 config row), loadable from the
scheduler config YAML (deploy/) and validated.
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields

from yoda_tpu.slo.engine import SloTargets

# --- hot-reload classification (ISSUE 15) ----------------------------------
#
# Every SchedulerConfig knob belongs to exactly one reload class; the
# classes drive `SchedulerConfig.diff()` and the SIGHUP/ConfigMap-watch
# hot-reload surface (yoda_tpu/overload.ConfigReloader):
#
# - RELOADABLE_KNOBS apply to a RUNNING scheduler atomically via
#   `standalone.apply_reloadable` — each one is re-read by its consumer at
#   use time (a live attribute), never captured into a serve-path local at
#   build time. The yodalint `reload-safety` pass enforces both directions:
#   every knob here must be re-applied in apply_reloadable, and no module
#   outside the assembly/reload layer may read it off a config object.
# - RESIZE_KNOBS change live through a dedicated topology path
#   (`shard_count` -> ShardSet.resize: quiesce commits, rebuild the
#   rendezvous map, reroute the moved ~1/N, resume).
# - IMMUTABLE_KNOBS define the process identity (mode, kernel backend,
#   profile set); a reload that changes one is refused with the old value
#   kept.
# - Everything else is REQUIRES-DRAIN: correct only through a restart via
#   the PR 5 failover path (reported by the reloader, never half-applied).

RELOADABLE_KNOBS = frozenset(
    {
        "trace_sample_rate",
        "slo_enabled",
        "slo_burn_threshold",
        "immediate_retry_attempts",
        "bind_retry_attempts",
        "bind_retry_base_s",
        "bind_retry_cap_s",
        "rebalance_min_gain",
        "rebalance_max_moves",
        "rebalance_max_victims",
        "rebalance_preemption",
        "rebalance_elastic",
        "spec_enabled",
        "spec_cache_size",
        "spec_shapes_max",
        "node_repair",
        "node_drain_deadline_s",
        "overload_period_s",
        "overload_queue_high",
        "overload_ingest_high",
        "overload_cycle_ms_high",
        "overload_step_down_hold_s",
        "overload_brownout_admit_per_s",
        "overload_shed_priority",
        "pending_index_max",
        "journal_sync",
        "journal_segment_bytes",
    }
)
RESIZE_KNOBS = frozenset({"shard_count"})
IMMUTABLE_KNOBS = frozenset(
    {
        "mode",
        "scheduler_name",
        "weights",
        "scoring_strategy",
        "kernel_platform",
        "kernel_device_min_elems",
        "kernel_backend",
        "mesh_devices",
        "profiles",
        # The journal directory identifies ONE durable log; repointing a
        # live process would split the record across two logs (neither
        # replayable alone) — restart to move it.
        "journal_path",
        # The commit transport is the control plane's spine: workers and
        # the tailing standby hold persistent connections to it, and the
        # epoch-term fence assumes one endpoint per parent generation —
        # restart to rewire it.
        "commit_listen",
        "commit_endpoint",
    }
)


def classify_knob(name: str) -> str:
    """The reload class of one knob: ``reloadable`` | ``resize`` |
    ``immutable`` | ``requires-drain``."""
    if name in RELOADABLE_KNOBS:
        return "reloadable"
    if name in RESIZE_KNOBS:
        return "resize"
    if name in IMMUTABLE_KNOBS:
        return "immutable"
    return "requires-drain"


@dataclass(frozen=True)
class Weights:
    """Score weights — reference consts parity (algorithm.go:17-27):
    Bandwidth/Clock/Core/Power 1, FreeMemory 2, TotalMemory 1, Actual 2,
    Allocate 2, with Core->tflops and the memory terms renamed to HBM."""

    hbm_bandwidth: int = 1
    clock: int = 1
    tflops: int = 1
    power: int = 1
    hbm_free: int = 2
    hbm_total: int = 1
    actual: int = 2
    allocate: int = 2
    # Anti-fragmentation (net-new, no reference analog): pods with no
    # tpu/topology requirement prefer hosts OUTSIDE multi-host ICI slices,
    # keeping slices whole for topology gangs. Tiered above the metric terms
    # (bonus = SLICE_PROTECT_BONUS x weight); 0 disables.
    slice_protect: int = 1
    # Soft steering: the pod's preferredDuringScheduling node-affinity
    # satisfaction ([0,100], api.types.preferred_affinity_score) x this
    # weight, added alongside the normalized metric score; 0 disables.
    preferred_affinity: int = 1
    # Soft avoidance: each PreferNoSchedule taint the pod does not
    # tolerate subtracts 100 x this weight (upstream TaintToleration's
    # scoring half, simplified: per-taint penalty, no fleet-wide
    # normalization); 0 disables.
    taint_prefer: int = 1
    # Inter-pod soft steering: the signed preferred pod-(anti-)affinity
    # weight sum (api.affinity.InterPodEvaluator.preference) x this weight;
    # 0 disables (upstream InterPodAffinity's scoring half).
    pod_affinity: int = 1
    # Topology-spread balance: the [0,100] ScheduleAnyway balance score
    # (api.affinity.SpreadEvaluator.score) x this weight; 0 disables
    # (upstream PodTopologySpread's scoring half).
    topology_spread: int = 1
    # Upstream ImageLocality: [0,100] size-and-spread-scaled presence of
    # the pod's container images on the node (needs Node.status.images
    # from the Node watch) x this weight; 0 disables. Deliberately small
    # by default — for TPU jobs image pulls are dwarfed by checkpoint
    # restore (plugins/yoda/image_locality.py).
    image_locality: int = 1

    @classmethod
    def from_dict(cls, d: dict) -> "Weights":
        known = {f.name for f in fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown weight keys: {sorted(unknown)}")
        bad = {k: v for k, v in d.items() if not isinstance(v, int) or v < 0}
        if bad:
            raise ValueError(f"weights must be non-negative ints: {bad}")
        return cls(**d)


# Added AFTER the metric score is min-max normalized to [0,100] (so metric
# resolution is not crushed by the tier): one tier step is 1000 > 100, and
# slice protection strictly dominates chip quality for non-topology pods.
SLICE_PROTECT_TIER = 1000


@dataclass(frozen=True)
class SchedulerConfig:
    """Top-level plugin configuration (the reference's pluginConfig Args
    analog, made real)."""

    mode: str = "batch"               # "batch" (fused kernel) | "loop"
    # The spec.schedulerName this profile serves (upstream profiles: one
    # binary, several schedulerNames with different plugin configs).
    scheduler_name: str = "yoda-tpu"
    weights: Weights = field(default_factory=Weights)
    # Upstream NodeResourcesFit scoringStrategy analog:
    # "least-allocated" (default) prefers the freest qualifying node —
    # spreads load, upstream's default; "most-allocated" inverts the
    # free-leaning score terms (hbm_free / actual / allocate) to prefer the
    # fullest node that still fits — bin-packing for saturation fleets
    # (BASELINE config 3). Hardware-quality terms and the slice-protect
    # tier keep their sign either way.
    scoring_strategy: str = "least-allocated"
    gang_permit_timeout_s: float = 120.0
    max_metrics_age_s: float = 0.0    # 0 disables staleness filtering
    # Cap per-node score-plugin work to this % of feasible nodes (upstream
    # percentageOfNodesToScore; rotating window, min 8 nodes). Applies to
    # mode="loop" only — the fused kernel scores the fleet in one dispatch.
    percentage_nodes_to_score: int = 100
    enable_preemption: bool = True    # modern-PostFilter eviction (BASELINE config 5)
    # Where the fused kernel runs: "auto" pins small fleets to host CPU
    # (accelerator dispatch latency dominates sub-device_min_elems work) and
    # large fleets to the default accelerator; "cpu"/"device" force a side.
    # None defers the threshold to plugins.yoda.batch.AUTO_DEVICE_MIN_ELEMS.
    kernel_platform: str = "auto"
    kernel_device_min_elems: int | None = None
    # Fused-kernel implementation: "xla" (jnp, XLA-fused — default, runs
    # anywhere) or "pallas" (hand-written Mosaic TPU kernel,
    # ops/pallas_kernel.py — for locally-attached TPUs; interpret mode
    # elsewhere). Bit-identical outputs either way (tests/test_pallas.py).
    kernel_backend: str = "xla"
    # Shard the fused kernel's fleet row axis over an N-device
    # jax.sharding.Mesh (parallel.ShardedDeviceFleetKernel): the global
    # reductions become XLA-inserted ICI collectives. None = single-device
    # kernel under the kernel_platform policy; when set, mesh devices come
    # from jax.devices() and kernel_platform is ignored.
    mesh_devices: int | None = None
    # Multi-pod fused dispatch: pop up to this many pending pods per loop
    # turn and evaluate them in ONE kernel call (YodaBatch.prepare_burst),
    # amortizing the fleet scan and the dispatch floor across pods. 1 =
    # one dispatch per pod (the pre-r4 behavior). Batch mode only.
    batch_requests: int = 1
    # Transient-error bind retry (failure-domain hardening): a bind that
    # fails with a retryable error (409 conflict, 429 throttle, 5xx,
    # socket timeout — cluster.retry classification) is retried up to
    # this many times with full-jitter exponential backoff (base
    # doubling, capped) before it becomes a scheduling failure and, for
    # gang members, a transactional rollback. 0 disables retry.
    bind_retry_attempts: int = 3
    bind_retry_base_s: float = 0.05
    bind_retry_cap_s: float = 1.0
    # Bind pipeline: size of the bounded executor that fans a gang's
    # member binds out in parallel and carries their retry/backoff sleeps
    # off the scheduling thread, letting the serve loop overlap the next
    # cycle's snapshot + kernel dispatch with the in-flight bind I/O.
    # 0 disables the executor entirely (every bind runs inline in its
    # scheduling cycle — the pre-pipeline shape). Size to the API server's
    # comfortable concurrent-write budget; 8 covers a 64-member gang in
    # 8 waves.
    bind_workers: int = 8
    # Gates the ASYNC fan-out: "auto" (default) pipelines only when binds
    # are real I/O — a remote API server (KubeCluster.remote_binds) or a
    # backend with injected bind latency; in-process microsecond binds
    # stay synchronous (the thread handoff would cost more than it
    # hides). "on" forces the pipeline, "off" forbids it.
    bind_pipeline: str = "auto"
    # Crash-safe failover (framework/reconciler.py): how a promoted
    # scheduler's warm-start resync treats a PARTIALLY-BOUND gang left by
    # the dead leader. > 0: the gang is ADOPTED — its bound members stay,
    # their claims are charged, and the remaining members get this many
    # seconds to complete the gang before the drift reconciler rolls the
    # whole thing back via the unbind path. 0: never adopt — every
    # partial gang is rolled back whole at resync (the conservative
    # policy: strictly no state inherited from the dead leader).
    failover_adopt_window_s: float = 60.0
    # Period of the background drift reconciler (leaked reservations,
    # ghost bindings the watch stream dropped, permit waits whose pod was
    # deleted). Each round diffs local accounting against cluster truth;
    # on a real API server it re-LISTs pods, so keep it tens of seconds.
    # 0 disables the background loop (the warm-start resync still runs).
    reconcile_period_s: float = 30.0
    # Federated multi-cluster scheduling (yoda_tpu/federation): the
    # per-cluster health ladder's silence thresholds. A cluster front
    # whose probes AND watch stream have been silent for degraded_after_s
    # stops receiving new spillover; past partitioned_after_s it is
    # fenced from binding entirely (and its warm-start gate closes, so a
    # rejoin resyncs through the reconciler before the first new bind);
    # past lost_after_s readiness stops waiting for it. Must satisfy
    # 0 < degraded <= partitioned <= lost.
    federation_degraded_after_s: float = 10.0
    federation_partitioned_after_s: float = 30.0
    federation_lost_after_s: float = 120.0
    # Period of the federation control loop (health probes, rejoin
    # resyncs, spillover migration) — one background thread, never the
    # serve loops. Probes are one cheap LIST per cluster per pass.
    federation_probe_period_s: float = 1.0
    # Spillover routing: migrate a gang the home cluster cannot fit whole
    # to the first healthy secondary whose snapshot fits it (all-or-
    # nothing, exactly one cluster). False = clusters federate for
    # health/failover only; every gang stays on its home cluster.
    federation_spillover: bool = True
    # Goodput-driven rebalancer (yoda_tpu/rebalance): period of the
    # background pass that repacks bound topology gangs onto tighter ICI
    # blocks, preempts strictly-lower-priority work to admit a parked
    # whole gang, and resizes elastic gangs (tpu/min-members /
    # tpu/max-members). One pass per stack, leadership-gated, never on a
    # serve loop. 0 disables the background loop (Stack.rebalancer can
    # still be driven manually via run_once()).
    rebalance_period_s: float = 30.0
    # Minimum fragmentation-score improvement (rebalance/score.py, [0,1])
    # a repack move must buy before a bound gang is migrated — moves are
    # not free (unbind + rebind I/O), so tiny gains are not worth churn.
    rebalance_min_gain: float = 0.05
    # At most this many gang moves per pass (migration cost is hidden
    # behind the bind pipeline, but each move still re-places a whole
    # gang — bound per pass keeps the blast radius one gang at a time).
    rebalance_max_moves: int = 1
    # Enable the priority-preemption pass (victims are UNBOUND and
    # requeued through the standard rollback path — never deleted — so
    # preempted work re-places when capacity returns).
    rebalance_preemption: bool = True
    # Enable elastic gang resize (grow toward tpu/max-members into free
    # capacity; shrink toward tpu/min-members as the cheapest preemption
    # unit).
    rebalance_elastic: bool = True
    # Victim budget per admitted gang: the preemption pass gives up
    # rather than evict more than this many pods for one parked gang.
    rebalance_max_victims: int = 8
    # Speculative placement cache (framework/speculation.py,
    # docs/OPERATIONS.md "Sub-millisecond serve" runbook): between serve
    # cycles the rebalancer thread's idle capacity pre-validates one
    # placement per recently-seen single-pod shape; a hot-shape arrival
    # binds from the cached plan after the epoch + staged-claim
    # revalidation, skipping the O(fleet) filter/score spans. All three
    # knobs hot-reload; spec_enabled=False flushes every cached plan
    # atomically (the operator kill switch).
    spec_enabled: bool = True
    # Bound on cached plans (one per shape; shapes beyond the bound serve
    # at the fused-dispatch baseline).
    spec_cache_size: int = 256
    # Bound on tracked miss shapes the speculator re-plans per tick.
    spec_shapes_max: int = 64
    # Durable claim journal (yoda_tpu/journal, docs/OPERATIONS.md
    # "Durability and warm-start" runbook): directory for the append-only
    # commit log of every claim mutation. "" (the default) = journal OFF
    # — the in-memory accountant is the commit log, today's behavior,
    # zero new hot-path work. Set (typically a PVC mount) the commit
    # point write-ahead-journals staged-claim/commit/rollback/release
    # records and a promoted standby warm-starts by REPLAY instead of
    # the full-LIST cold resync.
    journal_path: str = ""
    # fsync policy per append: "always" (every record durable before the
    # claim applies — strongest, slowest), "batch" (fsync on commit and
    # snapshot records plus every ~64 appends — the default; at most a
    # batch of uncommitted stage records can be lost, which replay +
    # divergence resync repair), "off" (OS page cache only — fastest,
    # survives process crash but not host crash).
    journal_sync: str = "batch"
    # Segment rotation threshold: when the active segment exceeds this
    # many bytes the journal rotates to a fresh segment headed by a full
    # snapshot record and deletes older segments (compaction) — steady-
    # state disk use stays ~flat at snapshot + one segment of deltas.
    journal_segment_bytes: int = 4 * 1024 * 1024
    # Node failure domains (yoda_tpu/nodehealth): the per-node health
    # ladder's silence thresholds. A node whose agent has been silent
    # past node_suspect_after_s is SUSPECT — fenced from NEW placements
    # (the debounce window: a publish returns it to HEALTHY, so a
    # flapping heartbeat never triggers repair); continuous silence past
    # node_down_after_s (or a TPU CR / Node deletion, or Node NotReady)
    # is DOWN — every gang with a member on the node is repaired WHOLE
    # (patch repair preferred: only the lost members re-plan, healthy
    # members keep their bindings; fallback whole unbind-and-requeue —
    # never a split gang, never a deleted pod). Must satisfy
    # 0 < suspect <= down.
    node_suspect_after_s: float = 15.0
    node_down_after_s: float = 60.0
    # Enable automatic DOWN repair (False = the monitor only classifies
    # and fences; repair is the operator's job).
    node_repair: bool = True
    # Graceful drain (NodeHealthMonitor.drain, rolling cluster
    # upgrades): how long the rebalancer gets to migrate bound gangs off
    # a draining node before the monitor force-evacuates the remainder.
    node_drain_deadline_s: float = 300.0
    # Period of the background node-health pass (ladder tick + repair),
    # leadership-gated like the rebalancer. 0 disables the loop
    # (Stack.nodehealth can still be driven via run_once()); event-time
    # signals (deletions, NotReady, ghost releases) stay live either way.
    node_health_period_s: float = 5.0
    # Lifecycle tracing (yoda_tpu/tracing.py): fraction of pod/gang
    # lifetimes traced end-to-end (enqueue -> gather -> dispatch ->
    # reserve -> permit-park -> bind -> bound, plus rebalancer moves,
    # spillover, and resync repairs). Sampling is deterministic per
    # subject (a gang's members always land on the same side). 0 turns
    # tracing off entirely — call sites pay one attribute read.
    trace_sample_rate: float = 1.0
    # Bounded span-ring size; overflow evicts oldest and counts into
    # yoda_trace_dropped_total. Sized for ~minutes of burst traffic.
    trace_capacity: int = 4096
    # Optional JSONL sink: every span is also appended to this file (one
    # JSON object per line) for offline analysis. "" disables. A sink
    # that becomes unwritable is dropped silently; the ring keeps working.
    trace_sink: str = ""
    # JSONL sink rotation: when the sink file grows past this many bytes
    # it is rotated to "<trace_sink>.1" (two generations kept: current +
    # .1, the previous .1 overwritten), so a week-long soak cannot fill
    # the disk. 0 = never rotate (the pre-rotation behavior).
    trace_sink_max_bytes: int = 0
    # Fleet SLO engine (yoda_tpu/slo, docs/OPERATIONS.md "SLO monitoring"
    # runbook): per-tenant sliding-window SLIs (admission-wait quantiles,
    # starvation windows, preemption/repair rates, goodput) computed from
    # events the scheduler already emits, judged against the declarative
    # slo_targets with multi-window burn-rate alerting, served at
    # /debug/slo + `yoda-tpu-scheduler slo` + the yoda_slo_* series.
    # False turns the record paths off entirely (one attribute read per
    # call site — the same near-zero-when-off contract as tracing).
    slo_enabled: bool = True
    # Declarative targets (keys of slo.SloTargets; unset keys keep their
    # defaults, 0 disables a target).
    slo_targets: "SloTargets" = field(default_factory=lambda: SloTargets())
    # A starved window: one tenant holding queued work with ZERO
    # admissions for this long. The bench matrix asserts zero of these.
    slo_starvation_window_s: float = 60.0
    # Multi-window burn-rate alerting: the admission SLI's error budget
    # is burned over BOTH windows; an alert needs both past the
    # threshold (fast-only = noise, slow-only = old news).
    slo_burn_fast_window_s: float = 300.0
    slo_burn_slow_window_s: float = 3600.0
    slo_burn_threshold: float = 2.0
    # Cluster events retry a parked pod immediately through this many
    # scheduling attempts; beyond it the pod's exponential backoff timer
    # holds regardless of event rate (upstream moveAllToActiveOrBackoffQueue
    # semantics — bounds retry storms over chronically unschedulable pods).
    # 0 = strict upstream behavior (every event move respects backoff).
    immediate_retry_attempts: int = 5
    # Batched watch-event ingestion (cluster/ingest.py, ISSUE 10): when
    # ingest_batch_window_ms > 0, watch events are drained into bounded
    # batches — coalesced by (kind, uid): last-write-wins for modifies,
    # delete supersedes — and each batch is applied under ONE informer
    # lock acquisition with one metrics-epoch bump and one parked-pod
    # reactivation decision. 0 (default) keeps per-event delivery:
    # every event applies synchronously, exactly the pre-batching
    # behavior. The window bounds event-to-queue latency; size it well
    # under the scheduling cadence (a few ms).
    ingest_batch_max: int = 256
    ingest_batch_window_ms: float = 0.0
    # Per-tenant DRF fair queuing (framework/tenancy.py): a tenant is
    # the pod's namespace, overridable via the tpu/tenant label. When
    # on, the scheduling queue pops from the LOWEST dominant-resource-
    # share (chips/HBM) tenant first, so a flooding tenant cannot starve
    # the others. Off (default) = the classic single tenant-blind queue.
    tenant_fairness: bool = False
    # Per-tenant quota admission (requires tenant_fairness): admitting a
    # pod whose tenant's BOUND usage would exceed these caps parks it
    # with a why-pending verdict until capacity frees. 0 = unlimited.
    tenant_quota_chips: int = 0
    tenant_quota_hbm_gib: float = 0.0
    # Overload brownout ladder (yoda_tpu/overload.py, docs/OPERATIONS.md
    # "Overload brownout + hot-reload" runbook): the scheduler's own
    # self-protection under flash-crowd floods. Pressure = the max of the
    # normalized signals below (plus the SLO engine's burn-rate alert);
    # the ladder climbs NOMINAL -> ELEVATED (pause the rebalancer /
    # node-health repair passes, drop trace sampling to 0) -> BROWNOUT
    # (cap per-tenant admission through the DRF quota path) -> SHED (park
    # new non-prod-tier arrivals with an `overload-shed` why-pending
    # verdict; they requeue when the ladder steps down). Step-up is one
    # level per evaluation; step-down is debounced by
    # overload_step_down_hold_s of sustained calm, so flapping load
    # cannot thrash features. overload_period_s drives the background
    # evaluation loop (0 disables it; the monitor can still be driven
    # manually). Signal thresholds: 0 disables that signal.
    overload_period_s: float = 1.0
    overload_queue_high: int = 10000       # queued (non-shed) entries
    overload_ingest_high: int = 50000      # buffered ingest events
    overload_cycle_ms_high: float = 250.0  # serve-cycle p99 wall ms
    overload_step_down_hold_s: float = 15.0
    # BROWNOUT admission cap: scheduling draws admitted per tenant per
    # second (token bucket on the monitor clock); over-cap draws park
    # with a quota verdict until the bucket refills or the ladder drops.
    overload_brownout_admit_per_s: float = 10.0
    # Pods whose tpu/priority is at least this are PROD-TIER: never shed
    # (and effectively exempt from brownout caps sized above their rate).
    overload_shed_priority: int = 10
    # Why-pending index bound (tracing.PendingIndex): LRU over keys, so a
    # million-pod shed flood cannot grow why-pending state without limit;
    # evictions count into yoda_pending_evicted_total.
    pending_index_max: int = 2048
    # Scheduler shard-out (framework/shards.py, docs/OPERATIONS.md
    # sharding runbook): partition the node fleet by ICI slice/pool
    # across this many INDEPENDENT serve loops (rendezvous-hashed
    # slice->shard assignment; each shard runs its own queue, resident
    # fleet state, and bind executor), sharing one ChipAccountant
    # through an optimistic claim->validate->commit protocol. Gangs no
    # single shard can host fall back to a serialized global lane.
    # 1 (default) = today's single serve loop, the staging/commit
    # machinery entirely off. Incompatible with `profiles` (each shard
    # serves the base profile) and with federated mode.
    shard_count: int = 1
    # How the shard serve loops are hosted (ISSUE 19, OPERATIONS.md
    # "Multi-process shard serve"): "thread" (default) runs all lanes
    # in one interpreter — the PR-14 shape, byte-identical behavior;
    # "process" runs each shard lane as its own OS process (GIL-free
    # bind pipelines) reaching the parent's journal-owning accountant
    # through the local commit RPC (framework/procserve.py). Ignored
    # when shard_count == 1. Requires-drain: changing the process
    # topology of a live scheduler means a restart.
    shard_mode: str = "thread"
    # Multi-host control plane (ISSUE 20, OPERATIONS.md "Multi-host
    # control plane runbook"). commit_listen: the parent's commit RPC
    # listen endpoint — "" (default) serves the AF_UNIX socket of PR 19
    # (single host, byte-identical behavior); "host:port" serves the
    # length-prefixed TCP transport so shard workers and the tailing
    # standby can live on other hosts. Every response is stamped with
    # the parent's epoch term; fencing is bidirectional (see the
    # runbook). Immutable: the transport is the control plane's spine —
    # restart to change it.
    commit_listen: str = ""
    # Where THIS process reaches the live parent's commit RPC:
    # "host:port" (TCP) or an AF_UNIX socket path. A leader-elected
    # standby uses it to TAIL the live parent's journal into a warm
    # mirror (journal/tail.py), so promotion is an O(1) handover + term
    # bump instead of a cold replay. "" = no tailing (cold promotion);
    # parent-spawned workers are handed the parent's own endpoint
    # regardless of this knob. Immutable for the same reason as
    # commit_listen.
    commit_endpoint: str = ""
    # Additional profiles (upstream KubeSchedulerConfiguration profiles):
    # each entry inherits every unspecified key from the base config and
    # serves its own scheduler_name. E.g. a spread-strategy "yoda-tpu"
    # base plus a bin-packing "yoda-tpu-batch" profile in one process.
    profiles: tuple = ()              # tuple[SchedulerConfig, ...]

    @classmethod
    def from_dict(cls, d: dict) -> "SchedulerConfig":
        d = dict(d)
        w = d.pop("weights", None)
        slo_t = d.pop("slo_targets", None)
        if slo_t is not None:
            # Instance passthrough: profile resolution re-runs from_dict
            # over merged base keys that may already be parsed.
            if isinstance(slo_t, SloTargets):
                d["slo_targets"] = slo_t
            elif isinstance(slo_t, dict):
                d["slo_targets"] = SloTargets.from_dict(slo_t)
            else:
                raise ValueError(
                    f"slo_targets must be a mapping, got {slo_t!r}"
                )
        profile_dicts = d.pop("profiles", None) or ()
        if profile_dicts:
            base = dict(d)
            base_w = dict(w or {})
            resolved = []
            for pd in profile_dicts:
                pd = dict(pd)
                if "scheduler_name" not in pd:
                    raise ValueError(
                        "each profile must set scheduler_name"
                    )
                merged = {**base, **pd}
                merged["weights"] = {**base_w, **(pd.get("weights") or {})}
                merged.pop("profiles", None)
                # A pallas profile is incompatible with kernel_platform
                # and mesh_devices; INHERITED values must not fail its
                # validation (the operator never set them on this profile)
                # — only explicit ones do.
                if merged.get("kernel_backend") == "pallas":
                    for knob in ("kernel_platform", "mesh_devices"):
                        if knob not in pd:
                            merged.pop(knob, None)
                resolved.append(cls.from_dict(merged))
            d["profiles"] = tuple(resolved)
            names = [d.get("scheduler_name", cls.scheduler_name)] + [
                p.scheduler_name for p in resolved
            ]
            if len(set(names)) != len(names):
                raise ValueError(
                    f"profiles must have distinct scheduler_names: {names}"
                )
        cfg = cls(**d, weights=Weights.from_dict(w) if w else Weights())
        if cfg.mode not in ("batch", "loop"):
            raise ValueError(f"mode must be 'batch' or 'loop', got {cfg.mode!r}")
        if not isinstance(cfg.scheduler_name, str) or not cfg.scheduler_name:
            raise ValueError(
                f"scheduler_name must be a non-empty string, got "
                f"{cfg.scheduler_name!r}"
            )
        if cfg.gang_permit_timeout_s <= 0:
            raise ValueError("gang_permit_timeout_s must be positive")
        if not isinstance(
            cfg.max_metrics_age_s, (int, float)
        ) or isinstance(
            cfg.max_metrics_age_s, bool
        ) or cfg.max_metrics_age_s < 0:
            raise ValueError(
                "max_metrics_age_s must be >= 0 (0 disables staleness "
                f"filtering), got {cfg.max_metrics_age_s!r}"
            )
        if not isinstance(cfg.enable_preemption, bool):
            raise ValueError(
                f"enable_preemption must be a bool, got "
                f"{cfg.enable_preemption!r}"
            )
        if cfg.kernel_device_min_elems is not None and (
            isinstance(cfg.kernel_device_min_elems, bool)
            or not isinstance(cfg.kernel_device_min_elems, int)
            or cfg.kernel_device_min_elems < 1
        ):
            raise ValueError(
                "kernel_device_min_elems must be a positive int or None "
                "(None defers to the batch plugin's threshold), got "
                f"{cfg.kernel_device_min_elems!r}"
            )
        if (
            isinstance(cfg.bind_retry_attempts, bool)
            or not isinstance(cfg.bind_retry_attempts, int)
            or not 0 <= cfg.bind_retry_attempts <= 100
        ):
            raise ValueError(
                "bind_retry_attempts must be an int in [0, 100] (0 "
                f"disables retry), got {cfg.bind_retry_attempts!r}"
            )
        retry_waits = (cfg.bind_retry_base_s, cfg.bind_retry_cap_s)
        if any(
            isinstance(v, bool) or not isinstance(v, (int, float)) or v <= 0
            for v in retry_waits
        ) or not retry_waits[0] <= retry_waits[1]:
            raise ValueError(
                "bind retry backoff must satisfy 0 < bind_retry_base_s "
                f"<= bind_retry_cap_s, got {retry_waits}"
            )
        if not isinstance(cfg.federation_spillover, bool):
            raise ValueError(
                f"federation_spillover must be a bool, got "
                f"{cfg.federation_spillover!r}"
            )
        if not isinstance(cfg.rebalance_preemption, bool):
            raise ValueError(
                f"rebalance_preemption must be a bool, got "
                f"{cfg.rebalance_preemption!r}"
            )
        if not isinstance(cfg.rebalance_elastic, bool):
            raise ValueError(
                f"rebalance_elastic must be a bool, got "
                f"{cfg.rebalance_elastic!r}"
            )
        if (
            isinstance(cfg.percentage_nodes_to_score, bool)
            or not isinstance(cfg.percentage_nodes_to_score, int)
            or not 1 <= cfg.percentage_nodes_to_score <= 100
        ):
            raise ValueError(
                "percentage_nodes_to_score must be an int in [1, 100], got "
                f"{cfg.percentage_nodes_to_score!r}"
            )
        if cfg.scoring_strategy not in ("least-allocated", "most-allocated"):
            raise ValueError(
                "scoring_strategy must be 'least-allocated' or "
                f"'most-allocated', got {cfg.scoring_strategy!r}"
            )
        if cfg.kernel_platform not in ("auto", "cpu", "device"):
            raise ValueError(
                "kernel_platform must be 'auto', 'cpu' or 'device', "
                f"got {cfg.kernel_platform!r}"
            )
        if cfg.kernel_backend not in ("xla", "pallas"):
            raise ValueError(
                f"kernel_backend must be 'xla' or 'pallas', got "
                f"{cfg.kernel_backend!r}"
            )
        if cfg.kernel_backend == "pallas" and cfg.mesh_devices is not None:
            raise ValueError(
                "kernel_backend='pallas' does not support mesh_devices "
                "(the mesh-sharded path is XLA-collective based)"
            )
        if cfg.kernel_backend == "pallas" and cfg.kernel_platform != "auto":
            raise ValueError(
                "kernel_backend='pallas' ignores kernel_platform; leave it "
                "'auto' (the Mosaic kernel runs on the default device)"
            )
        if (
            isinstance(cfg.batch_requests, bool)
            or not isinstance(cfg.batch_requests, int)
            or not 1 <= cfg.batch_requests <= 128
        ):
            raise ValueError(
                "batch_requests must be an int in [1, 128], got "
                f"{cfg.batch_requests!r}"
            )
        if cfg.batch_requests > 1 and cfg.mode != "batch":
            raise ValueError(
                "batch_requests > 1 requires mode='batch' (the fused kernel "
                "is what a burst amortizes)"
            )
        if (
            isinstance(cfg.bind_workers, bool)
            or not isinstance(cfg.bind_workers, int)
            or not 0 <= cfg.bind_workers <= 128
        ):
            raise ValueError(
                f"bind_workers must be an int in [0, 128], got "
                f"{cfg.bind_workers!r}"
            )
        if cfg.bind_pipeline not in ("auto", "on", "off"):
            raise ValueError(
                "bind_pipeline must be 'auto', 'on' or 'off', got "
                f"{cfg.bind_pipeline!r}"
            )
        if cfg.bind_pipeline == "on" and cfg.bind_workers == 0:
            raise ValueError(
                "bind_pipeline='on' requires bind_workers >= 1 (the "
                "pipeline IS the executor)"
            )
        if not isinstance(
            cfg.failover_adopt_window_s, (int, float)
        ) or isinstance(
            cfg.failover_adopt_window_s, bool
        ) or cfg.failover_adopt_window_s < 0:
            raise ValueError(
                "failover_adopt_window_s must be >= 0 (0 = never adopt), "
                f"got {cfg.failover_adopt_window_s!r}"
            )
        if not isinstance(
            cfg.reconcile_period_s, (int, float)
        ) or isinstance(
            cfg.reconcile_period_s, bool
        ) or cfg.reconcile_period_s < 0:
            raise ValueError(
                "reconcile_period_s must be >= 0 (0 disables the "
                f"background reconciler), got {cfg.reconcile_period_s!r}"
            )
        if not isinstance(
            cfg.rebalance_period_s, (int, float)
        ) or isinstance(
            cfg.rebalance_period_s, bool
        ) or cfg.rebalance_period_s < 0:
            raise ValueError(
                "rebalance_period_s must be >= 0 (0 disables the "
                f"background rebalancer), got {cfg.rebalance_period_s!r}"
            )
        if not isinstance(
            cfg.rebalance_min_gain, (int, float)
        ) or isinstance(
            cfg.rebalance_min_gain, bool
        ) or not 0 <= cfg.rebalance_min_gain <= 1:
            raise ValueError(
                "rebalance_min_gain must be in [0, 1], got "
                f"{cfg.rebalance_min_gain!r}"
            )
        if (
            isinstance(cfg.rebalance_max_moves, bool)
            or not isinstance(cfg.rebalance_max_moves, int)
            or cfg.rebalance_max_moves < 0
        ):
            raise ValueError(
                "rebalance_max_moves must be an int >= 0, got "
                f"{cfg.rebalance_max_moves!r}"
            )
        if (
            isinstance(cfg.rebalance_max_victims, bool)
            or not isinstance(cfg.rebalance_max_victims, int)
            or cfg.rebalance_max_victims < 1
        ):
            raise ValueError(
                "rebalance_max_victims must be an int >= 1, got "
                f"{cfg.rebalance_max_victims!r}"
            )
        if not isinstance(cfg.spec_enabled, bool):
            raise ValueError(
                f"spec_enabled must be a bool, got {cfg.spec_enabled!r}"
            )
        if (
            isinstance(cfg.spec_cache_size, bool)
            or not isinstance(cfg.spec_cache_size, int)
            or cfg.spec_cache_size < 1
        ):
            raise ValueError(
                "spec_cache_size must be an int >= 1, got "
                f"{cfg.spec_cache_size!r}"
            )
        if (
            isinstance(cfg.spec_shapes_max, bool)
            or not isinstance(cfg.spec_shapes_max, int)
            or cfg.spec_shapes_max < 1
        ):
            raise ValueError(
                "spec_shapes_max must be an int >= 1, got "
                f"{cfg.spec_shapes_max!r}"
            )
        if not isinstance(cfg.journal_path, str):
            raise ValueError(
                f"journal_path must be a directory path string ('' "
                f"disables the journal), got {cfg.journal_path!r}"
            )
        if cfg.journal_sync not in ("always", "batch", "off"):
            raise ValueError(
                "journal_sync must be 'always', 'batch', or 'off', got "
                f"{cfg.journal_sync!r}"
            )
        if (
            isinstance(cfg.journal_segment_bytes, bool)
            or not isinstance(cfg.journal_segment_bytes, int)
            or cfg.journal_segment_bytes < 4096
        ):
            raise ValueError(
                "journal_segment_bytes must be an int >= 4096, got "
                f"{cfg.journal_segment_bytes!r}"
            )
        node_thresholds = (cfg.node_suspect_after_s, cfg.node_down_after_s)
        if any(
            isinstance(t, bool) or not isinstance(t, (int, float))
            for t in node_thresholds
        ) or not 0 < node_thresholds[0] <= node_thresholds[1]:
            raise ValueError(
                "node health thresholds must satisfy 0 < "
                "node_suspect_after_s <= node_down_after_s, got "
                f"{node_thresholds}"
            )
        if not isinstance(cfg.node_repair, bool):
            raise ValueError(
                f"node_repair must be a bool, got {cfg.node_repair!r}"
            )
        if not isinstance(
            cfg.node_drain_deadline_s, (int, float)
        ) or isinstance(
            cfg.node_drain_deadline_s, bool
        ) or cfg.node_drain_deadline_s < 0:
            raise ValueError(
                "node_drain_deadline_s must be >= 0, got "
                f"{cfg.node_drain_deadline_s!r}"
            )
        if not isinstance(
            cfg.node_health_period_s, (int, float)
        ) or isinstance(
            cfg.node_health_period_s, bool
        ) or cfg.node_health_period_s < 0:
            raise ValueError(
                "node_health_period_s must be >= 0 (0 disables the "
                f"background loop), got {cfg.node_health_period_s!r}"
            )
        thresholds = (
            cfg.federation_degraded_after_s,
            cfg.federation_partitioned_after_s,
            cfg.federation_lost_after_s,
        )
        if any(
            isinstance(t, bool) or not isinstance(t, (int, float))
            for t in thresholds
        ) or not (0 < thresholds[0] <= thresholds[1] <= thresholds[2]):
            raise ValueError(
                "federation health thresholds must satisfy 0 < "
                "degraded_after_s <= partitioned_after_s <= lost_after_s, "
                f"got {thresholds}"
            )
        if not isinstance(
            cfg.federation_probe_period_s, (int, float)
        ) or isinstance(
            cfg.federation_probe_period_s, bool
        ) or cfg.federation_probe_period_s <= 0:
            raise ValueError(
                "federation_probe_period_s must be > 0, got "
                f"{cfg.federation_probe_period_s!r}"
            )
        if not isinstance(
            cfg.trace_sample_rate, (int, float)
        ) or isinstance(
            cfg.trace_sample_rate, bool
        ) or not 0 <= cfg.trace_sample_rate <= 1:
            raise ValueError(
                "trace_sample_rate must be in [0, 1] (0 = tracing off), "
                f"got {cfg.trace_sample_rate!r}"
            )
        if (
            isinstance(cfg.trace_capacity, bool)
            or not isinstance(cfg.trace_capacity, int)
            or cfg.trace_capacity < 16
        ):
            raise ValueError(
                f"trace_capacity must be an int >= 16, got "
                f"{cfg.trace_capacity!r}"
            )
        if not isinstance(cfg.trace_sink, str):
            raise ValueError(
                f"trace_sink must be a path string ('' disables), got "
                f"{cfg.trace_sink!r}"
            )
        if (
            isinstance(cfg.trace_sink_max_bytes, bool)
            or not isinstance(cfg.trace_sink_max_bytes, int)
            or cfg.trace_sink_max_bytes < 0
        ):
            raise ValueError(
                "trace_sink_max_bytes must be an int >= 0 (0 = never "
                f"rotate), got {cfg.trace_sink_max_bytes!r}"
            )
        if not isinstance(cfg.slo_enabled, bool):
            raise ValueError(
                f"slo_enabled must be a bool, got {cfg.slo_enabled!r}"
            )
        if not isinstance(cfg.slo_targets, SloTargets):
            raise ValueError(
                f"slo_targets must resolve to SloTargets, got "
                f"{cfg.slo_targets!r}"
            )
        slo_windows = (
            cfg.slo_starvation_window_s,
            cfg.slo_burn_fast_window_s,
            cfg.slo_burn_slow_window_s,
        )
        if any(
            isinstance(v, bool) or not isinstance(v, (int, float)) or v <= 0
            for v in slo_windows
        ) or not slo_windows[1] <= slo_windows[2]:
            raise ValueError(
                "SLO windows must satisfy slo_starvation_window_s > 0 and "
                "0 < slo_burn_fast_window_s <= slo_burn_slow_window_s, got "
                f"{slo_windows}"
            )
        if not isinstance(
            cfg.slo_burn_threshold, (int, float)
        ) or isinstance(
            cfg.slo_burn_threshold, bool
        ) or cfg.slo_burn_threshold <= 0:
            raise ValueError(
                "slo_burn_threshold must be > 0, got "
                f"{cfg.slo_burn_threshold!r}"
            )
        if (
            isinstance(cfg.immediate_retry_attempts, bool)
            or not isinstance(cfg.immediate_retry_attempts, int)
            or not 0 <= cfg.immediate_retry_attempts <= 1000
        ):
            raise ValueError(
                "immediate_retry_attempts must be an int in [0, 1000], got "
                f"{cfg.immediate_retry_attempts!r}"
            )
        if (
            isinstance(cfg.ingest_batch_max, bool)
            or not isinstance(cfg.ingest_batch_max, int)
            or not 1 <= cfg.ingest_batch_max <= 65536
        ):
            raise ValueError(
                "ingest_batch_max must be an int in [1, 65536], got "
                f"{cfg.ingest_batch_max!r}"
            )
        if not isinstance(
            cfg.ingest_batch_window_ms, (int, float)
        ) or isinstance(
            cfg.ingest_batch_window_ms, bool
        ) or not 0 <= cfg.ingest_batch_window_ms <= 10_000:
            raise ValueError(
                "ingest_batch_window_ms must be in [0, 10000] (0 = "
                "per-event delivery, batching off), got "
                f"{cfg.ingest_batch_window_ms!r}"
            )
        if not isinstance(cfg.tenant_fairness, bool):
            raise ValueError(
                f"tenant_fairness must be a bool, got "
                f"{cfg.tenant_fairness!r}"
            )
        if (
            isinstance(cfg.tenant_quota_chips, bool)
            or not isinstance(cfg.tenant_quota_chips, int)
            or cfg.tenant_quota_chips < 0
        ):
            raise ValueError(
                "tenant_quota_chips must be an int >= 0 (0 = unlimited), "
                f"got {cfg.tenant_quota_chips!r}"
            )
        if not isinstance(
            cfg.tenant_quota_hbm_gib, (int, float)
        ) or isinstance(
            cfg.tenant_quota_hbm_gib, bool
        ) or cfg.tenant_quota_hbm_gib < 0:
            raise ValueError(
                "tenant_quota_hbm_gib must be >= 0 (0 = unlimited), got "
                f"{cfg.tenant_quota_hbm_gib!r}"
            )
        if (
            cfg.tenant_quota_chips or cfg.tenant_quota_hbm_gib
        ) and not cfg.tenant_fairness:
            raise ValueError(
                "tenant_quota_* requires tenant_fairness: true (quotas "
                "are enforced by the tenant-aware queue)"
            )
        if (
            isinstance(cfg.shard_count, bool)
            or not isinstance(cfg.shard_count, int)
            or not 1 <= cfg.shard_count <= 64
        ):
            raise ValueError(
                "shard_count must be an int in [1, 64] (1 = single serve "
                f"loop, sharding off), got {cfg.shard_count!r}"
            )
        if cfg.shard_count > 1 and cfg.profiles:
            raise ValueError(
                "shard_count > 1 is incompatible with profiles (every "
                "shard serves the base profile; run profiles unsharded)"
            )
        if cfg.shard_mode not in ("thread", "process"):
            raise ValueError(
                "shard_mode must be 'thread' or 'process', got "
                f"{cfg.shard_mode!r}"
            )
        for knob in ("commit_listen", "commit_endpoint"):
            v = getattr(cfg, knob)
            if not isinstance(v, str):
                raise ValueError(
                    f"{knob} must be a string endpoint ('host:port' or "
                    f"a socket path), got {v!r}"
                )
        if cfg.commit_listen:
            host, sep, port = cfg.commit_listen.rpartition(":")
            if not sep or not host or not port.isdigit():
                raise ValueError(
                    "commit_listen must be 'host:port' (the TCP commit "
                    f"transport listen endpoint), got {cfg.commit_listen!r}"
                )
        if cfg.mesh_devices is not None and (
            isinstance(cfg.mesh_devices, bool)
            or not isinstance(cfg.mesh_devices, int)
            or cfg.mesh_devices < 1
        ):
            raise ValueError(
                f"mesh_devices must be a positive int, got {cfg.mesh_devices!r}"
            )
        for knob in (
            "overload_period_s",
            "overload_cycle_ms_high",
            "overload_step_down_hold_s",
        ):
            v = getattr(cfg, knob)
            if isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0:
                raise ValueError(
                    f"{knob} must be >= 0 (0 disables it), got {v!r}"
                )
        for knob in ("overload_queue_high", "overload_ingest_high"):
            v = getattr(cfg, knob)
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                raise ValueError(
                    f"{knob} must be an int >= 0 (0 disables the signal), "
                    f"got {v!r}"
                )
        if isinstance(
            cfg.overload_brownout_admit_per_s, bool
        ) or not isinstance(
            cfg.overload_brownout_admit_per_s, (int, float)
        ) or cfg.overload_brownout_admit_per_s <= 0:
            raise ValueError(
                "overload_brownout_admit_per_s must be > 0, got "
                f"{cfg.overload_brownout_admit_per_s!r}"
            )
        if isinstance(cfg.overload_shed_priority, bool) or not isinstance(
            cfg.overload_shed_priority, int
        ):
            raise ValueError(
                "overload_shed_priority must be an int (pods at or above "
                f"it are never shed), got {cfg.overload_shed_priority!r}"
            )
        if (
            isinstance(cfg.pending_index_max, bool)
            or not isinstance(cfg.pending_index_max, int)
            or cfg.pending_index_max < 16
        ):
            raise ValueError(
                f"pending_index_max must be an int >= 16, got "
                f"{cfg.pending_index_max!r}"
            )
        return cfg

    def diff(self, new: "SchedulerConfig") -> "dict[str, str]":
        """Changed knobs between this config and ``new``, each mapped to
        its reload class (:func:`classify_knob`) — the hot-reload
        surface's decision table: ``reloadable`` knobs apply live via
        ``standalone.apply_reloadable``, ``resize`` goes through
        ``ShardSet.resize``, ``requires-drain`` / ``immutable`` are
        reported and kept at their old values."""
        out: dict[str, str] = {}
        for f in fields(self):
            if getattr(self, f.name) != getattr(new, f.name):
                out[f.name] = classify_knob(f.name)
        return out

    def effective_weights(self) -> Weights:
        """The weights the score path actually runs with: under
        "most-allocated" the free-leaning terms are negated (a fuller node
        scores higher), while hardware-quality terms (bandwidth, clock,
        tflops, power, total HBM) and the slice-protect tier keep their
        sign. User-facing weights stay non-negative (Weights.from_dict);
        the sign is strategy-owned."""
        if self.scoring_strategy != "most-allocated":
            return self.weights
        from dataclasses import replace

        w = self.weights
        return replace(
            w, hbm_free=-w.hbm_free, actual=-w.actual, allocate=-w.allocate
        )
