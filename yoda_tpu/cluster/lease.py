"""Lease-based leader election for scheduler HA.

The reference deploys with ``leaderElect: true`` and gets the whole
mechanism from the upstream kube-scheduler it wraps (reference
deploy/yoda-scheduler.yaml:11-14 via pkg/register/register.go:10); this is
the from-scratch equivalent on the modern ``coordination.k8s.io/v1`` Lease
API (the resourceVersion-checked update IS the mutual exclusion — the API
server rejects concurrent writes with 409, so at most one candidate's
acquire/renew round-trip wins per lease interval).

Semantics follow upstream leaderelection.LeaderElector:

- A candidate acquires the lease when it is absent, expired
  (``renewTime + leaseDurationSeconds < now``), or already its own.
- The holder renews every ``renew_period_s``; on failure it keeps acting as
  leader until ``renew_deadline_s`` since the last successful renew
  (transient API blips do not flap leadership), then reports loss. The
  deadline is strictly inside the lease duration, so the old leader always
  stands down BEFORE a standby may acquire (upstream renewDeadline
  semantics — no split-brain window).
- Observing ANOTHER holder's valid lease while leading reports loss
  immediately (the lock moved: split-brain window closed).
- ``release()`` clears the holder on orderly shutdown so a standby takes
  over without waiting out the lease (upstream ReleaseOnCancel).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from datetime import datetime, timezone
from typing import Callable

log = logging.getLogger("yoda_tpu.lease")

LEASE_API_BASE = "/apis/coordination.k8s.io/v1"


def lease_path(namespace: str, name: str = "") -> str:
    base = f"{LEASE_API_BASE}/namespaces/{namespace}/leases"
    return f"{base}/{name}" if name else base


def _fmt_micro(ts: float) -> str:
    return (
        datetime.fromtimestamp(ts, tz=timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f")
        + "Z"
    )


def _parse_micro(s: str | None) -> float | None:
    if not s:
        return None
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            return datetime.strptime(s, fmt).replace(tzinfo=timezone.utc).timestamp()
        except ValueError:
            continue
    return None


@dataclass
class LeaseView:
    holder: str
    renew_unix: float | None
    duration_s: float
    transitions: int
    resource_version: str
    acquire_time: str | None = None  # raw spec.acquireTime, carried on renew


class LeaderElector:
    """Drives acquire/renew against the Lease API. ``run`` blocks; callers
    put it on a thread and react to the callbacks (cli._run_scheduler)."""

    def __init__(
        self,
        api,  # KubeApiClient
        *,
        identity: str,
        namespace: str = "kube-system",
        name: str = "yoda-tpu-scheduler",
        lease_duration_s: float = 15.0,
        renew_deadline_s: float | None = None,
        renew_period_s: float = 2.0,
        clock: Callable[[], float] = time.time,
    ) -> None:
        if not identity:
            raise ValueError("leader election requires a non-empty identity")
        # Upstream leaderelection margins: the holder ABANDONS leadership
        # once it has failed to renew for renew_deadline_s — strictly less
        # than lease_duration_s, the point where standbys may acquire — so
        # even with a detection granularity of renew_period_s the old leader
        # stops scheduling before a new one can start (no split-brain
        # window). Default: 2/3 of the lease duration, like upstream's
        # 10s/15s.
        if renew_deadline_s is None:
            renew_deadline_s = lease_duration_s * 2.0 / 3.0
        if not (renew_period_s < renew_deadline_s < lease_duration_s):
            raise ValueError(
                f"need renew_period ({renew_period_s}) < renew_deadline "
                f"({renew_deadline_s}) < lease_duration ({lease_duration_s})"
            )
        if lease_duration_s - renew_deadline_s <= renew_period_s:
            raise ValueError(
                "lease_duration - renew_deadline must exceed renew_period "
                "(the loss-detection tick granularity), or a standby could "
                "acquire before the old leader notices it must stop"
            )
        self.api = api
        self.identity = identity
        self.namespace = namespace
        self.name = name
        self.lease_duration_s = lease_duration_s
        self.renew_deadline_s = renew_deadline_s
        self.renew_period_s = renew_period_s
        self.clock = clock
        self._leading = threading.Event()
        self._last_renew = 0.0

    # --- introspection ---

    def is_leader(self) -> bool:
        return self._leading.is_set()

    def observe(self) -> LeaseView | None:
        """Current lease state, None when absent (tests, metrics)."""
        from yoda_tpu.cluster.kube import KubeApiError

        try:
            obj = self.api.request("GET", lease_path(self.namespace, self.name))
        except KubeApiError as e:
            if e.status == 404:
                return None
            raise
        spec = obj.get("spec", {})
        return LeaseView(
            holder=spec.get("holderIdentity") or "",
            renew_unix=_parse_micro(spec.get("renewTime")),
            duration_s=float(spec.get("leaseDurationSeconds") or 0),
            transitions=int(spec.get("leaseTransitions") or 0),
            resource_version=obj.get("metadata", {}).get("resourceVersion", ""),
            acquire_time=spec.get("acquireTime"),
        )

    # --- acquire / renew ---

    def _lease_body(
        self,
        *,
        acquire: bool,
        transitions: int,
        rv: str,
        acquire_time: str | None = None,
    ) -> dict:
        now = _fmt_micro(self.clock())
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration_s),
                "renewTime": now,
                "leaseTransitions": transitions,
            },
        }
        if acquire:
            body["spec"]["acquireTime"] = now
        elif acquire_time:
            # PUT replaces the whole spec on a real API server: carry the
            # acquireTime recorded at acquisition through every renewal.
            body["spec"]["acquireTime"] = acquire_time
        if rv:
            body["metadata"]["resourceVersion"] = rv
        return body

    def try_acquire_or_renew(self) -> bool:
        """One round: True when this identity holds the lease afterwards.
        Raises nothing — API errors count as a failed round (the run loop's
        expiry deadline decides when that costs leadership)."""
        from yoda_tpu.cluster.kube import KubeApiError

        try:
            view = self.observe()
            if view is None:
                self.api.request(
                    "POST",
                    lease_path(self.namespace),
                    body=self._lease_body(acquire=True, transitions=0, rv=""),
                )
                self._last_renew = self.clock()
                return True
            if view.holder == self.identity:
                body = self._lease_body(
                    acquire=False,
                    transitions=view.transitions,
                    rv=view.resource_version,
                    acquire_time=view.acquire_time,
                )
                self.api.request(
                    "PUT", lease_path(self.namespace, self.name), body=body
                )
                self._last_renew = self.clock()
                return True
            released = not view.holder  # orderly release(): free immediately
            expired = (
                view.renew_unix is None
                or view.renew_unix + view.duration_s <= self.clock()
            )
            if not released and not expired:
                return False
            body = self._lease_body(
                acquire=True,
                transitions=view.transitions + 1,
                rv=view.resource_version,
            )
            self.api.request("PUT", lease_path(self.namespace, self.name), body=body)
            self._last_renew = self.clock()
            return True
        except (KubeApiError, OSError):
            # 409 = lost the write race; others = API blip. Either way this
            # round did not secure the lease.
            return False

    def release(self) -> None:
        """Clear the holder so a standby can take over immediately."""
        from yoda_tpu.cluster.kube import KubeApiError

        try:
            view = self.observe()
            if view is None or view.holder != self.identity:
                return
            body = self._lease_body(
                acquire=False,
                transitions=view.transitions,
                rv=view.resource_version,
                acquire_time=view.acquire_time,
            )
            body["spec"]["holderIdentity"] = ""
            self.api.request("PUT", lease_path(self.namespace, self.name), body=body)
        except (KubeApiError, OSError):
            pass  # best-effort; the lease expires on its own
        finally:
            self._leading.clear()

    # --- the loop ---

    def run(
        self,
        stop: threading.Event,
        *,
        on_started_leading: Callable[[], None] | None = None,
        on_stopped_leading: Callable[[], None] | None = None,
    ) -> None:
        """Blocks until ``stop``. Fires ``on_started_leading`` when acquired
        and ``on_stopped_leading`` when leadership is lost (expired without
        renewal, or another holder observed). Releases on orderly exit."""
        try:
            while not stop.is_set():
                got = self.try_acquire_or_renew()
                if got and not self._leading.is_set():
                    log.info("acquired lease %s/%s as %s",
                             self.namespace, self.name, self.identity)
                    self._leading.set()
                    if on_started_leading:
                        on_started_leading()
                elif not got and self._leading.is_set():
                    view = None
                    try:
                        view = self.observe()
                    except Exception:
                        pass
                    taken_over = view is not None and view.holder not in (
                        "",
                        self.identity,
                    )
                    deadline_passed = (
                        self.clock() - self._last_renew >= self.renew_deadline_s
                    )
                    if taken_over or deadline_passed:
                        log.warning(
                            "lost leadership of %s/%s (%s)",
                            self.namespace, self.name,
                            "taken over by " + view.holder if taken_over
                            else "renew deadline passed",
                        )
                        self._leading.clear()
                        if on_stopped_leading:
                            on_stopped_leading()
                stop.wait(self.renew_period_s)
        finally:
            if self._leading.is_set():
                self.release()
