"""Kubernetes Event emission: the `kubectl describe pod` trail.

The reference's clusters get scheduling Events for free from the upstream
kube-scheduler it wraps (reference pkg/register/register.go:10 — the
framework's EventRecorder emits Scheduled / FailedScheduling); this repo's
from-scratch loop must emit its own. The recorder follows the upstream
aggregation discipline: one Event object per (involved pod UID, reason),
POSTed on first occurrence and updated with an incremented ``count`` and
refreshed ``lastTimestamp`` on repeats — so a pod retried 50 times shows
one FailedScheduling row with count=50, not 50 objects.

Reasons emitted (upstream-parity names):

- ``Scheduled`` (Normal) — pod bound to a node,
- ``FailedScheduling`` (Warning) — no feasible node this attempt,
- ``Preempted`` (Warning) — on the victim, when preemption evicts it.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable

from yoda_tpu.api.types import PodSpec

log = logging.getLogger("yoda_tpu.events")

# Bounded memory: beyond this many distinct (uid, reason) keys the oldest
# aggregation entry is dropped (its next event just POSTs a fresh object).
_MAX_TRACKED = 4096

# Bounded backlog of unsent events; overflow sheds the OLDEST (best-effort,
# like upstream's broadcaster) — in a mass-failure storm the newest events
# describe the storm's current phase and must survive (VERDICT r2).
_MAX_PENDING = 1024


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


class EventRecorder:
    """Builds and aggregates k8s Event objects, handing them to ``sink``.

    ``sink(obj, update)`` persists the Event: ``update=False`` means create
    (POST), ``update=True`` means rewrite the same named object (PUT) — the
    count-aggregation path. Both cluster backends implement this as
    ``write_event``.

    The sink runs on a dedicated worker thread (upstream keeps event
    emission off the scheduling path via an async broadcaster for the same
    reason: with a real API server every write is a blocking HTTP round
    trip, and the scheduling loop must not pay it). Enqueueing happens
    inside the aggregation lock, so sink calls for one (pod, reason) are
    strictly ordered — a later count can never be overwritten by an earlier
    one. Sink failures are logged and swallowed: events are best-effort
    observability, never scheduling-path errors. Call :meth:`flush` to wait
    for the backlog (tests, shutdown).
    """

    def __init__(
        self,
        sink: Callable[[dict, bool], None],
        *,
        component: str = "yoda-tpu-scheduler",
        clock: Callable[[], float] = time.time,
        on_drop: Callable[[], None] | None = None,
        max_tracked: int = _MAX_TRACKED,
        max_pending: int = _MAX_PENDING,
    ) -> None:
        self.sink = sink
        self.component = component
        self.clock = clock
        self.on_drop = on_drop
        self.max_tracked = max_tracked
        self.dropped_total = 0  # backlog sheds; mirrored to on_drop per event
        self._lock = threading.Lock()
        self._closing = False
        # (uid, reason) -> (event name, count, firstTimestamp); LRU-ordered:
        # every _emit reinserts its key, so capacity eviction removes the
        # least-recently-AGGREGATING entry, not the oldest-created.
        self._seen: dict[tuple[str, str], tuple[str, int, float]] = {}
        self._pending: queue.Queue = queue.Queue(maxsize=max_pending)
        self._worker = threading.Thread(
            target=self._drain, daemon=True, name="yoda-events"
        )
        self._worker.start()

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until all enqueued events reached the sink (True) or the
        timeout passed (False)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._pending.unfinished_tasks == 0:
                return True
            time.sleep(0.005)
        return False

    def close(self, timeout_s: float = 5.0) -> None:
        """Flush the backlog and stop the worker thread. Events emitted
        after close() are aggregated but never sent. Bounded: if the
        backlog is wedged (sink blocked on an unreachable API server) the
        sentinel is skipped and the daemon worker dies with the process —
        close() must never hold a SIGTERM handler past its timeout."""
        self.flush(timeout_s)
        # Under the lock: _emit's shed-oldest loop also runs under it, so a
        # concurrent emit can never dequeue this stop sentinel.
        with self._lock:
            self._closing = True
            try:
                self._pending.put_nowait(None)
            except queue.Full:
                return
        self._worker.join(timeout=timeout_s)

    def _drain(self) -> None:
        while True:
            item = self._pending.get()
            if item is None:
                self._pending.task_done()
                return
            obj, update = item
            try:
                self.sink(obj, update)
            except Exception:  # noqa: BLE001 — best-effort, see docstring
                log.warning(
                    "failed to write event %s/%s",
                    obj["metadata"].get("namespace"),
                    obj["metadata"].get("name"),
                    exc_info=True,
                )
            finally:
                self._pending.task_done()

    # --- the public reasons ---

    def scheduled(self, pod: PodSpec, node_name: str) -> None:
        self._emit(
            pod,
            "Normal",
            "Scheduled",
            f"Successfully assigned {pod.key} to {node_name}",
        )

    def failed_scheduling(self, pod: PodSpec, message: str) -> None:
        self._emit(pod, "Warning", "FailedScheduling", message)

    def preempted(self, victim: PodSpec, node: str) -> None:
        self._emit(
            victim,
            "Warning",
            "Preempted",
            f"Preempted by {self.component} on node {node} to make room for "
            "a higher-priority TPU workload",
        )

    def gang_rollback(self, member: PodSpec, gang: str, why: str) -> None:
        """The gang-level reason a member bounced (VERDICT r2 #6): each
        member's `kubectl describe pod` shows WHY the whole gang rolled
        back (the triggering member/host), not just its own
        FailedScheduling row."""
        self._emit(member, "Warning", "GangRollback", f"gang {gang}: {why}")

    # --- watch: prune aggregation state for deleted pods ---

    def handle(self, event) -> None:
        """Cluster watch hook (standalone wires it): a deleted pod's
        (uid, reason) entries can never aggregate again — drop them so idle
        entries for dead pods cannot crowd a live long-pending pod out of
        the LRU (ADVICE r2)."""
        if getattr(event, "kind", None) != "Pod" or event.type != "deleted":
            return
        uid = event.obj.uid
        with self._lock:
            for key in [k for k in self._seen if k[0] == uid]:
                del self._seen[key]

    # --- mechanics ---

    def _emit(self, pod: PodSpec, etype: str, reason: str, message: str) -> None:
        now = self.clock()
        key = (pod.uid, reason)
        with self._lock:
            # pop + reinsert: a repeat refreshes the key's LRU position, so
            # an actively-aggregating pod is never evicted by idle entries.
            prior = self._seen.pop(key, None)
            if prior is None:
                # Unique, deterministic-enough name: upstream uses
                # <pod>.<hex timestamp>; collisions just surface as a 409
                # the sink's create-then-update handles.
                name = f"{pod.name}.{format(int(now * 1e6), 'x')}"
                entry = (name, 1, now)
            else:
                entry = (prior[0], prior[1] + 1, prior[2])
            if len(self._seen) >= self.max_tracked:
                self._seen.pop(next(iter(self._seen)))
            self._seen[key] = entry
            name, count, first = entry
            obj = self._build(pod, etype, reason, message, name, count, first, now)
            # Inside the lock: enqueue order == aggregation order, so the
            # worker can never persist counts out of order. On overflow,
            # shed the OLDEST pending event — the newest describe the
            # current phase of whatever storm is causing the backlog.
            while not self._closing:
                try:
                    self._pending.put_nowait((obj, count > 1))
                    break
                except queue.Full:
                    try:
                        shed, _ = self._pending.get_nowait()
                        self._pending.task_done()
                    except queue.Empty:
                        # Worker drained everything between our put and get:
                        # the next put attempt will succeed.
                        continue
                    self.dropped_total += 1
                    if self.on_drop is not None:
                        try:
                            self.on_drop()
                        except Exception:  # noqa: BLE001 — metrics best-effort
                            pass
                    log.warning(
                        "event backlog full; shed oldest %s/%s",
                        shed["metadata"].get("namespace"),
                        shed["metadata"].get("name"),
                    )

    def _build(
        self,
        pod: PodSpec,
        etype: str,
        reason: str,
        message: str,
        name: str,
        count: int,
        first: float,
        now: float,
    ) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": pod.namespace},
            "involvedObject": {
                "apiVersion": "v1",
                "kind": "Pod",
                "namespace": pod.namespace,
                "name": pod.name,
                "uid": pod.uid,
            },
            "reason": reason,
            "message": message,
            "type": etype,
            "source": {"component": self.component},
            "firstTimestamp": _iso(first),
            "lastTimestamp": _iso(now),
            "count": count,
        }
