"""Kubernetes Event emission: the `kubectl describe pod` trail.

The reference's clusters get scheduling Events for free from the upstream
kube-scheduler it wraps (reference pkg/register/register.go:10 — the
framework's EventRecorder emits Scheduled / FailedScheduling); this repo's
from-scratch loop must emit its own. The recorder follows the upstream
aggregation discipline: one Event object per (involved pod UID, reason),
POSTed on first occurrence and updated with an incremented ``count`` and
refreshed ``lastTimestamp`` on repeats — so a pod retried 50 times shows
one FailedScheduling row with count=50, not 50 objects.

Reasons emitted (upstream-parity names):

- ``Scheduled`` (Normal) — pod bound to a node,
- ``FailedScheduling`` (Warning) — no feasible node this attempt,
- ``Preempted`` (Warning) — on the victim, when preemption evicts it.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable

from yoda_tpu.api.types import PodSpec

log = logging.getLogger("yoda_tpu.events")

# Bounded memory: beyond this many distinct (uid, reason) keys the oldest
# aggregation entry is dropped (its next event just POSTs a fresh object).
_MAX_TRACKED = 4096

# Bounded backlog of unsent events; overflow drops the newest (best-effort,
# like upstream's broadcaster, which also sheds under pressure).
_MAX_PENDING = 1024


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


class EventRecorder:
    """Builds and aggregates k8s Event objects, handing them to ``sink``.

    ``sink(obj, update)`` persists the Event: ``update=False`` means create
    (POST), ``update=True`` means rewrite the same named object (PUT) — the
    count-aggregation path. Both cluster backends implement this as
    ``write_event``.

    The sink runs on a dedicated worker thread (upstream keeps event
    emission off the scheduling path via an async broadcaster for the same
    reason: with a real API server every write is a blocking HTTP round
    trip, and the scheduling loop must not pay it). Enqueueing happens
    inside the aggregation lock, so sink calls for one (pod, reason) are
    strictly ordered — a later count can never be overwritten by an earlier
    one. Sink failures are logged and swallowed: events are best-effort
    observability, never scheduling-path errors. Call :meth:`flush` to wait
    for the backlog (tests, shutdown).
    """

    def __init__(
        self,
        sink: Callable[[dict, bool], None],
        *,
        component: str = "yoda-tpu-scheduler",
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.sink = sink
        self.component = component
        self.clock = clock
        self._lock = threading.Lock()
        # (uid, reason) -> (event name, count, firstTimestamp)
        self._seen: dict[tuple[str, str], tuple[str, int, float]] = {}
        self._pending: queue.Queue = queue.Queue(maxsize=_MAX_PENDING)
        self._worker = threading.Thread(
            target=self._drain, daemon=True, name="yoda-events"
        )
        self._worker.start()

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until all enqueued events reached the sink (True) or the
        timeout passed (False)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._pending.unfinished_tasks == 0:
                return True
            time.sleep(0.005)
        return False

    def close(self, timeout_s: float = 5.0) -> None:
        """Flush the backlog and stop the worker thread. Events emitted
        after close() are aggregated but never sent. Bounded: if the
        backlog is wedged (sink blocked on an unreachable API server) the
        sentinel is skipped and the daemon worker dies with the process —
        close() must never hold a SIGTERM handler past its timeout."""
        self.flush(timeout_s)
        try:
            self._pending.put_nowait(None)
        except queue.Full:
            return
        self._worker.join(timeout=timeout_s)

    def _drain(self) -> None:
        while True:
            item = self._pending.get()
            if item is None:
                self._pending.task_done()
                return
            obj, update = item
            try:
                self.sink(obj, update)
            except Exception:  # noqa: BLE001 — best-effort, see docstring
                log.warning(
                    "failed to write event %s/%s",
                    obj["metadata"].get("namespace"),
                    obj["metadata"].get("name"),
                    exc_info=True,
                )
            finally:
                self._pending.task_done()

    # --- the public reasons ---

    def scheduled(self, pod: PodSpec, node_name: str) -> None:
        self._emit(
            pod,
            "Normal",
            "Scheduled",
            f"Successfully assigned {pod.key} to {node_name}",
        )

    def failed_scheduling(self, pod: PodSpec, message: str) -> None:
        self._emit(pod, "Warning", "FailedScheduling", message)

    def preempted(self, victim: PodSpec, node: str) -> None:
        self._emit(
            victim,
            "Warning",
            "Preempted",
            f"Preempted by {self.component} on node {node} to make room for "
            "a higher-priority TPU workload",
        )

    # --- mechanics ---

    def _emit(self, pod: PodSpec, etype: str, reason: str, message: str) -> None:
        now = self.clock()
        key = (pod.uid, reason)
        with self._lock:
            prior = self._seen.get(key)
            if prior is None:
                # Unique, deterministic-enough name: upstream uses
                # <pod>.<hex timestamp>; collisions just surface as a 409
                # the sink's create-then-update handles.
                name = f"{pod.name}.{format(int(now * 1e6), 'x')}"
                entry = (name, 1, now)
            else:
                entry = (prior[0], prior[1] + 1, prior[2])
            if len(self._seen) >= _MAX_TRACKED and key not in self._seen:
                self._seen.pop(next(iter(self._seen)))
            self._seen[key] = entry
            name, count, first = entry
            obj = self._build(pod, etype, reason, message, name, count, first, now)
            try:
                # Inside the lock: enqueue order == aggregation order, so
                # the worker can never persist counts out of order.
                self._pending.put_nowait((obj, count > 1))
            except queue.Full:
                log.warning("event backlog full; dropping %s/%s", pod.key, reason)

    def _build(
        self,
        pod: PodSpec,
        etype: str,
        reason: str,
        message: str,
        name: str,
        count: int,
        first: float,
        now: float,
    ) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": pod.namespace},
            "involvedObject": {
                "apiVersion": "v1",
                "kind": "Pod",
                "namespace": pod.namespace,
                "name": pod.name,
                "uid": pod.uid,
            },
            "reason": reason,
            "message": message,
            "type": etype,
            "source": {"component": self.component},
            "firstTimestamp": _iso(first),
            "lastTimestamp": _iso(now),
            "count": count,
        }
