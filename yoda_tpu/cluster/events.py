"""Kubernetes Event emission: the `kubectl describe pod` trail.

The reference's clusters get scheduling Events for free from the upstream
kube-scheduler it wraps (reference pkg/register/register.go:10 — the
framework's EventRecorder emits Scheduled / FailedScheduling); this repo's
from-scratch loop must emit its own. The recorder follows the upstream
aggregation discipline: one Event object per (involved pod UID, reason),
POSTed on first occurrence and updated with an incremented ``count`` and
refreshed ``lastTimestamp`` on repeats — so a pod retried 50 times shows
one FailedScheduling row with count=50, not 50 objects.

Reasons emitted (upstream-parity names):

- ``Scheduled`` (Normal) — pod bound to a node,
- ``FailedScheduling`` (Warning) — no feasible node this attempt,
- ``Preempted`` (Warning) — on the victim, when preemption evicts it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from yoda_tpu.api.types import PodSpec

# Bounded memory: beyond this many distinct (uid, reason) keys the oldest
# aggregation entry is dropped (its next event just POSTs a fresh object).
_MAX_TRACKED = 4096


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


class EventRecorder:
    """Builds and aggregates k8s Event objects, handing them to ``sink``.

    ``sink(obj, update)`` persists the Event: ``update=False`` means create
    (POST), ``update=True`` means rewrite the same named object (PUT) — the
    count-aggregation path. Both cluster backends implement this as
    ``write_event``. Sink failures are swallowed: events are best-effort
    observability, never scheduling-path errors (matching upstream, where a
    broken event broadcaster does not fail the scheduler).
    """

    def __init__(
        self,
        sink: Callable[[dict, bool], None],
        *,
        component: str = "yoda-tpu-scheduler",
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.sink = sink
        self.component = component
        self.clock = clock
        self._lock = threading.Lock()
        # (uid, reason) -> (event name, count, firstTimestamp)
        self._seen: dict[tuple[str, str], tuple[str, int, float]] = {}

    # --- the public reasons ---

    def scheduled(self, pod: PodSpec, node_name: str) -> None:
        self._emit(
            pod,
            "Normal",
            "Scheduled",
            f"Successfully assigned {pod.key} to {node_name}",
        )

    def failed_scheduling(self, pod: PodSpec, message: str) -> None:
        self._emit(pod, "Warning", "FailedScheduling", message)

    def preempted(self, victim: PodSpec, node: str) -> None:
        self._emit(
            victim,
            "Warning",
            "Preempted",
            f"Preempted by {self.component} on node {node} to make room for "
            "a higher-priority TPU workload",
        )

    # --- mechanics ---

    def _emit(self, pod: PodSpec, etype: str, reason: str, message: str) -> None:
        now = self.clock()
        key = (pod.uid, reason)
        with self._lock:
            prior = self._seen.get(key)
            if prior is None:
                # Unique, deterministic-enough name: upstream uses
                # <pod>.<hex timestamp>; collisions just surface as a 409
                # the sink's create-then-update handles.
                name = f"{pod.name}.{format(int(now * 1e6), 'x')}"
                entry = (name, 1, now)
            else:
                entry = (prior[0], prior[1] + 1, prior[2])
            if len(self._seen) >= _MAX_TRACKED and key not in self._seen:
                self._seen.pop(next(iter(self._seen)))
            self._seen[key] = entry
        name, count, first = entry
        obj = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": name, "namespace": pod.namespace},
            "involvedObject": {
                "apiVersion": "v1",
                "kind": "Pod",
                "namespace": pod.namespace,
                "name": pod.name,
                "uid": pod.uid,
            },
            "reason": reason,
            "message": message,
            "type": etype,
            "source": {"component": self.component},
            "firstTimestamp": _iso(first),
            "lastTimestamp": _iso(now),
            "count": count,
        }
        try:
            self.sink(obj, count > 1)
        except Exception:  # noqa: BLE001 — best-effort, see class docstring
            import logging

            logging.getLogger("yoda_tpu.events").warning(
                "failed to write event %s/%s", pod.key, reason, exc_info=True
            )
