"""Real Kubernetes API backend: stdlib HTTP list/watch client.

The reference talks to the API server through controller-runtime with an
UNCACHED client — one HTTP round-trip per node per cycle (reference
pkg/yoda/scheduler.go:69-74,87-91,107-112; the §3.2 ★ hot-loop). Here the
real-cluster backend is the opposite shape by construction: background
list+watch loops keep a local store current, the scheduler reads only the
InformerCache built on top of it, and the only per-cycle API write is the
pods/binding POST (the step upstream default binding does for the
reference, SURVEY.md §3.2 [bind]).

Implemented with ``http.client`` only (no kubernetes / requests dependency):

- ``KubeApiConfig`` — endpoint + auth, from kubeconfig-ish env vars or the
  in-cluster service-account mount.
- ``KubeApiClient`` — JSON requests plus a streaming watch (chunked JSON
  lines), one connection per call.
- ``KubeCluster`` — the ``FakeCluster`` surface (add_watcher / list_pods /
  bind_pod / delete_pod / create_pod / put_tpu_metrics ...) backed by the
  real API: list-then-watch threads for Pods and TpuNodeMetrics CRs with
  resourceVersion resume, 410-Gone relist, diff-on-relist event replay, and
  exponential backoff.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import ssl
import threading
import time
import urllib.parse
from dataclasses import dataclass, field

log = logging.getLogger("yoda_tpu.cluster")

from yoda_tpu.api.types import (
    GROUP,
    VERSION,
    K8sNamespace,
    K8sPdb,
    K8sPv,
    K8sPvc,
    K8sNode,
    PodSpec,
    TpuNodeMetrics,
)
from yoda_tpu.cluster.fake import Event

PODS_PATH = "/api/v1/pods"
NODES_PATH = "/api/v1/nodes"
NAMESPACES_PATH = "/api/v1/namespaces"
PVCS_PATH = "/api/v1/persistentvolumeclaims"
PDBS_PATH = "/apis/policy/v1/poddisruptionbudgets"
PVS_PATH = "/api/v1/persistentvolumes"
CR_PLURAL = "tpunodemetrics"
CR_PATH = f"/apis/{GROUP}/{VERSION}/{CR_PLURAL}"

# Kinds KubeCluster can list+watch. The scheduler needs all of them; the node
# agent passes kinds=("Pod",) — it reads pods (HBM attribution of bound
# pods) but never list/watches TpuNodeMetrics or Nodes, so its RBAC needs
# pod reads plus only the tpunodemetrics WRITE verbs (ADVICE round 1: the
# unconditional three-kind watch 403-crash-looped the DaemonSet on a real
# cluster).
SCHEDULER_KINDS = (
    "Pod",
    "TpuNodeMetrics",
    "Node",
    "Namespace",
    "PersistentVolumeClaim",
    "PersistentVolume",
    "PodDisruptionBudget",
)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeApiError(RuntimeError):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


@dataclass(frozen=True)
class KubeApiConfig:
    """Where the API server is and how to authenticate to it."""

    base_url: str                      # e.g. "https://10.0.0.1:443"
    token: str = ""
    ca_file: str | None = None
    insecure_skip_verify: bool = False
    request_timeout_s: float = 30.0
    watch_timeout_s: int = 300         # server-side timeoutSeconds per watch

    @classmethod
    def in_cluster(cls) -> "KubeApiConfig":
        """Service-account config, the in-cluster analog of the reference's
        ``BuildConfigFromFlags("", "")`` fallthrough (reference
        pkg/yoda/scheduler.go:158). Raises (instead of returning a nil
        client like the reference's NewScvClient, SURVEY.md §3.1) when the
        mount is absent."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        token_path = os.path.join(SA_DIR, "token")
        if not host or not os.path.exists(token_path):
            raise RuntimeError(
                "not running in-cluster: KUBERNETES_SERVICE_HOST unset or "
                f"{token_path} missing"
            )
        with open(token_path) as f:
            token = f.read().strip()
        ca = os.path.join(SA_DIR, "ca.crt")
        return cls(
            base_url=f"https://{host}:{port}",
            token=token,
            ca_file=ca if os.path.exists(ca) else None,
        )

    @classmethod
    def from_env(cls) -> "KubeApiConfig":
        """Explicit endpoint via YODA_KUBE_API_URL (+ optional
        YODA_KUBE_TOKEN / YODA_KUBE_CA_FILE / YODA_KUBE_INSECURE=1), falling
        back to the in-cluster mount."""
        url = os.environ.get("YODA_KUBE_API_URL")
        if not url:
            return cls.in_cluster()
        return cls(
            base_url=url,
            token=os.environ.get("YODA_KUBE_TOKEN", ""),
            ca_file=os.environ.get("YODA_KUBE_CA_FILE") or None,
            insecure_skip_verify=os.environ.get("YODA_KUBE_INSECURE") == "1",
        )


class KubeApiClient:
    """Minimal JSON-over-HTTP client with a streaming watch.

    Unary requests reuse ONE keep-alive connection per thread (the wire
    decomposition in BENCH r4/r5 showed per-call TCP setup dominating the
    scheduler's share of gang latency — binding POSTs and status PATCHes
    ride the scheduler thread, so per-thread reuse removes the handshakes
    without any locking). Retry rules for failures on a REUSED connection
    (fresh-connection failures always propagate — a real outage, the
    caller's backoff): send-phase failures retry for any method (the
    server saw at most a truncated request); ``RemoteDisconnected`` —
    the server closed with ZERO response bytes, the signature of the
    keep-alive idle-close race — retries for any method (the Go
    net/http convention for replayable requests); other receive-phase
    failures retry only for idempotent methods, and timeouts never
    (both are response-possibly-processed ambiguous, and re-POSTing a
    committed binding turns a successful bind into a 409 failure).
    Connections idle past the server's plausible keep-alive window are
    proactively discarded, so the race window is the exception, not the
    steady state. Watches manage their own long-lived streaming
    connection as before."""

    # Discard a pooled connection idle longer than this (servers commonly
    # close keep-alive sockets after 60-300 s; reconnecting beats racing
    # the close).
    POOL_IDLE_MAX_S = 30.0

    def __init__(self, config: KubeApiConfig) -> None:
        self.config = config
        parsed = urllib.parse.urlsplit(config.base_url)
        if parsed.scheme not in ("http", "https"):
            raise ValueError(f"unsupported scheme in {config.base_url!r}")
        self._scheme = parsed.scheme
        self._netloc = parsed.netloc
        self._local = threading.local()
        self._ssl_ctx: ssl.SSLContext | None = None
        if self._scheme == "https":
            ctx = ssl.create_default_context(cafile=config.ca_file)
            if config.insecure_skip_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl_ctx = ctx

    def _connect(self, timeout: float) -> http.client.HTTPConnection:
        if self._scheme == "https":
            return http.client.HTTPSConnection(
                self._netloc, timeout=timeout, context=self._ssl_ctx
            )
        return http.client.HTTPConnection(self._netloc, timeout=timeout)

    def _headers(
        self, has_body: bool, content_type: str | None = None
    ) -> dict[str, str]:
        h = {"Accept": "application/json"}
        if self.config.token:
            h["Authorization"] = f"Bearer {self.config.token}"
        if has_body:
            h["Content-Type"] = content_type or "application/json"
        return h

    @staticmethod
    def _url(path: str, params: dict | None) -> str:
        if params:
            return f"{path}?{urllib.parse.urlencode(params)}"
        return path

    def _pooled(self) -> tuple[http.client.HTTPConnection, bool]:
        """This thread's keep-alive connection (reused=True), or a fresh
        one (reused=False). TCP_NODELAY is set on the fresh socket:
        without it, back-to-back request/response pairs on a persistent
        connection serialize on Nagle + delayed-ACK (observed: ~40 ms
        quanta per POST, 10x worse than per-call connections)."""
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            if (
                time.monotonic() - getattr(self._local, "last_used", 0.0)
                <= self.POOL_IDLE_MAX_S
            ):
                return conn, True
            self._discard(conn)  # likely server-closed while idle
        conn = self._connect(self.config.request_timeout_s)
        try:
            conn.connect()
            if conn.sock is not None:
                conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
        except OSError:
            pass  # connect errors surface on the actual request
        self._local.conn = conn
        return conn, False

    def _discard(self, conn: http.client.HTTPConnection) -> None:
        if getattr(self._local, "conn", None) is conn:
            self._local.conn = None
        try:
            conn.close()
        except Exception:  # noqa: BLE001 — already broken
            pass

    def request(
        self,
        method: str,
        path: str,
        *,
        body: dict | None = None,
        params: dict | None = None,
        content_type: str | None = None,
    ) -> dict:
        payload = json.dumps(body) if body is not None else None
        url = self._url(path, params)
        headers = self._headers(payload is not None, content_type)
        # Retry safety: a SEND-phase failure means the socket broke while
        # writing — the server saw at most a truncated request and will
        # not process it, so any method may retry. A RECEIVE-phase
        # failure is ambiguous (the server may have processed the request
        # and died before the response): only idempotent methods retry;
        # re-sending a POST could double-apply (a binding re-POST would
        # 409 and make a SUCCESSFUL bind look failed). Timeouts are
        # receive-ambiguous by definition and never retried.
        idempotent = method in ("GET", "HEAD", "PUT", "DELETE")
        for attempt in (0, 1):
            conn, reused = self._pooled()
            try:
                conn.request(method, url, body=payload, headers=headers)
            except (http.client.HTTPException, OSError):
                self._discard(conn)
                if reused and attempt == 0:
                    continue  # stale keep-alive caught at send: safe retry
                raise
            try:
                resp = conn.getresponse()
                data = resp.read()
            except socket.timeout:
                self._discard(conn)
                raise
            except http.client.RemoteDisconnected:
                self._discard(conn)
                if reused and attempt == 0:
                    # Zero response bytes on a reused connection: the
                    # keep-alive idle-close race — safe for any method.
                    continue
                raise
            except (http.client.HTTPException, OSError):
                self._discard(conn)
                if reused and attempt == 0 and idempotent:
                    continue
                raise
            self._local.last_used = time.monotonic()
            if resp.will_close:
                self._discard(conn)
            if resp.status >= 400:
                raise KubeApiError(resp.status, data.decode(errors="replace")[:512])
            return json.loads(data) if data else {}
        raise AssertionError("unreachable")

    def watch(self, path: str, *, params: dict | None = None):
        """Generator of decoded watch-event dicts ({"type","object"}).
        Returns (StopIteration) on orderly end-of-stream; raises on HTTP or
        connection errors. The caller owns resume/backoff."""
        params = dict(params or {})
        params["watch"] = "true"
        params.setdefault("timeoutSeconds", str(self.config.watch_timeout_s))
        params.setdefault("allowWatchBookmarks", "true")
        # Read timeout slightly past the server-side watch timeout so an
        # orderly stream end wins the race against the socket deadline.
        conn = self._connect(self.config.watch_timeout_s + 15)
        try:
            conn.request(
                "GET", self._url(path, params), headers=self._headers(False)
            )
            resp = conn.getresponse()
            if resp.status >= 400:
                raise KubeApiError(
                    resp.status, resp.read().decode(errors="replace")[:512]
                )
            buf = b""
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    if buf.strip():  # stream ended without trailing newline
                        yield json.loads(buf)
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if line.strip():
                        yield json.loads(line)
        finally:
            conn.close()


def _pod_path(namespace: str, name: str = "") -> str:
    base = f"/api/v1/namespaces/{namespace}/pods"
    return f"{base}/{name}" if name else base


def _split_key(pod_key: str) -> tuple[str, str]:
    namespace, _, name = pod_key.partition("/")
    if not name:
        raise ValueError(f"pod key must be namespace/name, got {pod_key!r}")
    return namespace, name


@dataclass
class _WatchTarget:
    kind: str                 # "Pod" | "TpuNodeMetrics"
    path: str
    decode: object            # Callable[[dict], object]
    key: object               # Callable[[obj], str]
    synced: threading.Event = field(default_factory=threading.Event)
    # True only after a LIST genuinely succeeded — distinct from `synced`,
    # which a 403-degraded optional target also sets (to unblock
    # wait_for_sync). The "synced" liveness sentinel (watch-loop emit and
    # late-watcher replay) must key on THIS flag: replaying the sentinel
    # for a degraded target would flip the informer's enforcement on over
    # an empty store — for PVCs that parks every claim-referencing pod on
    # "claim not found", the exact failure the sentinel exists to prevent.
    listed: threading.Event = field(default_factory=threading.Event)
    # Optional kinds degrade on RBAC 403 instead of blocking wait_for_sync
    # forever: the scheduler runs with no data for that kind (documented
    # fail-closed behavior at the consumer) while the loop keeps retrying.
    optional: bool = False
    # Kinds whose consumers distinguish "no data" from "verifiably empty"
    # get a per-kind "synced" liveness sentinel after a successful LIST
    # (and on late-watcher replay, keyed on `listed`).
    sentinel: bool = False


class KubeCluster:
    """The scheduler's cluster backend against a real API server.

    Exposes the same surface as ``FakeCluster`` (so ``build_stack`` and the
    whole plugin set run unchanged) while maintaining local stores through
    background list+watch loops. Watch delivery order within a kind matches
    API-server event order; ``add_watcher(replay=True)`` replays the current
    store first (list-then-watch), matching ``FakeCluster.add_watcher``.
    """

    # Binds are real API round-trips: the bind pipeline fans gang releases
    # out on the bounded bind executor and overlaps the next scheduling
    # cycle with the in-flight POSTs (standalone.build_stack's
    # bind_pipeline="auto" gate keys on this flag). In-process backends
    # leave this False — their binds are microseconds and the thread
    # handoff costs more.
    remote_binds = True

    def __init__(
        self,
        api: KubeApiClient,
        *,
        backoff_initial_s: float = 0.5,
        backoff_max_s: float = 30.0,
        kinds: tuple[str, ...] = SCHEDULER_KINDS,
        bind_latency_s: float = 0.0,
    ) -> None:
        self.api = api
        # Injectable extra per-bind latency (bench/soak only — emulates a
        # slower API server in front of the real wire path; 0 in
        # production). Slept before the POST, outside any lock, so
        # pipelined binds overlap it.
        self.bind_latency_s = bind_latency_s
        self._backoff_initial_s = backoff_initial_s
        self._backoff_max_s = backoff_max_s
        self._lock = threading.RLock()
        self._watchers: list = []
        self._pods: dict[str, PodSpec] = {}
        self._tpus: dict[str, TpuNodeMetrics] = {}
        self._nodes: dict[str, K8sNode] = {}
        self._nss: dict[str, K8sNamespace] = {}
        self._pvcs: dict[str, K8sPvc] = {}
        self._pdbs: dict[str, K8sPdb] = {}
        self._pvs: dict[str, K8sPv] = {}
        self._rvs: dict[tuple[str, str], str] = {}  # (kind, key) -> resourceVersion
        # Watch-health signals for the federation monitor: when the last
        # event was applied (staleness clock) and how many consecutive
        # watch-loop failures have occurred since the last successful
        # LIST (reset there) — a climbing count with a climbing event age
        # is a partitioned or dying API server, not a quiet cluster.
        self._last_event_mono: float | None = None
        self.consecutive_watch_failures = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        all_targets = {
            "Pod": _WatchTarget(
                "Pod",
                PODS_PATH,
                decode=PodSpec.from_obj,
                key=lambda p: p.key,
            ),
            "TpuNodeMetrics": _WatchTarget(
                "TpuNodeMetrics",
                CR_PATH,
                decode=TpuNodeMetrics.from_obj,
                key=lambda t: t.name,
            ),
            "Node": _WatchTarget(
                "Node",
                NODES_PATH,
                decode=K8sNode.from_obj,
                key=lambda n: n.name,
            ),
            "Namespace": _WatchTarget(
                "Namespace",
                NAMESPACES_PATH,
                decode=K8sNamespace.from_obj,
                key=lambda n: n.name,
                # An image upgraded before the ClusterRole gains the
                # namespaces rule must not crash-loop on sync timeout:
                # namespaceSelector terms fail closed without data, the
                # rest of the scheduler is unaffected.
                optional=True,
            ),
            "PersistentVolumeClaim": _WatchTarget(
                "PersistentVolumeClaim",
                PVCS_PATH,
                decode=K8sPvc.from_obj,
                key=lambda c: c.key,
                # Same degradation contract as Namespace: without the RBAC
                # rule the LIST 403s forever, the "synced" liveness
                # sentinel never fires, the informer's watches_pvcs stays
                # False, and volume constraints are simply not enforced
                # (pre-r4 behavior) instead of parking PVC-referencing
                # pods on "claim not found".
                optional=True,
                sentinel=True,
            ),
            "PersistentVolume": _WatchTarget(
                "PersistentVolume",
                PVS_PATH,
                decode=K8sPv.from_obj,
                key=lambda v: v.name,
                # Same degradation contract: no RBAC rule -> sentinel
                # never fires -> PV affinity not enforced (the claim's
                # zone-label stand-in still applies).
                optional=True,
                sentinel=True,
            ),
            "PodDisruptionBudget": _WatchTarget(
                "PodDisruptionBudget",
                PDBS_PATH,
                decode=K8sPdb.from_obj,
                key=lambda b: b.key,
                # Same degradation contract as PersistentVolumeClaim:
                # without the RBAC rule the LIST 403s forever, the
                # "synced" sentinel never fires, the informer's
                # watches_pdbs stays False, and preemption's victim
                # preference simply ignores budgets (pre-r5 behavior:
                # violations surface as per-eviction 429 refusals).
                optional=True,
                sentinel=True,
            ),
        }
        unknown = set(kinds) - set(all_targets)
        if unknown:
            raise ValueError(f"unknown watch kinds: {sorted(unknown)}")
        self.kinds = tuple(kinds)
        self._targets = [all_targets[k] for k in kinds]

    # --- lifecycle ---

    def start(self) -> None:
        if self._threads:
            raise RuntimeError("KubeCluster already started")
        for target in self._targets:
            t = threading.Thread(
                target=self._watch_loop,
                args=(target,),
                name=f"kube-watch-{target.kind}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    def wait_for_sync(self, timeout_s: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_s
        for target in self._targets:
            if not target.synced.wait(max(deadline - time.monotonic(), 0)):
                return False
        return True

    # --- watch plumbing ---

    def _store(self, kind: str):
        return {
            "Pod": self._pods,
            "TpuNodeMetrics": self._tpus,
            "Node": self._nodes,
            "Namespace": self._nss,
            "PersistentVolumeClaim": self._pvcs,
            "PersistentVolume": self._pvs,
            "PodDisruptionBudget": self._pdbs,
        }[kind]

    def _list_rv(self, target: _WatchTarget) -> str:
        """One LIST: reconcile the local store (diff → added/modified/
        deleted events) and return the collection resourceVersion to watch
        from."""
        data = self.api.request("GET", target.path)
        items = data.get("items", [])
        if target.kind == "Pod":
            # Emit in creation order so restored arrival sequence numbers
            # (queue FIFO tie-breaks) follow pod age.
            items.sort(
                key=lambda o: (
                    o.get("metadata", {}).get("creationTimestamp") or "",
                    o.get("metadata", {}).get("name", ""),
                )
            )
        events: list[Event] = []
        with self._lock:
            store = self._store(target.kind)
            seen: set[str] = set()
            for obj in items:
                decoded = target.decode(obj)
                key = target.key(decoded)
                rv = obj.get("metadata", {}).get("resourceVersion", "")
                seen.add(key)
                prev_rv = self._rvs.get((target.kind, key))
                if key not in store:
                    events.append(Event("added", target.kind, decoded))
                elif rv != prev_rv:
                    events.append(Event("modified", target.kind, decoded))
                else:
                    continue
                store[key] = decoded
                self._rvs[(target.kind, key)] = rv
            for key in list(store):
                if key not in seen:
                    gone = store.pop(key)
                    self._rvs.pop((target.kind, key), None)
                    events.append(Event("deleted", target.kind, gone))
            # Emit while still holding the lock: add_watcher(replay=True)
            # serializes against this, so a registering watcher sees each
            # object exactly once — via replay or via these events, never
            # both (the FakeCluster mutate+emit-under-lock contract).
            # Delivered as ONE list to batch-capable watchers: a relist
            # after a 410/partition replays thousands of diffs, and the
            # batched-ingest pipeline applies them in one pass.
            self._emit_many(events)
        return data.get("metadata", {}).get("resourceVersion", "")

    def _watch_loop(self, target: _WatchTarget) -> None:
        backoff = self._backoff_initial_s
        while not self._stop.is_set():
            try:
                rv = self._list_rv(target)
                self.consecutive_watch_failures = 0
                target.listed.set()
                target.synced.set()
                if target.sentinel:
                    # Prove the watch is genuinely live (RBAC granted) to
                    # downstream informers: only then does an empty store
                    # mean "no objects exist" rather than "no data"
                    # (InformerCache._handle_pvc / _handle_pdb). Without
                    # this sentinel a cluster missing the RBAC rule would
                    # enforce against missing data instead of degrading.
                    self._emit(Event("synced", target.kind, None))
                backoff = self._backoff_initial_s
                while not self._stop.is_set():
                    params = {"resourceVersion": rv} if rv else {}
                    ended = False
                    for raw in self.api.watch(target.path, params=params):
                        etype = raw.get("type", "")
                        if etype == "BOOKMARK":
                            rv = (
                                raw.get("object", {})
                                .get("metadata", {})
                                .get("resourceVersion", rv)
                            )
                            continue
                        if etype == "ERROR":
                            code = raw.get("object", {}).get("code")
                            if code == 410:  # Gone: resume window lost, relist
                                ended = True
                                break
                            raise KubeApiError(
                                int(code or 500), json.dumps(raw.get("object", {}))
                            )
                        obj = raw.get("object", {})
                        rv = obj.get("metadata", {}).get("resourceVersion", rv)
                        self._apply(target, etype, obj)
                    if ended:
                        break  # relist
                    # Orderly stream end (server watch timeout): re-watch
                    # from the last seen rv without relisting.
            except Exception as e:
                if self._stop.is_set():
                    return
                self.consecutive_watch_failures += 1
                if isinstance(e, KubeApiError) and e.status == 410:
                    # Resume window gone (the server answered the watch
                    # request itself with 410, not an in-band ERROR event):
                    # the stored resourceVersion is stale, so relist NOW —
                    # a full list-and-resync reconciles the store and
                    # replays the diff as events. Backing off here would
                    # only widen the blind window; this is not an outage.
                    log.warning(
                        "watch %s: resume window expired (410 Gone); "
                        "relisting immediately", target.kind,
                    )
                    continue
                if (
                    target.optional
                    and isinstance(e, KubeApiError)
                    and e.status == 403
                    and not target.synced.is_set()
                ):
                    log.warning(
                        "watch %s forbidden (RBAC not applied?); scheduler "
                        "proceeds WITHOUT %s data — namespaceSelector "
                        "affinity terms match nothing (pods wait) and "
                        "anti-affinity terms repel conservatively until "
                        "access is granted",
                        target.kind, target.kind,
                    )
                    target.synced.set()  # degrade: do not block cache sync
                # Surface persistent failures (401/403/TLS would otherwise
                # only show up as an opaque sync timeout — ADVICE round 1).
                log.warning(
                    "watch %s failed (%s: %s); retrying in %.1fs",
                    target.kind,
                    type(e).__name__,
                    e,
                    backoff,
                )
                time.sleep(backoff)
                backoff = min(backoff * 2, self._backoff_max_s)

    def _apply(self, target: _WatchTarget, etype: str, obj: dict) -> None:
        decoded = target.decode(obj)
        key = target.key(decoded)
        kind = target.kind
        mapped = {"ADDED": "added", "MODIFIED": "modified", "DELETED": "deleted"}.get(
            etype
        )
        if mapped is None:
            return
        with self._lock:
            store = self._store(kind)
            if mapped == "deleted":
                decoded = store.pop(key, decoded)
                self._rvs.pop((kind, key), None)
            else:
                store[key] = decoded
                self._rvs[(kind, key)] = obj.get("metadata", {}).get(
                    "resourceVersion", ""
                )
            # Under the lock (see _list_rv): no duplicate delivery around a
            # concurrent add_watcher replay.
            self._emit(Event(mapped, kind, decoded))

    # --- FakeCluster surface: watch ---

    def add_watcher(
        self, fn, *, replay: bool = True, batch_fn=None
    ) -> None:
        """Register a watcher (``FakeCluster.add_watcher`` contract).
        ``batch_fn`` marks it batch-capable: the replay here and every
        LIST reconcile diff (``_list_rv``) arrive as ONE list call — the
        batched-ingest pipeline's list plumbing. Live watch events still
        deliver per-event via ``fn``."""
        self._do_add_watcher(fn, replay=replay, batch_fn=batch_fn)

    def remove_watcher(self, fn) -> None:
        """Unregister a watcher by its per-event fn (live shard resize
        retiring a dissolved lane); unknown fns are ignored."""
        with self._lock:
            self._watchers = [
                (f, b) for f, b in self._watchers if f is not fn
            ]

    def _do_add_watcher(self, fn, *, replay: bool = True, batch_fn=None) -> None:
        with self._lock:
            self._watchers.append((fn, batch_fn))
            if replay:
                events: list[Event] = []
                events.extend(
                    Event("added", "Namespace", ns)
                    for ns in self._nss.values()
                )
                for t in self._targets:
                    # Late watchers must not miss the liveness sentinel
                    # (the informer may register after the first LIST).
                    # Key on `listed`, NOT `synced`: a 403-degraded
                    # optional target sets synced without ever listing,
                    # and replaying the sentinel for it would turn the
                    # degradation into enforcement-over-no-data.
                    if t.sentinel and t.listed.is_set():
                        events.append(Event("synced", t.kind, None))
                events.extend(
                    Event("added", "PersistentVolumeClaim", pvc)
                    for pvc in self._pvcs.values()
                )
                events.extend(
                    Event("added", "PersistentVolume", pv)
                    for pv in self._pvs.values()
                )
                events.extend(
                    Event("added", "PodDisruptionBudget", pdb)
                    for pdb in self._pdbs.values()
                )
                events.extend(
                    Event("added", "Node", node)
                    for node in self._nodes.values()
                )
                events.extend(
                    Event("added", "TpuNodeMetrics", tpu)
                    for tpu in self._tpus.values()
                )
                events.extend(
                    Event("added", "Pod", pod)
                    for pod in sorted(
                        self._pods.values(), key=lambda p: p.creation_seq
                    )
                )
                if batch_fn is not None:
                    batch_fn(events)
                else:
                    for event in events:
                        fn(event)

    def _emit(self, event: Event) -> None:
        # Callers hold self._lock (RLock) so store mutation + delivery are
        # atomic w.r.t. add_watcher replay, as in FakeCluster._emit.
        with self._lock:
            self._last_event_mono = time.monotonic()
            for fn, _ in list(self._watchers):
                fn(event)

    def _emit_many(self, events: "list[Event]") -> None:
        """Deliver a reconcile diff: one list call to batch-capable
        watchers, per-event to the rest. Callers hold self._lock."""
        if not events:
            return
        with self._lock:
            self._last_event_mono = time.monotonic()
            for fn, batch_fn in list(self._watchers):
                if batch_fn is not None:
                    batch_fn(events)
                else:
                    for event in events:
                        fn(event)

    def last_event_age_s(self) -> "float | None":
        """Seconds since the last watch event was applied (None before the
        first): the federation health monitor's watch-staleness signal,
        mirroring FakeCluster.last_event_age_s."""
        with self._lock:
            if self._last_event_mono is None:
                return None
            return max(time.monotonic() - self._last_event_mono, 0.0)

    def probe(self) -> None:
        """One cheap authenticated round-trip against the API server (the
        federation health monitor's probe): a single-item pod LIST, so RBAC
        already granted for the watch covers it. Raises on failure — the
        monitor classifies the exception with cluster.retry's rules
        (timeouts/5xx = connectivity loss driving PARTITIONED/LOST; other
        API errors = reachable-but-broken, pinning DEGRADED)."""
        self.api.request("GET", PODS_PATH, params={"limit": "1"})

    # --- FakeCluster surface: pods ---

    def create_pod(self, pod: PodSpec) -> PodSpec:
        self.api.request("POST", _pod_path(pod.namespace), body=pod.to_obj())
        return pod

    def bind_pod(self, pod_key: str, node_name: str) -> None:
        """POST the pods/binding subresource — upstream default binding's
        API call (SURVEY.md §3.2 [bind])."""
        if self.bind_latency_s > 0:
            time.sleep(self.bind_latency_s)
        namespace, name = _split_key(pod_key)
        body = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": name, "namespace": namespace},
            "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
        }
        try:
            self.api.request(
                "POST", f"{_pod_path(namespace, name)}/binding", body=body
            )
        except KubeApiError as e:
            raise ValueError(f"binding {pod_key} -> {node_name}: {e}") from e

    def delete_pod(self, pod_key: str) -> None:
        namespace, name = _split_key(pod_key)
        try:
            self.api.request("DELETE", _pod_path(namespace, name))
        except KubeApiError as e:
            if e.status != 404:
                raise

    def unbind_pod(self, pod_key: str, node_name: str) -> None:
        """Gang transactional rollback against a real API server: a bound
        pod cannot be un-bound (spec.nodeName is immutable once set), so
        the rollback deletes the pod and its controller (Job/Deployment)
        recreates a fresh unbound replica — the same remediation
        coscheduling operators apply to partially-bound gangs. An
        already-gone pod counts as rolled back (delete_pod's 404 path)."""
        self.delete_pod(pod_key)

    def set_nominated_node(self, pod_key: str, node_name: str | None) -> None:
        """PATCH status.nominatedNodeName (merge-patch on pods/status) —
        upstream preemption's nomination write: kubectl's NOMINATED NODE
        column, and other components see the earmarked capacity.

        Best-effort BY DESIGN: this is cosmetic status, and it is the only
        synchronous remote write on the scheduling loop's callback path
        (binds/events go through their own error handling) — a 403 from
        not-yet-applied RBAC, a transient 5xx, or a socket error must
        degrade to a warning, never kill serve_forever."""
        namespace, name = _split_key(pod_key)
        try:
            self.api.request(
                "PATCH",
                f"{_pod_path(namespace, name)}/status",
                body={"status": {"nominatedNodeName": node_name}},
                content_type="application/merge-patch+json",
            )
        except KubeApiError as e:
            if e.status != 404:  # pod deleted while nominating: routine
                log.warning(
                    "nominatedNodeName patch for %s failed (%s); status "
                    "not updated", pod_key, e,
                )
        except OSError as e:
            log.warning(
                "nominatedNodeName patch for %s failed (%s); status not "
                "updated", pod_key, e,
            )

    def write_event(self, obj: dict, update: bool = False) -> None:
        """Persist a scheduling Event (cluster.events.EventRecorder sink):
        POST on first occurrence, PUT the same named object on count
        aggregation. A 409 on create (name collision after recorder
        restart) falls through to the update path; a 404 on update (the
        API server TTL-garbage-collected the Event while the recorder
        still aggregates it — default --event-ttl is 1h, long-pending
        pods outlive it) falls back to re-creating."""
        md = obj.get("metadata", {})
        ns, name = md.get("namespace", "default"), md["name"]
        base = f"/api/v1/namespaces/{ns}/events"
        if not update:
            try:
                self.api.request("POST", base, body=obj)
                return
            except KubeApiError as e:
                if e.status != 409:
                    raise
        try:
            self.api.request("PUT", f"{base}/{name}", body=obj)
        except KubeApiError as e:
            if e.status != 404:
                raise
            self.api.request("POST", base, body=obj)

    def evict_pod(self, pod_key: str) -> bool:
        """Evict via the ``pods/eviction`` subresource — the API-server path
        that honors PodDisruptionBudgets and grace periods, which a bare
        DELETE bypasses (upstream preemption evicts; the reference's cluster
        exhibits that behavior via its upstream scheduler). Returns False
        when the server refuses the eviction (429: a PDB would be violated)
        so the caller can retry a later cycle; an already-gone pod counts
        as evicted."""
        namespace, name = _split_key(pod_key)
        body = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": name, "namespace": namespace},
        }
        try:
            self.api.request(
                "POST", f"{_pod_path(namespace, name)}/eviction", body=body
            )
        except KubeApiError as e:
            if e.status == 404:
                return True
            if e.status == 429:
                log.warning(
                    "eviction of %s refused (disruption budget); will retry",
                    pod_key,
                )
                return False
            raise
        return True

    def get_pod(self, pod_key: str) -> PodSpec | None:
        with self._lock:
            return self._pods.get(pod_key)

    def list_pods(self) -> list[PodSpec]:
        with self._lock:
            return list(self._pods.values())

    def resync_pods(self) -> None:
        """Force one pod LIST against the API server and reconcile the
        local store to it — the failover reconciler's truth refresh
        (framework/reconciler.py). The diff replays through every
        registered watcher as added/modified/deleted events (_list_rv's
        contract), so a bind or deletion the watch stream dropped is
        repaired in the informer, the accountant, and the gang plugin in
        one pass. The watch loop keeps streaming from its own
        resourceVersion; re-applying an already-seen change is a no-op
        (same rv -> no event)."""
        target = next((t for t in self._targets if t.kind == "Pod"), None)
        if target is not None:
            self._list_rv(target)

    # --- FakeCluster surface: TpuNodeMetrics CRs (agent side) ---

    def put_tpu_metrics(self, tpu: TpuNodeMetrics) -> None:
        """Create-or-update the per-node CR: the node agent's publish path.
        Uses GET + POST/PUT (resourceVersion-checked) rather than
        server-side apply to stay dependency-free."""
        path = f"{CR_PATH}/{tpu.name}"
        obj = tpu.to_obj()
        try:
            current = self.api.request("GET", path)
        except KubeApiError as e:
            if e.status != 404:
                raise
            obj["metadata"].pop("resourceVersion", None)
            self.api.request("POST", CR_PATH, body=obj)
            return
        obj["metadata"]["resourceVersion"] = current.get("metadata", {}).get(
            "resourceVersion", ""
        )
        self.api.request("PUT", path, body=obj)

    def delete_tpu_metrics(self, name: str) -> None:
        try:
            self.api.request("DELETE", f"{CR_PATH}/{name}")
        except KubeApiError as e:
            if e.status != 404:
                raise

    def list_tpu_metrics(self) -> list[TpuNodeMetrics]:
        with self._lock:
            return list(self._tpus.values())

    # --- FakeCluster surface: Node objects ---

    def list_nodes(self) -> list[K8sNode]:
        with self._lock:
            return list(self._nodes.values())
