"""Transient-error classification and jittered-backoff retry.

The reference plugin turns EVERY framework-hook failure into a permanent
pod failure; production API servers throw transient errors constantly —
keep-alive races, 409 write conflicts, 429 throttles, rolling-restart 5xx
— and retrying those with bounded jittered backoff is the difference
between a blip and an unschedulable pod. This module owns the policy so
the binder, the permit-release path, and the chaos tests all agree on
what "transient" means:

- ``retryable_api_error``: classifies an exception (``__cause__`` chains
  included — ``KubeCluster.bind_pod`` wraps ``KubeApiError`` in
  ``ValueError``). Duck-typed on ``.status`` so the chaos harness's
  injected errors classify without importing kube internals.
- ``BackoffPolicy`` + ``call_with_retries``: bounded attempts, exponential
  delay with full jitter from a SEEDED rng (deterministic under the chaos
  harness — the same plan replays the same retry schedule).

Genuine infeasibility (a 404 pod, a plain "already bound elsewhere"
ValueError, a label parse error) is never retried: retry only buys time
against errors where time helps.

Backoff sleeps are interruptible: ``interruptible_sleep(stop)`` builds a
sleeper that waits on a ``threading.Event`` instead of ``time.sleep``, and
raises ``RetryAborted`` the moment the event fires — so shutdown or
leadership loss aborts a pending retry immediately instead of draining up
to ``cap_s`` per attempt on the scheduling thread (ISSUE 4).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable

# HTTP statuses worth retrying: 409 write conflicts (optimistic-concurrency
# losers and bind races that a fresh read resolves), 429 API throttling,
# and server-side 5xx. 4xx otherwise means the request itself is wrong.
RETRYABLE_STATUSES = frozenset({409, 429, 500, 502, 503, 504})


def retryable_api_error(exc: BaseException) -> bool:
    """True when retrying the SAME call can plausibly succeed. Walks the
    ``__cause__`` chain so wrapped errors classify by their root."""
    seen = 0
    e: BaseException | None = exc
    while e is not None and seen < 8:  # bounded: defensive vs cause cycles
        status = getattr(e, "status", None)
        if isinstance(status, int) and status in RETRYABLE_STATUSES:
            return True
        if isinstance(e, (TimeoutError, ConnectionError)):
            return True
        if isinstance(e, OSError):
            return True  # socket-level failures: the transport, not the verb
        e = e.__cause__
        seen += 1
    return False


class RetryAborted(RuntimeError):
    """A retry backoff sleep was interrupted (stop event fired): the call
    is abandoned immediately. Never retryable by classification — no
    ``status``, not an OSError — so it propagates out of
    ``call_with_retries`` unchanged."""


def interruptible_sleep(stop: "threading.Event") -> Callable[[float], None]:
    """A ``sleep`` drop-in for ``call_with_retries`` that waits on
    ``stop``: the full delay passes when the event stays clear; the event
    firing raises ``RetryAborted`` at once (shutdown / leadership loss
    must not be delayed by up to ``cap_s`` per pending attempt)."""

    def _sleep(delay_s: float) -> None:
        if stop.wait(delay_s):
            raise RetryAborted("stop requested during retry backoff")

    return _sleep


@dataclass(frozen=True)
class BackoffPolicy:
    """Bounded retry with full-jitter exponential backoff (attempt k sleeps
    uniform(0, min(base * 2**k, cap)) — the AWS full-jitter shape, which
    desynchronizes contending retriers better than equal-jitter)."""

    attempts: int = 3          # retries AFTER the first try (0 = no retry)
    base_s: float = 0.05
    cap_s: float = 1.0

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        return rng.uniform(0.0, min(self.base_s * (2 ** attempt), self.cap_s))


def call_with_retries(
    fn: Callable[[], object],
    *,
    policy: BackoffPolicy,
    rng: random.Random | None = None,
    sleep: Callable[[float], None] = time.sleep,
    classify: Callable[[BaseException], bool] = retryable_api_error,
    on_retry: Callable[[int, BaseException], None] | None = None,
):
    """Run ``fn``, retrying transient failures per ``policy``. Non-retryable
    errors and the final exhausted attempt propagate unchanged."""
    rng = rng or random.Random()
    for attempt in range(policy.attempts + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classification decides
            if attempt >= policy.attempts or not classify(e):
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(policy.delay_s(attempt, rng))
    raise AssertionError("unreachable")
