"""In-memory fake API server with watch semantics.

Stores pods and TpuNodeMetrics CRs, delivers create/update/delete events to
watchers synchronously (the informer), and implements pod binding — the
subset of the Kubernetes API the scheduler touches. Thread-safe.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Literal

from yoda_tpu.api.types import (
    K8sNamespace,
    K8sNode,
    K8sPdb,
    K8sPv,
    K8sPvc,
    PodSpec,
    TpuNodeMetrics,
)

# "synced" is a per-kind liveness sentinel (KubeCluster emits it once a
# kind's LIST succeeded — the informer's RBAC-degradation gates key on it).
EventType = Literal["added", "modified", "deleted", "synced"]


@dataclass(frozen=True)
class Event:
    type: EventType
    kind: str  # "Pod" | "TpuNodeMetrics" | "Node" | "Namespace" | ...
    # The object; None for "synced" sentinel events, which carry no
    # payload (watchers filtering by kind first never see a None obj).
    obj: object


class FakeCluster:
    def __init__(self, *, bind_latency_s: float = 0.0) -> None:
        # Injectable per-bind latency (bind-pipeline bench + tests):
        # emulates the API round-trip a real pods/binding POST costs.
        # Slept OUTSIDE the store lock so concurrent pipelined binds
        # overlap the way real RPCs do; > 0 also flips build_stack's
        # bind_pipeline="auto" gate on.
        self.bind_latency_s = bind_latency_s
        self._lock = threading.RLock()
        self._pods: dict[str, PodSpec] = {}
        self._tpus: dict[str, TpuNodeMetrics] = {}
        self._nodes: dict[str, K8sNode] = {}
        self._namespaces: dict[str, K8sNamespace] = {}
        self._pvcs: dict[str, K8sPvc] = {}  # "namespace/name" -> claim
        self._pdbs: dict[str, K8sPdb] = {}  # "namespace/name" -> budget
        # Evictions consumed against a PUBLISHED status.disruptionsAllowed
        # since the budget object last changed — the real API decrements
        # the status as it admits evictions; a static fake value would
        # under-enforce sequential evictions. Reset by put_pdb (the
        # disruption controller republishing).
        self._pdb_used: dict[str, int] = {}
        self._pvs: dict[str, K8sPv] = {}    # name -> persistent volume
        self._events: dict[str, dict] = {}
        self._watchers: list[Callable[[Event], None]] = []
        self._rv = 0
        # Pod keys whose eviction a PodDisruptionBudget would block (tests).
        self.eviction_blocked: set[str] = set()
        # Monotonic instant of the last event DELIVERED to watchers (not
        # suppressed ones): the cluster-side half of the watch staleness
        # clock the federation health monitor reads.
        self._last_emit_mono: float | None = None
        # Watch-drop injection (failover / reconciler tests): events of
        # these kinds mutate the store but are NOT delivered to watchers
        # — the store (cluster truth) and the informer caches diverge
        # exactly the way a dropped watch stream makes them diverge, and
        # the drift reconciler's repair is what re-converges them.
        self.suppress_kinds: set[str] = set()

    # --- watch ---

    def add_watcher(
        self,
        fn: Callable[[Event], None],
        *,
        replay: bool = True,
        batch_fn: "Callable[[list[Event]], None] | None" = None,
    ) -> None:
        """Register a watcher; with ``replay`` it first receives synthetic
        'added' events for existing objects (list-then-watch semantics).
        ``batch_fn``, when given, marks the watcher batch-capable: bulk
        deliveries (the replay here, KubeCluster's LIST reconcile diffs)
        arrive as ONE list call instead of per-event — the batched-ingest
        pipeline's list plumbing (cluster.ingest). Live mutations still
        deliver per-event via ``fn``."""
        with self._lock:
            self._watchers.append(fn)
            if replay:
                events = self._replay_events()
                if batch_fn is not None:
                    batch_fn(events)
                else:
                    for event in events:
                        fn(event)

    def remove_watcher(self, fn: Callable[[Event], None]) -> None:
        """Unregister a watcher (live shard resize retiring a dissolved
        lane's informer chain). Unknown fns are ignored — removal must
        be idempotent across partially-wired stacks."""
        with self._lock:
            try:
                self._watchers.remove(fn)
            except ValueError:
                pass

    def _replay_events(self) -> "list[Event]":
        return (
            [Event("added", "Namespace", ns) for ns in self._namespaces.values()]
            + [
                Event("added", "PersistentVolumeClaim", pvc)
                for pvc in self._pvcs.values()
            ]
            + [Event("added", "PersistentVolume", pv) for pv in self._pvs.values()]
            + [
                Event("added", "PodDisruptionBudget", pdb)
                for pdb in self._pdbs.values()
            ]
            + [Event("added", "Node", node) for node in self._nodes.values()]
            + [
                Event("added", "TpuNodeMetrics", tpu)
                for tpu in self._tpus.values()
            ]
            + [Event("added", "Pod", pod) for pod in self._pods.values()]
        )

    def _emit(self, event: Event) -> None:
        if event.kind in self.suppress_kinds:
            return  # injected watch drop: store updated, stream silent
        self._last_emit_mono = time.monotonic()
        for fn in list(self._watchers):
            fn(event)

    def last_event_age_s(self) -> "float | None":
        """Seconds since an event was last delivered to watchers (None
        before the first): the health monitor's watch-staleness signal."""
        with self._lock:
            if self._last_emit_mono is None:
                return None
            return max(time.monotonic() - self._last_emit_mono, 0.0)

    def probe(self) -> None:
        """Cheap liveness probe (federation health monitor): an in-memory
        store is reachable by construction. Fault-injecting fronts
        (testing.chaos.ChaosCluster) override this to raise while the
        cluster is partitioned or lost."""
        with self._lock:
            pass

    # --- pods ---

    def create_pod(self, pod: PodSpec) -> PodSpec:
        with self._lock:
            if pod.key in self._pods:
                raise ValueError(f"pod {pod.key} already exists")
            self._pods[pod.key] = pod
            self._emit(Event("added", "Pod", pod))
            return pod

    def bind_pod(self, pod_key: str, node_name: str) -> None:
        """The pods/binding subresource (upstream default binding POSTs this,
        SURVEY.md §3.2 [bind])."""
        if self.bind_latency_s > 0:
            time.sleep(self.bind_latency_s)
        with self._lock:
            pod = self._pods[pod_key]
            if pod.node_name is not None and pod.node_name != node_name:
                raise ValueError(
                    f"pod {pod_key} already bound to {pod.node_name}"
                )
            pod.node_name = node_name
            pod.phase = "Running"
            self._emit(Event("modified", "Pod", pod))

    def unbind_pod(self, pod_key: str, node_name: str) -> None:
        """Reverse a binding (gang transactional rollback). Only the named
        node's binding is cleared — a pod re-bound elsewhere concurrently
        is left alone. Missing pods are a no-op (deleted mid-rollback)."""
        with self._lock:
            pod = self._pods.get(pod_key)
            if pod is None or pod.node_name != node_name:
                return
            pod.node_name = None
            pod.phase = "Pending"
            self._emit(Event("modified", "Pod", pod))

    def update_pod(self, pod: PodSpec) -> None:
        """Replace an existing pod's spec (e.g. a controller clearing
        spec.schedulingGates) and emit the modified event. Object identity
        (uid, arrival order) is preserved from the stored pod — a real API
        server keeps metadata.uid across updates, and the informer's
        gate-clear detection keys on it."""
        with self._lock:
            old = self._pods.get(pod.key)
            if old is None:
                raise KeyError(pod.key)
            pod.uid = old.uid
            pod.creation_seq = old.creation_seq
            self._pods[pod.key] = pod
            self._emit(Event("modified", "Pod", pod))

    def delete_pod(self, pod_key: str) -> None:
        with self._lock:
            pod = self._pods.pop(pod_key, None)
            if pod is not None:
                self._emit(Event("deleted", "Pod", pod))

    def set_nominated_node(self, pod_key: str, node_name: str | None) -> None:
        """The pods/status nominatedNodeName patch, fake-side (no-op for
        missing pods, mirroring KubeCluster)."""
        with self._lock:
            pod = self._pods.get(pod_key)
            if pod is None:
                return
            pod.nominated_node_name = node_name
            self._emit(Event("modified", "Pod", pod))

    def evict_pod(self, pod_key: str) -> bool:
        """The pods/eviction subresource, fake-side: deletes unless the
        eviction would violate a stored PodDisruptionBudget (the real API
        server's enforcement, 429 path of KubeCluster.evict_pod) or the
        test marked the pod protected via ``eviction_blocked``."""
        with self._lock:
            if pod_key in self.eviction_blocked:
                return False
            pod = self._pods.get(pod_key)
            consumed: list[str] = []
            if pod is not None:
                for pdb in self._pdbs.values():
                    if not pdb.matches(pod):
                        continue
                    # Only BOUND pods count toward the budget (the real
                    # API derives disruptionsAllowed from currentHealthy,
                    # i.e. running pods — a pending replica protects
                    # nothing), matching preemption's _PdbLedger view.
                    matching = sum(
                        1
                        for p in self._pods.values()
                        if p.node_name and pdb.matches(p)
                    )
                    allowed = pdb.allowed_disruptions(
                        matching
                    ) - self._pdb_used.get(pdb.key, 0)
                    if allowed < 1:
                        return False
                    consumed.append(pdb.key)
                for key in consumed:
                    # Decrement only budgets with a PUBLISHED status (the
                    # derived path self-corrects via the matching count).
                    if self._pdbs[key].disruptions_allowed is not None:
                        self._pdb_used[key] = self._pdb_used.get(key, 0) + 1
        self.delete_pod(pod_key)
        return True

    def get_pod(self, pod_key: str) -> PodSpec | None:
        with self._lock:
            return self._pods.get(pod_key)

    def list_pods(self) -> list[PodSpec]:
        with self._lock:
            return list(self._pods.values())

    # --- Events (written by cluster.events.EventRecorder) ---

    def write_event(self, obj: dict, update: bool = False) -> None:
        md = obj.get("metadata", {})
        key = f"{md.get('namespace', 'default')}/{md['name']}"
        with self._lock:
            self._events[key] = obj

    def list_events(self) -> list[dict]:
        with self._lock:
            return list(self._events.values())

    # --- TpuNodeMetrics CRs (written by the node agent) ---

    def put_tpu_metrics(self, tpu: TpuNodeMetrics) -> None:
        with self._lock:
            self._rv += 1
            tpu.resource_version = self._rv
            is_new = tpu.name not in self._tpus
            self._tpus[tpu.name] = tpu
            self._emit(Event("added" if is_new else "modified", "TpuNodeMetrics", tpu))

    def delete_tpu_metrics(self, name: str) -> None:
        with self._lock:
            tpu = self._tpus.pop(name, None)
            if tpu is not None:
                self._emit(Event("deleted", "TpuNodeMetrics", tpu))

    def list_tpu_metrics(self) -> list[TpuNodeMetrics]:
        with self._lock:
            return list(self._tpus.values())

    # --- Node objects (cordon / taints / lifecycle) ---

    def put_namespace(self, ns: K8sNamespace) -> None:
        with self._lock:
            is_new = ns.name not in self._namespaces
            self._namespaces[ns.name] = ns
            self._emit(
                Event("added" if is_new else "modified", "Namespace", ns)
            )

    def delete_namespace(self, name: str) -> None:
        with self._lock:
            ns = self._namespaces.pop(name, None)
            if ns is not None:
                self._emit(Event("deleted", "Namespace", ns))

    def put_pvc(self, pvc: K8sPvc) -> None:
        with self._lock:
            is_new = pvc.key not in self._pvcs
            self._pvcs[pvc.key] = pvc
            self._emit(
                Event(
                    "added" if is_new else "modified",
                    "PersistentVolumeClaim",
                    pvc,
                )
            )

    def delete_pvc(self, key: str) -> None:
        with self._lock:
            pvc = self._pvcs.pop(key, None)
            if pvc is not None:
                self._emit(Event("deleted", "PersistentVolumeClaim", pvc))

    def put_pv(self, pv: K8sPv) -> None:
        with self._lock:
            is_new = pv.name not in self._pvs
            self._pvs[pv.name] = pv
            self._emit(
                Event("added" if is_new else "modified", "PersistentVolume", pv)
            )

    def delete_pv(self, name: str) -> None:
        with self._lock:
            pv = self._pvs.pop(name, None)
            if pv is not None:
                self._emit(Event("deleted", "PersistentVolume", pv))

    def put_pdb(self, pdb: K8sPdb) -> None:
        with self._lock:
            is_new = pdb.key not in self._pdbs
            self._pdbs[pdb.key] = pdb
            self._pdb_used.pop(pdb.key, None)  # controller republished
            self._emit(
                Event("added" if is_new else "modified", "PodDisruptionBudget", pdb)
            )

    def delete_pdb(self, key: str) -> None:
        with self._lock:
            pdb = self._pdbs.pop(key, None)
            if pdb is not None:
                self._emit(Event("deleted", "PodDisruptionBudget", pdb))

    def list_pdbs(self) -> list[K8sPdb]:
        with self._lock:
            return list(self._pdbs.values())

    def put_node(self, node: K8sNode) -> None:
        with self._lock:
            is_new = node.name not in self._nodes
            self._nodes[node.name] = node
            self._emit(Event("added" if is_new else "modified", "Node", node))

    def set_node_ready(self, name: str, ready: bool) -> None:
        """Node-condition helper (node failure-domain tests + chaos): flip
        the stored Node's Ready condition — what the node controller does
        when a kubelet stops responding — creating a bare Node object if
        none exists. The node health monitor treats NotReady as DOWN."""
        with self._lock:
            node = self._nodes.get(name) or K8sNode(name=name)
            node.ready = ready
        self.put_node(node)

    def kill_node(self, name: str) -> None:
        """Full host death in one call: the Node object AND the TPU CR
        deleted (what a cloud provider's node deletion looks like on the
        watch stream). Bound pods are left in place — node GC owns them;
        the health monitor's ghost-release + repair handle the fallout."""
        self.delete_node(name)
        self.delete_tpu_metrics(name)

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is not None:
                self._emit(Event("deleted", "Node", node))

    def list_nodes(self) -> list[K8sNode]:
        with self._lock:
            return list(self._nodes.values())
