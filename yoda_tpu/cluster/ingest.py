"""Batched watch-event ingestion: bounded coalescing batches between the
cluster watch streams and the handler chain.

The serve-side scalability wall this removes (ISSUE 10): every watch event
used to run the full handler chain one at a time — per-event informer lock
round-trips, a metrics-epoch bump per event, and (for qualifying events) a
whole-queue ``move_all_to_active()`` sweep per event. At 1M-pod fleet
event rates the scheduler serializes through per-event Python long before
any kernel dispatch matters. Here the stream is drained into bounded
batches, coalesced by ``(kind, uid)`` — last-write-wins for modifies,
delete supersedes — and each batch is applied under ONE informer lock
acquisition with ONE metrics-epoch bump and ONE reactivation decision
(``InformerCache.handle_batch`` + the ``on_change_batch`` hook wired in
``standalone.build_stack``).

Coalescing semantics (the ingest-parity contract, tests/test_ingest.py):
consumers never observe intermediate states inside one batch window —
an object modified five times arrives once with its final value; an
object created and deleted inside the window never arrives at all. End
state is identical to per-event application; only the intermediate
observations (and the version/epoch counters) differ.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable

from yoda_tpu.cluster.fake import Event


def _coalesce_key(event: Event) -> "tuple[str, str] | None":
    """Identity key for coalescing, or None for barrier events ("synced"
    sentinels carry no object and must never merge or reorder). Keyed by
    uid where the object has one (a deleted-and-recreated pod has a fresh
    uid and must NOT coalesce with its predecessor), else by the object's
    key/name."""
    if event.type == "synced" or event.obj is None:
        return None
    obj = event.obj
    ident = getattr(obj, "uid", "") or ""
    if not ident:
        ident = getattr(obj, "key", None) or getattr(obj, "name", "")
    return (event.kind, str(ident))


def coalesce(events: Iterable[Event]) -> list[Event]:
    """Collapse an event run to its net effect per object, preserving the
    relative order of first appearance (cross-kind causality — a Node
    added before a Pod bound to it stays before it). Rules:

    - modify after add  -> one "added" carrying the LATEST object (the
      consumer never saw the add, so the merged event must still announce
      a new object);
    - modify after modify -> last write wins;
    - delete after modify -> the delete alone (delete supersedes);
    - delete after a not-yet-delivered add -> both dropped (net zero);
    - delete then add under the SAME key (non-uid kinds recreated in one
      window) -> both kept, in order — never merged across a deletion.
    """
    slots: list[Event | None] = []
    index: dict[tuple[str, str], int] = {}
    for event in events:
        key = _coalesce_key(event)
        if key is None:
            slots.append(event)
            continue
        i = index.get(key)
        prev = slots[i] if i is not None else None
        if prev is None:
            index[key] = len(slots)
            slots.append(event)
            continue
        if event.type == "deleted":
            if prev.type == "added":
                slots[i] = None  # created and destroyed inside the window
                del index[key]
            else:
                slots[i] = event
        elif prev.type == "deleted":
            # Recreation under a reused key: keep the delete where it
            # was and start a fresh entry for the new object.
            index[key] = len(slots)
            slots.append(event)
        elif prev.type == "added":
            slots[i] = Event("added", event.kind, event.obj)
        else:
            slots[i] = event
    return [e for e in slots if e is not None]


class EventBatcher:
    """Bounded batching stage between a cluster's watch delivery and the
    handler chain. ``offer`` (the per-event watcher) buffers and
    coalesces; a batch is applied — via ``apply_fn(list_of_events)`` —
    when the buffer reaches ``batch_max``, when ``window_s`` elapses
    since the batch's first event (background drain thread), or on an
    explicit :meth:`flush`. With ``window_s == 0`` every offer flushes
    immediately (batch of one: per-event semantics, kept for the
    knob-gated off position). Batches are applied one at a time in
    arrival order (``_apply_lock``); events offered during an apply go
    to the next batch."""

    def __init__(
        self,
        apply_fn: Callable[[list[Event]], None],
        *,
        batch_max: int = 256,
        window_s: float = 0.0,
        on_batch: "Callable[[int, int], None] | None" = None,
    ) -> None:
        self.apply_fn = apply_fn
        self.batch_max = max(int(batch_max), 1)
        self.window_s = max(float(window_s), 0.0)
        # Observability hook: (raw events in, coalesced events applied)
        # per batch — feeds yoda_ingest_events_total / _batch_size.
        self.on_batch = on_batch
        self.events_in = 0
        self.batches = 0
        self.events_out = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._apply_lock = threading.Lock()
        self._slots: list[Event | None] = []
        self._index: dict[tuple[str, str], int] = {}
        self._pending = 0  # live (non-None) slots — O(1) batch_max check
        self._raw = 0  # raw events buffered (pre-coalescing)
        self._first_at: float | None = None
        self._stopped = False
        self._thread: threading.Thread | None = None
        if self.window_s > 0:
            self._thread = threading.Thread(
                target=self._drain_loop, name="yoda-ingest", daemon=True
            )
            self._thread.start()

    # --- watcher surface (cluster add_watcher) ---

    def offer(self, event: Event) -> None:
        self.offer_batch((event,))

    def offer_batch(self, events: Iterable[Event]) -> None:
        """Buffer a run of events (the clusters' list-delivery path hands
        whole LIST/replay diffs here in one call). Coalesces in place
        against anything already buffered."""
        flush_now = False
        with self._cond:
            for event in events:
                self._raw += 1
                key = _coalesce_key(event)
                i = self._index.get(key) if key is not None else None
                prev = self._slots[i] if i is not None else None
                if key is None or prev is None:
                    if key is not None:
                        self._index[key] = len(self._slots)
                    self._slots.append(event)
                    self._pending += 1
                elif event.type == "deleted":
                    if prev.type == "added":
                        self._slots[i] = None
                        del self._index[key]
                        self._pending -= 1
                    else:
                        self._slots[i] = event
                elif prev.type == "deleted":
                    self._index[key] = len(self._slots)
                    self._slots.append(event)
                    self._pending += 1
                elif prev.type == "added":
                    self._slots[i] = Event("added", event.kind, event.obj)
                else:
                    self._slots[i] = event
            if self._first_at is None and self._pending:
                self._first_at = time.monotonic()
                self._cond.notify_all()
            if self._pending >= self.batch_max or (
                self.window_s == 0 and self._pending
            ):
                flush_now = True
        if flush_now:
            self.flush()

    def backlog(self) -> int:
        """Coalesced events buffered and not yet applied — the overload
        monitor's ingest-pressure signal (a flood the apply chain is not
        keeping up with shows here first)."""
        with self._lock:
            return self._pending

    # --- draining ---

    def _take_locked(self) -> "tuple[list[Event], int]":
        batch = [e for e in self._slots if e is not None]
        raw = self._raw
        self._slots = []
        self._index = {}
        self._pending = 0
        self._raw = 0
        self._first_at = None
        return batch, raw

    def flush(self) -> None:
        """Apply everything buffered right now (tests, shutdown, and the
        batch_max / zero-window fast paths). Serialized against the drain
        thread so batches land in order."""
        with self._apply_lock:
            with self._cond:
                batch, raw = self._take_locked()
            if not batch and raw == 0:
                return
            self.events_in += raw
            if batch:
                self.batches += 1
                self.events_out += len(batch)
                self.apply_fn(batch)
            if self.on_batch is not None:
                self.on_batch(raw, len(batch))

    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                while self._first_at is None and not self._stopped:
                    self._cond.wait()
                if self._stopped and self._first_at is None:
                    return
                deadline = (self._first_at or 0.0) + self.window_s
                while not self._stopped:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._first_at is None:
                        break
                    self._cond.wait(timeout=remaining)
            self.flush()
            if self._stopped:
                with self._cond:
                    if self._first_at is None:
                        return

    def stop(self) -> None:
        """Stop the drain thread and apply any residue."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self.flush()
