"""Informer cache: watch-driven local store + per-cycle snapshots.

The fix for the reference's hot-loop (SURVEY.md §3.2 ★, §7 step 2): the
scheduler reads ONLY this cache during a cycle. The cache maintains:

- the TpuNodeMetrics CR per node (watch on the CRD, replacing per-cycle Gets),
- the pods bound to each node (for allocation scoring, reference
  pkg/yoda/score/algorithm.go:77-80),
- incrementally-maintained claimed-HBM per node,
- two monotonic versions: ``version`` (any change — snapshot cache key) and
  ``metrics_version`` (TPU CR changes only — fleet-array cache key, so pod
  binds do not force an O(nodes x chips) array rebuild),
- an epoch/delta feed over ``metrics_version`` (:meth:`changes_since`):
  consumers holding device-resident fleet state ask "which nodes changed
  since epoch E" and apply only those rows instead of re-reading the whole
  fleet (ops/resident.py FleetStateCache), plus the analogous
  :meth:`claimed_changes_since` feed over the per-node claimed-HBM totals.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

from yoda_tpu.api.requests import LabelParseError, pod_request
from yoda_tpu.api.types import K8sNode, K8sPdb, K8sPv, K8sPvc, PodSpec, TpuNodeMetrics
from yoda_tpu.cluster.fake import Event
from yoda_tpu.framework.interfaces import NodeInfo, Snapshot

MIB = 1 << 20


@dataclass(frozen=True)
class FleetDelta:
    """What changed in the metrics-relevant fleet between two epochs
    (:meth:`InformerCache.changes_since`). ``epoch`` is the current
    metrics epoch the delta brings the consumer up to; ``changed`` names
    nodes whose CR VALUES changed in place (row refill suffices);
    ``structural`` means the candidate-node SET itself changed (CR or
    Node object added/deleted) — bucketed row indices may have shifted
    and the consumer must re-stack from a snapshot."""

    epoch: int
    changed: frozenset
    structural: bool


class InformerCache:
    def __init__(
        self,
        *,
        scheduler_name: str = "yoda-tpu",
        on_pod_pending: Callable[[PodSpec], None] | None = None,
        on_change: Callable[[Event], None] | None = None,
        on_change_batch: "Callable[[list[Event]], None] | None" = None,
        watches_pvcs: bool = False,
        watches_pvs: bool = False,
        watches_pdbs: bool = False,
        staleness_s: float = 0.0,
        now_fn: Callable[[], float] = time.time,
        mono_fn: Callable[[], float] = time.monotonic,
        node_filter_fn: "Callable[[str, TpuNodeMetrics], bool] | None" = None,
        pod_route_fn: "Callable[[PodSpec], bool] | None" = None,
    ) -> None:
        self.scheduler_name = scheduler_name
        self.on_pod_pending = on_pod_pending
        self.on_change = on_change
        # Batched-ingest hook (ISSUE 10): when set, one call per applied
        # batch with the list of schedulability-relevant events —
        # standalone wires the delete fast path plus ONE
        # move_all_to_active decision here, instead of a per-event sweep.
        # Falls back to per-event ``on_change`` when unset.
        self.on_change_batch = on_change_batch
        # True when the backend streams PersistentVolumeClaim events: then
        # an empty PVC store means "no claims exist" (pods referencing one
        # wait), while False means "no PVC data" (volume constraints are
        # not enforced — snapshot.pvcs stays None).
        self.watches_pvcs = watches_pvcs
        # Same contract for PodDisruptionBudgets: True = the PDB watch is
        # live, preemption's victim preference may trust the (possibly
        # empty) store; False = no PDB data, the preference is skipped and
        # violations surface only as per-eviction 429 refusals.
        self.watches_pdbs = watches_pdbs
        # And for PersistentVolumes: True = bound claims resolve to their
        # PV's real nodeAffinity; False = the claim's zone-label stand-in
        # applies (snapshot.pvs stays None).
        self.watches_pvs = watches_pvs
        # The scheduler's max_metrics_age_s, used ONLY to classify
        # timestamp-only republishes: a node whose publish GAP exceeded
        # this had gone stale, so its refresh changes schedulability and
        # must reactivate parked pods; an on-time heartbeat does not.
        # ``now_fn`` must be the SAME clock domain the agents stamp
        # last_updated_unix with (wall clock in production; inject the
        # simulated clock in virtual-time setups or every heartbeat
        # misclassifies as a stale-node refresh).
        self.staleness_s = staleness_s
        self.now_fn = now_fn
        # Scheduler shard-out (framework/shards.py): a sharded stack's
        # informer restricts its SNAPSHOT (and therefore its resident
        # fleet arrays) to the shard's node partition, and routes only
        # this shard's pods into the scheduling queue. Both hooks must be
        # PURE functions of (name, CR) / pod labels — they run under the
        # informer lock per snapshot build / delta read. None (default) =
        # full fleet, every matching pod (the unsharded behavior).
        self.node_filter_fn = node_filter_fn
        self.pod_route_fn = pod_route_fn
        # Watch-stream staleness clock (federation health signal, also a
        # standalone stuck-watch debugging probe): the monotonic instant
        # the last watch event of ANY kind reached this cache. Separate
        # clock domain from now_fn — event age is a local liveness
        # measure, never compared against agent-stamped wall timestamps.
        self.mono_fn = mono_fn
        self._last_event_mono: float | None = None
        # Node-health fence hook (yoda_tpu/nodehealth): returns the node
        # names currently fenced from NEW placements; stamped onto every
        # snapshot (Snapshot.fenced) so the admission call sites veto
        # them. The monitor calls invalidate_snapshot() on fence flips.
        self.fence_fn: "Callable[[], frozenset] | None" = None
        self._lock = threading.RLock()
        self._tpus: dict[str, TpuNodeMetrics] = {}
        # _tpus keys maintained in sorted order incrementally (bisect on
        # CR add/delete): snapshot() hands the pre-sorted candidate list
        # to Snapshot(order=...) instead of re-sorting O(N log N) per
        # build — at datacenter scale the sort was the next wall after
        # the NodeInfo reuse cache.
        self._tpu_order: list[str] = []
        self._nodes: dict[str, K8sNode] = {}
        self._namespaces: dict[str, dict[str, str]] = {}
        # "namespace/name" -> K8sPvc (minimal volume awareness: the
        # selected-node annotation and zone label the filter honors).
        self._pvcs: dict[str, K8sPvc] = {}
        self._pdbs: dict[str, K8sPdb] = {}
        self._pvs: dict[str, K8sPv] = {}
        # True once any Node event arrived: from then on a TPU CR without a
        # live Node object is excluded from snapshots (node deleted — the
        # reference's upstream snapshot drops such nodes for free, reference
        # pkg/yoda/scheduler.go:101). False = backend has no Node watch;
        # every CR is trusted.
        self._node_informed = False
        self._pods_by_node: dict[str, dict[str, PodSpec]] = {}
        self._claimed_mib: dict[str, int] = {}
        # Every pod uid currently alive in the cluster (any event kind).
        # The scheduler consults this at cycle start so a pod deleted while
        # queued is dropped instead of retried forever (upstream removes
        # deleted pods from its scheduling queues).
        self._live_uids: set[str] = set()
        # Unbound pods held by spec.schedulingGates (upstream
        # PodSchedulingReadiness): kept OUT of the scheduling queue until a
        # modified event shows the gates cleared.
        self._gated_uids: set[str] = set()
        # pod uid -> (node counted on, claim MiB added) — the stored claim is
        # subtracted on uncount so later label mutations cannot skew totals.
        self._pod_nodes: dict[str, tuple[str, int]] = {}
        self._version = 1
        self._metrics_version = 1
        self._snapshot_cache: Snapshot | None = None
        # Epoch/delta feed over metrics_version: one ring entry per bump,
        # (epoch-after-bump, kind, node name), kind "modified" (row refill
        # suffices) or "structural" (node set changed: full re-stack).
        # The ring therefore covers epochs (ring[0].epoch - 1, current];
        # a consumer further behind gets None and re-stacks. 4096 bumps of
        # slack ≈ minutes of heavy churn between consumer syncs.
        self._delta_ring: deque[tuple[int, str, str]] = deque(maxlen=4096)
        # Claimed-HBM delta feed (dyn row 2 of the device-resident state):
        # epoch bumped per claimed-total change, ring of (epoch, node).
        self._claim_epoch = 0
        self._claim_ring: deque[tuple[int, str]] = deque(maxlen=65536)
        # Admission delta feed: epoch bumped per change that can flip a
        # node's ADMISSION verdict without touching the metrics feed —
        # Node-object events (cordon/taint/label/allocatable flips ride
        # "modified", which the metrics ring deliberately elides) and
        # per-node pod-set changes (count/cpu/mem/hostPort usage). The
        # cross-snapshot admission-vector cache (plugins/yoda/batch.py)
        # patches only these nodes' rows instead of re-running its O(N)
        # loop per snapshot rebuild. Fence flips are NOT here: the fence
        # set is stamped per snapshot and consumers diff it directly.
        self._admission_epoch = 0
        self._admission_ring: deque[tuple[int, str]] = deque(maxlen=65536)
        # NodeInfo reuse across snapshots: rebuilding 10^5 NodeInfo objects
        # (plus their pod-list copies) per watch event dominated snapshot()
        # at datacenter scale. Entries are invalidated per node on the
        # events that change what NodeInfo carries (CR, Node object, pod
        # set); unchanged nodes share one immutable NodeInfo across
        # snapshots.
        self._ni_cache: dict[str, NodeInfo] = {}
        # Per-batch accumulators, written by the ``_handle_*`` internals
        # (which run with the lock held and must NOT bump versions
        # themselves): ``handle_batch`` resets them, applies every event,
        # then finalizes — ONE ``_version`` bump, ONE ``_metrics_version``
        # bump covering every changed node, one snapshot invalidation.
        self._batch_dirty = False
        self._batch_metrics: list[tuple[str, str]] = []  # (kind, node)
        self._batch_pending: list[PodSpec] = []

    # --- watch sink ---

    def handle(self, event: Event) -> None:
        self.handle_batch((event,))

    def handle_batch(self, events) -> None:
        """Apply a run of watch events under ONE lock acquisition, with
        one ``version`` bump, one ``metrics_version`` bump (covering every
        metrics-relevant node in the batch — the delta ring gets one entry
        per node, all at the new epoch), and one snapshot invalidation.
        Callers hand in coalesced batches (cluster.ingest); a single-event
        batch is exactly the old per-event ``handle``. Pending-pod and
        change callbacks fire after the batch is fully applied, outside
        the lock — consumers never observe a half-applied batch."""
        relevant_events: list[Event] = []
        with self._lock:
            self._last_event_mono = self.mono_fn()
            self._batch_dirty = False
            self._batch_metrics = []
            self._batch_pending = []
            for event in events:
                relevant = True
                if event.kind == "TpuNodeMetrics":
                    relevant = self._handle_tpu(event)
                elif event.kind == "Pod":
                    self._handle_pod(event)
                elif event.kind == "Node":
                    self._handle_node(event)
                elif event.kind == "Namespace":
                    self._handle_namespace(event)
                elif event.kind == "PersistentVolumeClaim":
                    self._handle_pvc(event)
                elif event.kind == "PersistentVolume":
                    self._handle_pv(event)
                elif event.kind == "PodDisruptionBudget":
                    self._handle_pdb(event)
                # Timestamp-only heartbeats are NOT propagated as cluster
                # changes (upstream's queueing-hint discipline):
                # reactivating every parked pod per heartbeat is a retry
                # storm burning a full-queue sweep per event for zero new
                # information.
                if relevant:
                    relevant_events.append(event)
            if self._batch_dirty:
                self._version += 1
                self._snapshot_cache = None
            if self._batch_metrics:
                self._metrics_version += 1
                for kind, name in self._batch_metrics:
                    self._delta_ring.append(
                        (self._metrics_version, kind, name)
                    )
            pending = self._batch_pending
            self._batch_pending = []
        if self.on_pod_pending is not None:
            for pod in pending:
                self.on_pod_pending(pod)
        if relevant_events:
            if self.on_change_batch is not None:
                self.on_change_batch(relevant_events)
            elif self.on_change is not None:
                for event in relevant_events:
                    self.on_change(event)

    def _handle_pvc(self, event: Event) -> None:
        # Lock held by handle_batch; version bumps via the accumulators.
        if event.type == "synced":
            # KubeCluster emits this after a successful PVC LIST: the
            # watch is genuinely live (RBAC granted), so an empty
            # store now means "no claims exist" and enforcement is on.
            # Without it (403: missing ClusterRole rule) volume
            # constraints degrade to not-enforced instead of parking
            # every PVC-referencing pod on "claim not found".
            self.watches_pvcs = True
            self._batch_dirty = True
            return
        pvc: K8sPvc = event.obj  # type: ignore[assignment]
        if event.type == "deleted":
            self._pvcs.pop(pvc.key, None)
        else:
            self._pvcs[pvc.key] = pvc
        self._batch_dirty = True

    def _handle_pv(self, event: Event) -> None:
        if event.type == "synced":
            self.watches_pvs = True
            self._batch_dirty = True
            return
        pv: K8sPv = event.obj  # type: ignore[assignment]
        if event.type == "deleted":
            self._pvs.pop(pv.name, None)
        else:
            self._pvs[pv.name] = pv
        self._batch_dirty = True

    def _handle_pdb(self, event: Event) -> None:
        if event.type == "synced":
            # PDB LIST succeeded (RBAC granted): enforcement on, as
            # for _handle_pvc's sentinel.
            self.watches_pdbs = True
            return
        pdb: K8sPdb = event.obj  # type: ignore[assignment]
        if event.type == "deleted":
            self._pdbs.pop(pdb.key, None)
        else:
            self._pdbs[pdb.key] = pdb
        # No version bump: budgets gate victim PREFERENCE inside
        # preemption, not filtering/scoring — snapshots and fleet
        # arrays are unaffected.

    def _handle_namespace(self, event: Event) -> None:
        ns = event.obj
        if event.type == "deleted":
            self._namespaces.pop(ns.name, None)
        else:
            self._namespaces[ns.name] = dict(ns.labels)
        self._batch_dirty = True

    def _handle_node(self, event: Event) -> None:
        node: K8sNode = event.obj  # type: ignore[assignment]
        self._node_informed = True
        if event.type == "deleted":
            self._nodes.pop(node.name, None)
        else:
            self._nodes[node.name] = node
        self._ni_cache.pop(node.name, None)
        self._batch_dirty = True
        # EVERY Node event (modified included) feeds the admission ring:
        # cordon/taint/label flips change admission verdicts even though
        # the metrics arrays don't care.
        self._admission_epoch += 1
        self._admission_ring.append((self._admission_epoch, node.name))
        if event.type in ("added", "deleted"):
            # The candidate-node SET changed (a CR may enter/leave the
            # snapshot), which invalidates the fleet arrays keyed on
            # metrics_version. A cordon/taint flip (modified) does not:
            # admission is evaluated per cycle, not baked into arrays.
            self._batch_metrics.append(("structural", node.name))

    def _handle_tpu(self, event: Event) -> bool:
        """Returns whether the event carries schedulability-relevant change.
        A value-identical republish (the agents' steady-state heartbeat)
        refreshes the stored timestamp and the snapshot, but does NOT bump
        ``metrics_version`` — the fleet arrays, burst sets, and parked-pod
        reactivation all key off that, and rebuilding them per heartbeat
        is pure waste (freshness flows live via :meth:`last_updated_map`).
        Exception: a node whose publish gap exceeded ``staleness_s`` had
        gone STALE — its refresh changes feasibility and counts as a real
        change."""
        tpu: TpuNodeMetrics = event.obj  # type: ignore[assignment]
        structural = False
        if event.type == "deleted":
            if self._tpus.pop(tpu.name, None) is not None:
                i = bisect.bisect_left(self._tpu_order, tpu.name)
                if (
                    i < len(self._tpu_order)
                    and self._tpu_order[i] == tpu.name
                ):
                    del self._tpu_order[i]
            relevant = structural = True
        else:
            prev = self._tpus.get(tpu.name)
            self._tpus[tpu.name] = tpu
            if prev is None:
                bisect.insort(self._tpu_order, tpu.name)
            structural = prev is None  # CR added: node set changed
            relevant = prev is None or not prev.values_equal(tpu)
            if not relevant and self.staleness_s > 0:
                # Observed AGE at arrival, not the publish gap: watch
                # delivery latency can push a node past the staleness
                # threshold even when the agent published on time, and
                # its refresh must still reactivate parked pods
                # (arrival age >= publish gap, so this test dominates).
                age = self.now_fn() - prev.last_updated_unix
                relevant = age > self.staleness_s  # was stale: now fresh
        self._ni_cache.pop(tpu.name, None)
        self._batch_dirty = True
        if relevant:
            self._batch_metrics.append(
                ("structural" if structural else "modified", tpu.name)
            )
        return relevant

    def _handle_pod(self, event: Event) -> None:
        pod: PodSpec = event.obj  # type: ignore[assignment]
        if event.type == "deleted":
            self._live_uids.discard(pod.uid)
        else:
            self._live_uids.add(pod.uid)
        counted = self._pod_nodes.get(pod.uid)
        if counted and (event.type == "deleted" or counted[0] != pod.node_name):
            self._uncount_pod(pod.uid)
            counted = None
        if event.type != "deleted" and pod.node_name and counted is None:
            self._count_pod(pod, pod.node_name)
        ours_unbound = (
            event.type != "deleted"
            and pod.node_name is None
            and pod.scheduler_name == self.scheduler_name
        )
        if event.type == "deleted":
            self._gated_uids.discard(pod.uid)
        elif ours_unbound and pod.scheduling_gates:
            self._gated_uids.add(pod.uid)  # held, not schedulable
        elif event.type == "added" and ours_unbound:
            if self._routes_here(pod):
                self._batch_pending.append(pod)
        elif (
            event.type == "modified"
            and ours_unbound
            and pod.uid in self._gated_uids
        ):
            # Gates cleared: NOW the pod becomes schedulable.
            self._gated_uids.discard(pod.uid)
            if self._routes_here(pod):
                self._batch_pending.append(pod)
        self._batch_dirty = True

    def _routes_here(self, pod: PodSpec) -> bool:
        """Does this pending pod belong to THIS informer's scheduling
        queue? True without a route hook (unsharded). Fail closed on a
        raising hook — two shards queueing one pod is the double-bind the
        router exists to prevent; the router's own fallback (global lane)
        catches unroutable pods before this can drop them."""
        fn = self.pod_route_fn
        if fn is None:
            return True
        try:
            return bool(fn(pod))
        except Exception:  # noqa: BLE001 — fail closed (see docstring)
            return False

    def _count_pod(self, pod: PodSpec, node: str) -> None:
        claim = _pod_claim_mib(pod)
        self._pods_by_node.setdefault(node, {})[pod.uid] = pod
        self._pod_nodes[pod.uid] = (node, claim)
        self._claimed_mib[node] = self._claimed_mib.get(node, 0) + claim
        self._ni_cache.pop(node, None)
        self._admission_epoch += 1
        self._admission_ring.append((self._admission_epoch, node))
        if claim:
            self._claim_epoch += 1
            self._claim_ring.append((self._claim_epoch, node))

    def _uncount_pod(self, uid: str) -> None:
        node, claim = self._pod_nodes.pop(uid)
        self._pods_by_node.get(node, {}).pop(uid, None)
        self._claimed_mib[node] = max(self._claimed_mib.get(node, 0) - claim, 0)
        self._ni_cache.pop(node, None)
        self._admission_epoch += 1
        self._admission_ring.append((self._admission_epoch, node))
        if claim:
            self._claim_epoch += 1
            self._claim_ring.append((self._claim_epoch, node))

    # --- readers ---

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    @property
    def metrics_version(self) -> int:
        with self._lock:
            return self._metrics_version

    def changes_since(self, epoch: int) -> "FleetDelta | None":
        """The epoch/delta feed over ``metrics_version``: which nodes
        changed in epochs ``(epoch, current]``. Returns None when the
        consumer is too far behind (the bounded ring no longer covers its
        epoch) or ahead (epoch skew — e.g. state inherited from another
        informer): either way the consumer must fall back to a full
        re-stack from a snapshot. A device-resident consumer
        (ops/resident.py) applies ``changed`` rows in place and re-stacks
        only on ``structural`` deltas."""
        with self._lock:
            cur = self._metrics_version
            if epoch == cur:
                return FleetDelta(cur, frozenset(), False)
            if epoch > cur or not self._delta_ring:
                return None
            if self._delta_ring[0][0] > epoch + 1:
                return None  # ring evicted past the consumer's epoch
            changed: set[str] = set()
            structural = False
            node_filter = self.node_filter_fn
            for e, kind, name in reversed(self._delta_ring):
                if e <= epoch:
                    break
                if node_filter is not None:
                    # Shard partition: another shard's node changing must
                    # not force THIS shard's resident arrays to re-stack
                    # (a foreign name is absent from this snapshot, which
                    # the consumer treats as epoch skew). A node the
                    # filter cannot resolve anymore (CR deleted) stays
                    # relevant — the restack it forces is the safe path.
                    tpu = self._tpus.get(name)
                    if tpu is not None and not node_filter(name, tpu):
                        continue
                if kind == "structural":
                    structural = True
                else:
                    changed.add(name)
            return FleetDelta(cur, frozenset(changed), structural)

    @property
    def claimed_epoch(self) -> int:
        with self._lock:
            return self._claim_epoch

    def claimed_changes_since(
        self, epoch: int
    ) -> "tuple[int, dict[str, int] | None]":
        """Delta feed over the per-node claimed-HBM totals: returns
        ``(current_epoch, {node: claimed_mib})`` for nodes whose total
        changed in epochs ``(epoch, current]``, or ``(current_epoch,
        None)`` when the ring no longer reaches back — the consumer then
        rebuilds from :meth:`claimed_hbm_mib_map` (reading the returned
        epoch FIRST keeps the rebuild race-free: changes landing during
        the map copy are simply re-applied on the next delta)."""
        with self._lock:
            cur = self._claim_epoch
            if epoch == cur:
                return cur, {}
            if epoch > cur or not self._claim_ring:
                return cur, None
            if self._claim_ring[0][0] > epoch + 1:
                return cur, None
            nodes: set[str] = set()
            for e, name in reversed(self._claim_ring):
                if e <= epoch:
                    break
                nodes.add(name)
            return cur, {n: self._claimed_mib.get(n, 0) for n in nodes}

    @property
    def admission_epoch(self) -> int:
        with self._lock:
            return self._admission_epoch

    def admission_changes_since(
        self, epoch: int
    ) -> "tuple[int, frozenset[str] | None]":
        """Delta feed over admission-relevant node state (Node-object
        events + per-node pod-set changes — everything the metrics ring
        elides that can still flip an admission verdict): returns
        ``(current_epoch, changed_nodes)`` for epochs ``(epoch, current]``,
        or ``(current_epoch, None)`` when the bounded ring no longer
        reaches back (or the consumer is ahead — epoch skew): the
        consumer must rebuild its vector from the snapshot. Consumers
        read the SNAPSHOT-STAMPED epoch (``snapshot.admission_epoch``),
        not this live one, so a patched vector is exactly as fresh as the
        snapshot it was patched from."""
        with self._lock:
            cur = self._admission_epoch
            if epoch == cur:
                return cur, frozenset()
            if epoch > cur or not self._admission_ring:
                return cur, None
            if self._admission_ring[0][0] > epoch + 1:
                return cur, None
            nodes: set[str] = set()
            for e, name in reversed(self._admission_ring):
                if e <= epoch:
                    break
                nodes.add(name)
            return cur, frozenset(nodes)

    def claimed_hbm_mib(self, node_name: str) -> int:
        with self._lock:
            return self._claimed_mib.get(node_name, 0)

    def claimed_hbm_mib_map(self) -> dict[str, int]:
        """One consistent copy under a single lock acquisition (see
        ChipAccountant.chips_by_node — same per-dispatch N-call cost)."""
        with self._lock:
            return dict(self._claimed_mib)

    def last_event_age_s(self) -> "float | None":
        """Seconds since the last watch event of any kind reached this
        cache, or None before the first event (a stack built list-then-
        watch replays existing objects, so None means the watch source
        never delivered anything at all). The federation health monitor's
        primary staleness signal — a partitioned API server goes silent
        here long before a probe times out — and a standalone probe for
        debugging stuck watch streams (`informer.last_event_age_s()`
        climbing while the cluster churns = the watch is dead, not the
        cluster quiet)."""
        with self._lock:
            if self._last_event_mono is None:
                return None
            return max(self.mono_fn() - self._last_event_mono, 0.0)

    def last_updated_map(self) -> dict[str, float]:
        """Live per-node metric timestamps — the freshness source for the
        fused kernel's dynamics row. Must be read per dispatch (not baked
        into the metrics-version-cached arrays): timestamp-only heartbeats
        deliberately do NOT bump the metrics version, so cached arrays
        carry stale timestamps while these stay current."""
        with self._lock:
            return {
                name: t.last_updated_unix for name, t in self._tpus.items()
            }

    def list_pdbs(self) -> "list[K8sPdb] | None":
        """The cached PodDisruptionBudgets, or None when no PDB watch is
        live (preemption then skips the violation preference entirely —
        distinct from an empty list, which means budgets verifiably do
        not exist)."""
        with self._lock:
            if not (self.watches_pdbs or self._pdbs):
                return None
            return list(self._pdbs.values())

    def pod_alive(self, pod: PodSpec) -> bool:
        """False once the watch saw the pod's deletion (by uid — a deleted
        and re-created pod has a fresh uid and is unaffected)."""
        with self._lock:
            return pod.uid in self._live_uids

    def counts_bound(self, uid: str) -> bool:
        """True when this cache charges the pod to a node — the failover
        reconciler compares this against cluster truth to find GHOST
        bindings (bind events the watch stream dropped)."""
        with self._lock:
            return uid in self._pod_nodes

    def live_uid_set(self) -> set[str]:
        """Every pod uid the cache believes alive (any phase, any node).
        A uid here that cluster truth lacks is a dropped deletion."""
        with self._lock:
            return set(self._live_uids)

    def pod_schedulable(self, pod: PodSpec) -> bool:
        """Should a popped queue entry actually be scheduled? False for
        deleted pods, pods the informer already counts as BOUND (a stale
        duplicate queue entry must not double-bind), and pods currently
        held by scheduling gates (a stale pre-gate-clear copy). The
        scheduler drops such entries at cycle start; the fresh watch event
        enqueued the current copy."""
        with self._lock:
            return (
                pod.uid in self._live_uids
                and pod.uid not in self._pod_nodes
                and pod.uid not in self._gated_uids
            )

    def snapshot(self) -> Snapshot:
        """Consistent view for one scheduling cycle. Cached until the next
        watch event; NodeInfo pod lists are copies, safe across threads."""
        with self._lock:
            if self._snapshot_cache is not None:
                return self._snapshot_cache
            # NodeInfo objects are REUSED across snapshots for nodes whose
            # CR / Node object / pod set did not change (the per-event
            # invalidations above): at 10^5 nodes, rebuilding every
            # NodeInfo (and copying every pod list) per watch event was
            # the dominant snapshot cost. The returned objects are
            # treated as immutable by every consumer.
            cache = self._ni_cache
            nodes = {}
            order: list[str] = []
            # _tpu_order is maintained sorted incrementally (bisect on CR
            # add/delete), so the candidate list below is born sorted and
            # Snapshot skips its O(N log N) re-sort per build.
            node_filter = self.node_filter_fn
            for name in self._tpu_order:
                tpu = self._tpus[name]
                # Once Node-informed, a CR whose Node is gone is a deleted
                # node with a not-yet-expired metrics object: never a
                # candidate (the round-1 gap: pods could bind to deleted
                # nodes on stale-but-fresh CRs).
                if self._node_informed and name not in self._nodes:
                    continue
                # Shard partition: a sharded stack's snapshot carries only
                # its own nodes (the filter is a pure function of the
                # slice/pool assignment, so the partition is identical
                # across rebuilds until shard_count itself changes).
                if node_filter is not None and not node_filter(name, tpu):
                    continue
                ni = cache.get(name)
                if ni is None or ni.tpu is not tpu:
                    ni = NodeInfo(
                        name=name,
                        tpu=tpu,
                        pods=list(self._pods_by_node.get(name, {}).values()),
                        node=self._nodes.get(name),
                    )
                    cache[name] = ni
                nodes[name] = ni
                order.append(name)
            snap = Snapshot(
                nodes,
                order=order,
                version=self._version,
                namespaces=self._namespaces or None,
                pvcs=(
                    self._pvcs
                    if (self.watches_pvcs or self._pvcs)
                    else None
                ),
                pvs=(
                    self._pvs
                    if (self.watches_pvs or self._pvs)
                    else None
                ),
            )
            snap.metrics_version = self._metrics_version
            # Admission-feed epoch AT BUILD, under the same lock: a
            # consumer that patches a cached vector from this snapshot
            # stamps this epoch, so events landing after the build are
            # re-applied on the next patch instead of silently skipped.
            snap.admission_epoch = self._admission_epoch
            if self.fence_fn is not None:
                try:
                    snap.fenced = frozenset(self.fence_fn())
                except Exception:  # noqa: BLE001 — a bad hook must not
                    pass           # wedge snapshot builds; fail open
            self._snapshot_cache = snap
            return snap

    def invalidate_snapshot(self) -> None:
        """An EXTERNAL schedulability input changed (the node health
        monitor's fence set): bump the snapshot version and drop the
        cached snapshot so the next cycle rebuilds it — and with it the
        per-snapshot admission-vector caches. metrics_version is NOT
        bumped: the fleet arrays are fence-independent (the veto rides
        the host_ok dynamics vector, not the static arrays)."""
        with self._lock:
            self._version += 1
            self._snapshot_cache = None


def _pod_claim_mib(pod: PodSpec) -> int:
    try:
        r = pod_request(pod)
    except LabelParseError:
        return 0
    return (r.hbm_per_chip // MIB) * r.effective_chips
