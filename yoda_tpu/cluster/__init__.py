"""Cluster backends: the in-memory fake API server and the informer cache.

The reference talks to a real Kubernetes API server through an UNCACHED
controller-runtime client — one HTTP round-trip per node per pod in both
Filter and Score plus a full List per pod (reference pkg/yoda/scheduler.go:70,
88,108; SURVEY.md §2 "Distributed communication backend"). The redesign:
a watch-driven informer cache is the only reader; scheduling cycles see a
consistent snapshot and never touch the API server (SURVEY.md §7 step 2).

``FakeCluster`` plays the API server for tests, demos, and benchmarks — the
"1-node kind cluster with fake SCV CR" strategy of BASELINE config 1 without
kind. ``KubeCluster`` is the real-cluster client on the same watch surface:
stdlib-HTTP list+watch loops (resourceVersion resume, 410 relist, backoff)
feeding the same Event stream, plus pods/binding and CR publish writes.
"""

from yoda_tpu.cluster.fake import Event, FakeCluster
from yoda_tpu.cluster.informer import InformerCache
from yoda_tpu.cluster.kube import KubeApiClient, KubeApiConfig, KubeCluster
from yoda_tpu.cluster.lease import LeaderElector

__all__ = [
    "Event",
    "FakeCluster",
    "InformerCache",
    "KubeApiClient",
    "KubeApiConfig",
    "KubeCluster",
    "LeaderElector",
]
