"""Per-cluster health: the ``UP -> DEGRADED -> PARTITIONED -> LOST`` ladder.

One monitor per cluster front. Health is SILENCE-driven: "contact" is any
proof the cluster front is alive — a successful probe round-trip, or a
watch event reaching the informer (the staleness clock,
``InformerCache.last_event_age_s``). The state is a function of how long
both signals have been silent:

    silence < degraded_after_s      UP          full member of placement
    silence < partitioned_after_s   DEGRADED    serves locally, but no
                                                NEW spillover routed to it
    silence < lost_after_s          PARTITIONED fenced: no bind may hit its
                                                API; its resync gate closes
    silence >= lost_after_s         LOST        as PARTITIONED, and /readyz
                                                stops waiting for it

Probe failures are classified with the SAME rules the bind retrier uses
(``cluster.retry.retryable_api_error``): a transient/transport failure
(timeout, connection refused, 5xx) is connectivity loss — silence keeps
accumulating toward PARTITIONED/LOST. A NON-retryable API error means the
server answered (reachable, so the silence clock resets) but is broken in
a way retrying won't fix — that pins the cluster at DEGRADED until a probe
succeeds cleanly.

Ticks and state reads are lock-cheap and never do I/O; ``probe()`` does
one round-trip and is only ever called from the federation's background
thread — health evaluation must never ride the serve loop.
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Callable

from yoda_tpu.cluster.retry import retryable_api_error

log = logging.getLogger("yoda_tpu.federation")


class ClusterState(enum.Enum):
    UP = "up"
    DEGRADED = "degraded"
    PARTITIONED = "partitioned"
    LOST = "lost"

    @property
    def severity(self) -> int:
        """Gauge encoding (yoda_cluster_state): 0=up 1=degraded
        2=partitioned 3=lost."""
        return _SEVERITY[self]

    @property
    def serving(self) -> bool:
        """May this cluster's own scheduler bind right now? DEGRADED still
        serves locally (the API answers — it is only excluded as a NEW
        spillover target); PARTITIONED/LOST are fenced."""
        return self in (ClusterState.UP, ClusterState.DEGRADED)


_SEVERITY = {
    ClusterState.UP: 0,
    ClusterState.DEGRADED: 1,
    ClusterState.PARTITIONED: 2,
    ClusterState.LOST: 3,
}


class ClusterHealthMonitor:
    """The health ladder for one cluster front.

    ``probe_fn`` does one cheap round-trip against the cluster's API and
    raises on failure (``KubeCluster.probe`` / ``FakeCluster.probe``);
    ``staleness_fn`` returns the watch-stream event age in seconds or None
    (``InformerCache.last_event_age_s``). ``on_transition(old, new)``
    fires under no lock whenever the state changes.
    """

    def __init__(
        self,
        name: str,
        *,
        probe_fn: "Callable[[], object] | None" = None,
        staleness_fn: "Callable[[], float | None] | None" = None,
        degraded_after_s: float = 10.0,
        partitioned_after_s: float = 30.0,
        lost_after_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
        on_transition: "Callable[[ClusterState, ClusterState], None] | None" = None,
    ) -> None:
        if not 0 < degraded_after_s <= partitioned_after_s <= lost_after_s:
            raise ValueError(
                "health thresholds must satisfy 0 < degraded <= "
                f"partitioned <= lost, got {degraded_after_s}/"
                f"{partitioned_after_s}/{lost_after_s}"
            )
        self.name = name
        self.probe_fn = probe_fn
        self.staleness_fn = staleness_fn
        self.degraded_after_s = degraded_after_s
        self.partitioned_after_s = partitioned_after_s
        self.lost_after_s = lost_after_s
        self.clock = clock
        self.on_transition = on_transition
        self.transitions = 0
        self._lock = threading.Lock()
        self._state = ClusterState.UP
        # Optimistic start: a freshly-built member gets the full degraded
        # window to prove itself before it is fenced out of anything.
        self._last_contact = clock()
        # Set by a NON-retryable probe error (server reachable but
        # broken): pins DEGRADED; cleared by the next clean probe.
        self._api_error = False

    # --- readers ---

    @property
    def state(self) -> ClusterState:
        with self._lock:
            return self._state

    def silence_s(self) -> float:
        """Seconds since the last proof of life, taking the FRESHER of
        probe contact and watch-event arrival (a healthy-but-quiet
        cluster stays UP on probes alone; a chatty watch keeps a cluster
        UP between probes)."""
        now = self.clock()
        with self._lock:
            silence = now - self._last_contact
        if self.staleness_fn is not None:
            age = self.staleness_fn()
            if age is not None:
                silence = min(silence, age)
        return max(silence, 0.0)

    # --- drivers ---

    def probe(self) -> ClusterState:
        """One probe round-trip, then a tick. Runs I/O — background thread
        only, never the serve loop."""
        if self.probe_fn is not None:
            try:
                self.probe_fn()
            except Exception as e:  # noqa: BLE001 — classification decides
                if retryable_api_error(e):
                    # Transient/transport failure: connectivity loss — no
                    # contact recorded, silence accumulates toward
                    # PARTITIONED/LOST.
                    log.debug(
                        "cluster %s: probe failed transiently (%s: %s)",
                        self.name, type(e).__name__, e,
                    )
                else:
                    # The server ANSWERED with a non-retryable error:
                    # reachable but broken. Contact resets the partition
                    # clock; the error pins DEGRADED.
                    with self._lock:
                        self._last_contact = self.clock()
                        self._api_error = True
                    log.warning(
                        "cluster %s: probe answered with a non-retryable "
                        "error (%s: %s); pinning DEGRADED", self.name,
                        type(e).__name__, e,
                    )
            else:
                with self._lock:
                    self._last_contact = self.clock()
                    self._api_error = False
        return self.tick()

    def record_contact(self) -> None:
        """External proof of life (e.g. a successful API write observed by
        the caller) — equivalent to a clean probe, without the round-trip."""
        with self._lock:
            self._last_contact = self.clock()
            self._api_error = False

    def tick(self) -> ClusterState:
        """Re-evaluate the ladder from current silence; fire
        ``on_transition`` if the state changed. Lock-cheap, no I/O."""
        silence = self.silence_s()
        with self._lock:
            if silence >= self.lost_after_s:
                new = ClusterState.LOST
            elif silence >= self.partitioned_after_s:
                new = ClusterState.PARTITIONED
            elif silence >= self.degraded_after_s or self._api_error:
                new = ClusterState.DEGRADED
            else:
                new = ClusterState.UP
            old, self._state = self._state, new
            if new is not old:
                self.transitions += 1
        if new is not old:
            log.warning(
                "cluster %s: health %s -> %s (%.1fs silent)",
                self.name, old.value, new.value, silence,
            )
            cb = self.on_transition
            if cb is not None:
                cb(old, new)
        return new
