"""Federated multi-cluster scheduling: several cluster fronts behind one
scheduler, with partition-tolerant degradation.

Every robustness layer before this PR (chaos hardening, the bind pipeline,
crash-safe failover) assumed a single cluster front — one watch stream, one
reconciler, one failure domain — so a partitioned or dead API server still
took the whole scheduler down with it. This package closes that gap:

- :mod:`yoda_tpu.federation.health` — a per-cluster health state machine
  (``UP -> DEGRADED -> PARTITIONED -> LOST``) driven by watch-stream
  staleness (``InformerCache.last_event_age_s``), probe deadlines, and the
  transient-error classifier in ``cluster/retry.py``.
- :mod:`yoda_tpu.federation.federation` — the ``Federation`` coordinator:
  one fully-wired stack (and therefore one PR 5 ``Reconciler``) per
  cluster front, per-cluster fencing that keeps a sick cluster's binds off
  the API without blocking any serve loop, spillover routing that migrates
  a gang the home cluster cannot fit WHOLE onto exactly one secondary
  cluster (all-or-nothing, never split), and rejoin handling that
  warm-starts a healed cluster through its reconciler's resync while the
  other clusters keep serving.

Assemble one with ``standalone.build_federation``.
"""

from yoda_tpu.federation.federation import Federation, FederationMember
from yoda_tpu.federation.health import ClusterHealthMonitor, ClusterState

__all__ = [
    "ClusterHealthMonitor",
    "ClusterState",
    "Federation",
    "FederationMember",
]
