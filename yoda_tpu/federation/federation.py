"""The Federation coordinator: several cluster fronts behind one scheduler.

Design rule (Pollux's separation, PAPERS.md): the expensive control actions
— health probes, remote-cluster resync, spillover migration — run on ONE
background thread (:meth:`Federation.run_forever`), never on any cluster's
serve loop. The serve loops only ever read a per-member fence (three cheap
predicate reads), so a partitioned remote degrades the federation to
local-only placement at full speed instead of serializing placement behind
dead-cluster timeouts.

Per-member invariants:

- **Fencing**: a member's scheduler may bind only while (a) the process
  holds leadership, (b) the member's health state is serving (UP or
  DEGRADED — PARTITIONED/LOST clusters make no API writes), and (c) the
  member's warm-start resync gate is open. The gate CLOSES when a cluster
  falls to PARTITIONED/LOST and re-opens only after its PR 5 reconciler
  resync completes on rejoin — no post-partition bind can precede the
  reconciliation of what happened during the silence.
- **Spillover** (home = ``members[0]``): a gang the home cluster provably
  cannot fit whole is migrated — all members, exactly one target cluster,
  never split — to the first healthy secondary whose snapshot fits it.
  Fit checks against each candidate reuse the cross-gang consumption-
  ledger discipline of the PR 2 joint pass: gangs spilled toward the same
  target within one pass see each other's simulated claims, so two gangs
  cannot both be promised the same remote chips. The home queue entries
  are held by the migrator for the whole evaluation+migration window
  (``SchedulingQueue.take_gang``), which is what makes "no cross-cluster
  double bind" structural rather than probabilistic.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from yoda_tpu.api.requests import LabelParseError, pod_request
from yoda_tpu.api.types import PodSpec, pod_admits_on
from yoda_tpu.federation.health import ClusterHealthMonitor, ClusterState
from yoda_tpu.framework.queue import QueuedPodInfo

log = logging.getLogger("yoda_tpu.federation")


def _always_leading() -> bool:
    return True


@dataclass
class FederationMember:
    """One cluster front: its API handle, its fully-wired scheduler stack
    (own informer, accountant, gang plugin, reconciler — cluster capacity
    is disjoint, so nothing is shared across members except the metrics
    registry), and its health monitor."""

    name: str
    cluster: object
    stack: object  # standalone.Stack
    health: ClusterHealthMonitor
    # The process-wide leader gate (cli wires the lease elector's
    # is_leader into every member): leadership is per-process, health is
    # per-cluster, and a member binds only under both.
    leader_fn: Callable[[], bool] = field(default=_always_leading)


class Federation:
    def __init__(
        self,
        members: "list[FederationMember]",
        *,
        metrics=None,
        spillover: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if not members:
            raise ValueError("a federation needs at least one member")
        names = [m.name for m in members]
        if len(set(names)) != len(names):
            raise ValueError(f"member names must be distinct: {names}")
        self.members = list(members)
        self.metrics = metrics
        self.spillover = spillover
        self.clock = clock
        self._lock = threading.Lock()
        # (member, pod key) home deletions that failed mid-migration; the
        # pod already lives whole on the target and its home queue entries
        # were consumed, so the lingering home copy is inert — retried
        # every health pass until the delete lands.
        self._retry_deletes: "list[tuple[FederationMember, str]]" = []
        self.spillover_gangs = 0
        for m in self.members:
            m.health.on_transition = self._make_on_transition(m)
            m.stack.scheduler.fence_fn = self._make_fence(m)
            if metrics is not None:
                metrics.cluster_state.set(
                    m.health.state.severity, cluster=m.name
                )

    # --- wiring ---

    @property
    def home(self) -> FederationMember:
        return self.members[0]

    def set_leader_gate(self, fn: Callable[[], bool]) -> None:
        """Wire the process-wide leader gate (cli: elector.is_leader) into
        every member's fence."""
        for m in self.members:
            m.leader_fn = fn

    def _make_fence(self, m: FederationMember) -> Callable[[], bool]:
        reconciler = m.stack.reconciler

        def admitted() -> bool:
            # True = this member may bind. Three cheap predicate reads —
            # the serve loop pays nothing for federation membership.
            return (
                m.leader_fn()
                and m.health.state.serving
                and reconciler.resynced.is_set()
            )

        return admitted

    def _make_on_transition(self, m: FederationMember):
        def on_transition(old: ClusterState, new: ClusterState) -> None:
            if self.metrics is not None:
                self.metrics.cluster_transitions.inc(cluster=m.name)
                self.metrics.cluster_state.set(new.severity, cluster=m.name)
            if old.serving and not new.serving:
                # Close the warm-start gate: whatever happened during the
                # silence (binds that landed, pods that died) must be
                # reconciled through the PR 5 resync path before this
                # cluster binds again. The fence reads this, so the
                # member's serve loop parks its queue without blocking.
                m.stack.reconciler.resynced.clear()

        return on_transition

    # --- readiness (the degraded-readiness contract) ---

    def ready(self) -> bool:
        """/readyz in federated mode: ready once the HOME cluster has
        resynced. A remote member must either be resynced too or be
        verifiably out (PARTITIONED/LOST) — a dead remote must never
        wedge the standby's readiness forever, while a reachable remote
        that simply has not resynced yet still holds readiness back (it
        will, within one health pass)."""
        if not self.home.stack.reconciler.resynced.is_set():
            return False
        for m in self.members[1:]:
            if m.stack.reconciler.resynced.is_set():
                continue
            if m.health.state in (ClusterState.PARTITIONED, ClusterState.LOST):
                continue
            return False
        return True

    def states(self) -> "dict[str, ClusterState]":
        return {m.name: m.health.state for m in self.members}

    # --- the background control loop ---

    def health_pass(self) -> "dict[str, ClusterState]":
        """Probe every member, run state transitions, warm-start members
        whose resync gate is closed but whose cluster answers again, and
        retry stale home deletions. All I/O lives here — this is the
        thread the serve loops never wait on."""
        for m in self.members:
            m.health.probe()
        self._drain_retry_deletes()
        for m in self.members:
            if not m.health.state.serving:
                continue
            if m.stack.reconciler.resynced.is_set():
                continue
            # Rejoin (or first boot): warm-start through the PR 5 path —
            # resync rebuilds reservations from cluster truth and adopts
            # or rolls back partially-bound gangs; the drift pass repairs
            # what the watch stream dropped during the silence. Failure
            # leaves the gate closed; retried next pass.
            try:
                m.stack.reconciler.resync()
                m.stack.reconciler.reconcile(relist=False)
            except Exception:  # noqa: BLE001 — cluster may have dropped again
                log.exception(
                    "cluster %s: rejoin resync failed; member stays fenced",
                    m.name,
                )
                continue
            m.stack.queue.move_all_to_active()
            log.info(
                "cluster %s: resynced and serving (state %s)",
                m.name, m.health.state.value,
            )
        if self.metrics is not None:
            for m in self.members:
                self.metrics.cluster_state.set(
                    m.health.state.severity, cluster=m.name
                )
        return self.states()

    def _drain_retry_deletes(self) -> None:
        with self._lock:
            pending, self._retry_deletes = self._retry_deletes, []
        kept: "list[tuple[FederationMember, str]]" = []
        for member, key in pending:
            # Fence-before-write (PR 3/4 discipline): no longer leader
            # for this cluster front means no API writes — the lingering
            # home copy is inert (no queue entry), so it keeps until
            # leadership returns or the new leader's drift reconciler
            # retires it.
            if member.stack.scheduler._fenced():
                kept.append((member, key))
                continue
            try:
                member.cluster.delete_pod(key)
            except Exception:  # noqa: BLE001 — keep retrying
                kept.append((member, key))
        if kept:
            with self._lock:
                self._retry_deletes.extend(kept)

    def run_forever(
        self, stop: threading.Event, *, period_s: float = 1.0
    ) -> None:
        """The federation control loop (cli puts this on one thread):
        health probes, rejoin resyncs, spillover migration. Exceptions are
        logged, never fatal — a control-plane hiccup must not take the
        serving schedulers with it."""
        while not stop.is_set():
            try:
                self.health_pass()
                self.spillover_pass()
            except Exception:  # noqa: BLE001 — control loop must survive
                log.exception("federation control pass failed; will retry")
            if stop.wait(period_s):
                return

    # --- spillover routing ---

    def spillover_pass(self) -> int:
        """Migrate gangs the home cluster provably cannot fit whole to the
        first healthy secondary that can. Returns the number of gangs
        migrated. All-or-nothing per gang: a gang is either untouched at
        home or whole on exactly one target — never split, never copied."""
        if not self.spillover or len(self.members) < 2:
            return 0
        home = self.home
        if (
            home.health.state is not ClusterState.UP
            or not home.stack.reconciler.resynced.is_set()
        ):
            # Spillover migrates pods OFF the home API: only meaningful
            # while home is fully healthy and reconciled.
            return 0
        pending = home.stack.queue.pending_gangs()
        if not pending:
            return 0
        migrated = 0
        # Per-target consumption ledgers for THIS pass (the PR 2 joint-
        # dispatch discipline, applied across clusters): gang g+1's fit
        # check against a target sees the chips gang g was just promised.
        sims: "dict[str, dict[str, int]]" = {
            m.name: {} for m in self.members
        }
        for gang in sorted(pending):
            count, min_attempts = pending[gang]
            if min_attempts < 1:
                continue  # has not failed a home cycle yet: not stuck
            status = home.stack.gang.gang_status(gang)
            if status is not None and (status[1] > 0 or status[2] > 0):
                continue  # members waiting at Permit or bound: mid-flight
            qpis = home.stack.queue.take_gang(gang)
            pods = [q.pod for q in qpis]
            size = _gang_size(pods)
            if size is None or len(pods) < size:
                # Not the whole gang in hand (members mid-cycle, or not
                # yet created): migrating a subset would split the gang
                # across clusters — the one thing spillover must never do.
                self._readd(home, qpis)
                continue
            if _gang_fits(home.stack, pods, sims[home.name]):
                # Home can fit it now (capacity freed since it parked):
                # local placement always beats migration.
                self._readd(home, qpis)
                continue
            target = None
            for m in self.members[1:]:
                if m.health.state is not ClusterState.UP:
                    continue  # sick clusters take no NEW work
                if m.stack.scheduler._fenced():
                    continue  # per-cluster leader fence: no split-brain
                if _gang_fits(m.stack, pods, sims[m.name]):
                    target = m
                    break
            if target is None:
                self._readd(home, qpis)
                continue
            if self._migrate(home, target, gang, qpis):
                migrated += 1
        return migrated

    @staticmethod
    def _readd(member: FederationMember, qpis: "list[QueuedPodInfo]") -> None:
        for q in qpis:
            member.stack.queue.readd(q)

    def _migrate(
        self,
        home: FederationMember,
        target: FederationMember,
        gang: str,
        qpis: "list[QueuedPodInfo]",
    ) -> bool:
        """Create the whole gang on ``target``, then retire the home
        copies. Create-first is safe because the home queue entries are in
        hand: even while both copies exist, home cannot bind (entries
        taken) and only target's scheduler can place the gang. A failed
        target create rolls the created copies back and returns the gang
        to the home queue untouched; a failed home delete is retried by
        the health pass (the lingering home copy has no queue entry, so
        it is inert — no double bind either way)."""
        tracer = getattr(self.metrics, "tracer", None)
        if tracer is not None and not tracer.enabled:
            tracer = None
        t0 = time.monotonic()
        pods = [q.pod for q in qpis]
        created: "list[PodSpec]" = []
        for pod in pods:
            clone = copy.deepcopy(pod)
            clone.node_name = None
            clone.phase = "Pending"
            clone.nominated_node_name = None
            try:
                target.cluster.create_pod(clone)
            except Exception:  # noqa: BLE001 — all-or-nothing
                log.exception(
                    "spillover: creating %s on cluster %s failed; rolling "
                    "back the migration of gang %s",
                    pod.key, target.name, gang,
                )
                for c in created:
                    try:
                        target.cluster.delete_pod(c.key)
                    except Exception:  # noqa: BLE001 — best effort
                        log.exception(
                            "spillover rollback: could not delete %s from "
                            "cluster %s", c.key, target.name,
                        )
                self._readd(home, qpis)
                if tracer is not None:
                    tracer.add(
                        f"gang:{gang}", "spillover",
                        t0=t0, t1=time.monotonic(), track="federation",
                        attrs={
                            "home": home.name, "target": target.name,
                            "members": len(pods), "aborted": "create-failed",
                        },
                    )
                return False
            created.append(clone)
        for pod in pods:
            try:
                home.cluster.delete_pod(pod.key)
            except Exception:  # noqa: BLE001 — retried by the health pass
                log.exception(
                    "spillover: deleting home copy %s failed; will retry",
                    pod.key,
                )
                with self._lock:
                    self._retry_deletes.append((home, pod.key))
        with self._lock:
            self.spillover_gangs += 1
        if self.metrics is not None:
            self.metrics.spillover_gangs.inc()
        if tracer is not None:
            # The gang's trace crosses clusters here: the span joins the
            # same trace_id its home-cluster cycles recorded under, so the
            # migrated story stays one connected walk.
            tracer.add(
                f"gang:{gang}", "spillover",
                t0=t0, t1=time.monotonic(), track="federation",
                attrs={
                    "home": home.name, "target": target.name,
                    "members": len(pods), "aborted": "",
                },
            )
        log.info(
            "spillover: migrated gang %s (%d member(s)) %s -> %s",
            gang, len(pods), home.name, target.name,
        )
        return True


def _gang_size(pods: "list[PodSpec]") -> "int | None":
    for pod in pods:
        try:
            spec = pod_request(pod).gang
        except LabelParseError:
            continue
        if spec is not None:
            return spec.size
    return None


def _gang_fits(stack, pods: "list[PodSpec]", sim: "dict[str, int]") -> bool:
    """Host-side whole-gang fit check against one cluster's snapshot, net
    of its accountant's reservations AND ``sim`` (chips already promised
    to earlier gangs this spillover pass — the shared consumption ledger).
    Mirrors the PR 2 joint fit gate's shape: the real multislice block
    planner for topology gangs, a greedy claimable walk for plain gangs.
    A PREDICATE, not a placement: the target's own scheduling pass
    re-validates everything, so a wrong "fits" degrades to a normal
    admission park on the target (and the gang spills again or returns);
    a wrong "does not fit" just delays migration one pass."""
    from yoda_tpu.plugins.yoda.filter_plugin import (
        available_chips,
        node_fits_resources,
    )

    reqs = []
    for pod in pods:
        try:
            req = pod_request(pod)
        except LabelParseError:
            return False
        if req.gang is None:
            return False
        reqs.append(req)
    if not reqs:
        return False
    snapshot = stack.informer.snapshot()
    reserved = stack.accountant.chips_by_node()
    spec = reqs[0].gang
    if spec.topology is not None:
        from yoda_tpu.plugins.yoda.topology import plan_multislice_placement

        req0 = reqs[0]
        chips = max(req0.effective_chips, 1)

        def host_ok(ni) -> bool:
            if ni.tpu is None:
                return False
            if not pod_admits_on(ni.node, pods[0])[0]:
                return False
            if not node_fits_resources(ni, pods[0], None)[0]:
                return False
            r = reserved.get(ni.name, 0) + sim.get(ni.name, 0)
            return available_chips(ni.tpu, req0, r) >= chips

        plan = plan_multislice_placement(
            snapshot,
            want_dims=spec.topology,
            slices=spec.slices,
            host_ok=host_ok,
        )
        if plan is None:
            return False
        for host in sorted(plan)[: len(pods)]:
            sim[host] = sim.get(host, 0) + chips
        return True
    # Plain gang: greedy claimable walk, one member at a time, each seeing
    # capacity net of the previously-walked members (and earlier gangs).
    tentative = dict(sim)
    for pod, req in zip(pods, reqs):
        chips = max(req.effective_chips, 1)
        best: "str | None" = None
        best_avail = -1
        for ni in snapshot.infos():
            if ni.tpu is None:
                continue
            if not pod_admits_on(ni.node, pod)[0]:
                continue
            if not node_fits_resources(ni, pod, None)[0]:
                continue
            r = reserved.get(ni.name, 0) + tentative.get(ni.name, 0)
            avail = available_chips(ni.tpu, req, r)
            if avail >= chips and avail > best_avail:
                best, best_avail = ni.name, avail
        if best is None:
            return False
        tentative[best] = tentative.get(best, 0) + chips
    sim.clear()
    sim.update(tentative)
    return True
