"""JAX compute core: the fleet as structure-of-arrays and the fused
filter+collect+score kernel.

This is the TPU-native redesign of the reference's per-(pod, node) hot path.
The reference does, per pod: one live API Get per node in Filter
(reference pkg/yoda/scheduler.go:70), a full SCV List + O(nodes x cards)
re-scan in collection (scheduler.go:88, collection/collection.go:30-57), and
another per-node Get in Score (scheduler.go:108) — with the three Fits
predicates recomputed three times per (pod, node) (SURVEY.md §3.2).

Here the informer snapshot is lowered once per metrics change into padded,
statically-shaped int32 arrays (``FleetArrays``), and one jitted XLA
computation evaluates feasibility, cluster maxima, weighted scores, and the
argmax selection for EVERY node in a single device launch
(``fused_filter_score``). Under ``yoda_tpu.parallel`` the same kernel shards
over a device mesh with the maxima becoming collectives.
"""

from yoda_tpu.ops.arrays import FleetArrays, MIB
from yoda_tpu.ops.kernel import (
    KernelRequest,
    KernelResult,
    fused_filter_score,
    REASON_OK,
    REASON_MESSAGES,
)

__all__ = [
    "FleetArrays",
    "MIB",
    "KernelRequest",
    "KernelResult",
    "fused_filter_score",
    "REASON_OK",
    "REASON_MESSAGES",
]
