"""The fused filter + collect + score + select kernel.

One jitted XLA computation replaces the reference's entire per-pod hot path
(Filter per node -> CollectMaxValues over all cards -> Score per node ->
NormalizeScore -> selection; reference pkg/yoda/scheduler.go:66-147):

    feasibility:  chips / HBM / clock / generation / freshness / reservation
                  predicates, vectorized over [nodes, chips]
    collection:   cluster maxima over feasible nodes' qualifying chips
                  (reference collection/collection.go:30-57) as masked maxes
    scoring:      weighted per-chip scores + allocation headroom + actual
                  free ratio (reference score/algorithm.go:29-88, with the
                  clock/MaxBandwidth normalization bug fixed)
    normalize:    min-max to [0,100] with the all-equal guard (reference
                  scheduler.go:122-147)
    select:       argmax with the deterministic name-order tiebreak

All arithmetic is int32 (HBM in MiB), bitwise identical to the Python plugin
path when HBM values are MiB-multiples. Request scalars are traced (not
static), so ONE compiled executable serves every pod at a given fleet bucket
shape.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Protocol

import jax
import jax.numpy as jnp
import numpy as np

from yoda_tpu.api.requests import TpuRequest
from yoda_tpu.config import SLICE_PROTECT_TIER, Weights
from yoda_tpu.ops.arrays import MIB, FleetArrays

REASON_OK = 0
REASON_NO_METRICS = 1
REASON_STALE = 2
REASON_GENERATION = 3
REASON_CHIPS = 4
REASON_HBM = 5
REASON_CLOCK = 6
REASON_RESERVED = 7
REASON_NODE = 8

REASON_MESSAGES = {
    REASON_NO_METRICS: "node has no TPU metrics",
    REASON_STALE: "node TPU metrics are stale",
    REASON_GENERATION: "node generation below requested",
    REASON_CHIPS: "not enough healthy chips",
    REASON_HBM: "not enough chips with free HBM",
    REASON_CLOCK: "not enough chips at requested clock",
    REASON_RESERVED: "qualifying chips reserved by in-flight pods",
    REASON_NODE: "node is cordoned, has untolerated taints, or does not "
    "match the pod's nodeSelector/required node affinity",
}

# The kernel's input schema: FleetArrays fields, split by shape. [N] node
# vectors vs [N, C] chip grids — the sharding layer row-shards both but
# needs the split to build PartitionSpecs. Single source of truth for
# fused_filter_score, yoda_tpu.parallel, and __graft_entry__.
NODE_KEYS = (
    "node_valid",
    "in_slice",
    "fresh",
    "host_ok",
    "generation_rank",
    "reserved_chips",
    "claimed_hbm_mib",
    "ext_chips",
)
CHIP_KEYS = (
    "chip_valid",
    "chip_healthy",
    "chip_used",
    "hbm_free_mib",
    "hbm_total_mib",
    "clock_mhz",
    "hbm_bandwidth",
    "tflops",
    "power_w",
)

# Split of NODE_KEYS for the device-resident path (DeviceFleetKernel):
# static per metrics version vs changing every scheduling cycle. DYN_KEYS
# order defines the rows of the packed [4, N] dynamics array.
STATIC_NODE_KEYS = ("node_valid", "in_slice", "generation_rank", "ext_chips")
DYN_KEYS = ("fresh", "reserved_chips", "claimed_hbm_mib", "host_ok")


def arrays_dict(arrays: "FleetArrays") -> dict:
    """Lower FleetArrays to the kernel's input dict."""
    return {k: getattr(arrays, k) for k in NODE_KEYS + CHIP_KEYS}


def result_from_outputs(arrays: "FleetArrays", outputs) -> "KernelResult":
    """Trim padded kernel outputs back to the real node count."""
    feasible, reasons, raw, final, best, claimable = outputs
    n = arrays.n_nodes
    best_i = int(best)
    return KernelResult(
        feasible=np.asarray(feasible)[:n],
        reasons=np.asarray(reasons)[:n],
        raw_scores=np.asarray(raw)[:n],
        scores=np.asarray(final)[:n],
        best_index=best_i if best_i < n else -1,
        claimable=np.asarray(claimable)[:n],
    )


@dataclass(frozen=True)
class KernelRequest:
    """Traced request scalars (one compiled kernel serves all requests)."""

    number: int          # effective chip count
    hbm_mib: int         # per-chip free-HBM requirement, MiB
    clock_mhz: int
    generation_rank: int
    wants_topology: int  # 1 when the pod is part of a topology gang

    @classmethod
    def from_request(cls, req: TpuRequest) -> "KernelRequest":
        return cls(
            number=req.effective_chips,
            # Ceil so sub-MiB requests stay a real constraint (chip free HBM
            # is floored to MiB, so both roundings are conservative).
            hbm_mib=-(-req.hbm_per_chip // MIB),
            clock_mhz=req.min_clock_mhz,
            generation_rank=req.min_generation_rank,
            wants_topology=int(
                req.gang is not None and req.gang.topology is not None
            ),
        )


@dataclass
class KernelResult:
    """Numpy views of the kernel outputs, trimmed to the real node count."""

    feasible: np.ndarray      # [N] bool
    reasons: np.ndarray       # [N] int32 (REASON_*)
    raw_scores: np.ndarray    # [N] int32 metric score, pre-normalization
    scores: np.ndarray        # [N] int32: minmax-normalized [0,100] + slice tier
    best_index: int           # -1 when nothing feasible
    # [N] int32 chips claimable right now (after the reservation/stale-freed
    # corrections) — what the gang batcher decrements host-side when placing
    # N members from ONE dispatch (plugins/yoda/batch.py).
    claimable: np.ndarray | None = None


def _norm(metric, maximum):
    return metric * 100 // maximum


def kernel_impl(
    a: dict, number, hbm_mib, clock_mhz, gen_rank, wants_topology, weights: Weights,
    xp=jnp,
):
    # ``xp`` selects the array namespace: jnp for the jitted XLA kernels,
    # numpy for the host-side fallback evaluator (NumpyFleetKernel) — one
    # body, so the dispatch fallback chain cannot drift from the device
    # semantics. Only namespace-portable ops are used (clip-at-zero is
    # spelled maximum(x, 0); numpy's clip signature differs across
    # versions).
    healthy = a["chip_valid"] & a["chip_healthy"]
    hbm_ok = healthy & (a["hbm_free_mib"] >= hbm_mib)
    clock_ok = healthy & (a["clock_mhz"] >= clock_mhz)
    qual = hbm_ok & clock_ok

    count_healthy = xp.sum(healthy, axis=1)
    count_hbm = xp.sum(hbm_ok, axis=1)
    count_clock = xp.sum(clock_ok, axis=1)
    count_qual = xp.sum(qual, axis=1)

    # Predicate parity with plugins/yoda/filter_plugin.py (and reference
    # filter.go): the hbm/clock counts are independent; the reservation
    # check mirrors filter_plugin.available_chips — chips already showing
    # consumption are excluded (exclusive-chip model), reservations not yet
    # visible in metrics are subtracted on top, and chips whose metrics
    # usage has no live claim behind it (freed by a delete/evict the agent
    # hasn't re-scraped — filter_plugin.stale_freed_chips) are added back
    # at full HBM, gated on qualifying-when-full.
    apparently_used = xp.sum(healthy & a["chip_used"], axis=1)
    # External-tenant chips (hardware-read usage no running pod explains —
    # api/types.py external_used_chips) are occupied-by-nobody: they absorb
    # no reservation (else a reservation on a genuinely-free chip would be
    # cancelled by a foreign tenant's usage and the node overcommits) and
    # they are never stale-freed (their usage is live truth, not a
    # deletion awaiting re-scrape).
    absorbable = xp.maximum(apparently_used - a["ext_chips"], 0)
    invisible = xp.maximum(a["reserved_chips"] - absorbable, 0)
    stale_freed = xp.maximum(absorbable - a["reserved_chips"], 0)
    # WHICH used chips are free is unknown: worst case, the external
    # chips and remaining live claims sit on qualifying used chips first
    # (filter_plugin.stale_freed_chips parity). External-tenant chips are
    # excluded from both the stale count and the candidates; hardware-read
    # chips whose usage was OURS stay creditable (a deleted pod's HBM
    # lingers in the counters until re-scrape — the same stale class, and
    # preemption's post-eviction simulation depends on the credit).
    # No-accounting callers neutralize both corrections by passing
    # reserved_chips == absorbable, i.e. apparently_used - ext_chips
    # (ops.arrays._neutral_reserved, used by dyn_packed / with_dynamic).
    freed_candidates = xp.sum(
        healthy
        & a["chip_used"]
        & (a["clock_mhz"] >= clock_mhz)
        & (a["hbm_total_mib"] >= hbm_mib),
        axis=1,
    )
    freed_candidates = xp.maximum(freed_candidates - a["ext_chips"], 0)
    freed = xp.minimum(
        stale_freed, xp.maximum(freed_candidates - a["reserved_chips"], 0)
    )
    count_avail = xp.sum(qual & ~a["chip_used"], axis=1)
    fits_chips = count_healthy >= number
    fits_hbm = (hbm_mib == 0) | ((count_hbm + freed) >= number)
    fits_clock = (clock_mhz == 0) | (count_clock >= number)
    fits_reserved = (count_avail + freed - invisible) >= number
    fits_gen = a["generation_rank"] >= gen_rank

    feasible = (
        a["node_valid"]
        & a["host_ok"]
        & a["fresh"]
        & fits_gen
        & fits_chips
        & fits_hbm
        & fits_clock
        & fits_reserved
    )

    # First failing predicate, in the same order the Python filter checks.
    reasons = xp.select(
        [
            ~a["node_valid"],
            ~a["host_ok"],
            ~a["fresh"],
            ~fits_gen,
            ~fits_chips,
            ~fits_hbm,
            ~fits_clock,
            ~fits_reserved,
        ],
        [
            REASON_NO_METRICS,
            REASON_NODE,
            REASON_STALE,
            REASON_GENERATION,
            REASON_CHIPS,
            REASON_HBM,
            REASON_CLOCK,
            REASON_RESERVED,
        ],
        REASON_OK,
    ).astype(xp.int32)

    # --- collection: maxima over feasible nodes' qualifying chips ---
    cmask = feasible[:, None] & qual

    def masked_max(x):
        return xp.maximum(xp.max(xp.where(cmask, x, 0)), 1)

    max_bw = masked_max(a["hbm_bandwidth"])
    max_clock = masked_max(a["clock_mhz"])
    max_tflops = masked_max(a["tflops"])
    max_power = masked_max(a["power_w"])
    max_free = masked_max(a["hbm_free_mib"])
    max_total = masked_max(a["hbm_total_mib"])

    # --- scoring ---
    w = weights
    chip_scores = (
        _norm(a["hbm_bandwidth"], max_bw) * w.hbm_bandwidth
        + _norm(a["clock_mhz"], max_clock) * w.clock
        + _norm(a["tflops"], max_tflops) * w.tflops
        + _norm(a["power_w"], max_power) * w.power
        + _norm(a["hbm_free_mib"], max_free) * w.hbm_free
        + _norm(a["hbm_total_mib"], max_total) * w.hbm_total
    )
    basic = xp.sum(xp.where(qual, chip_scores, 0), axis=1)

    free_sum = xp.sum(xp.where(a["chip_valid"], a["hbm_free_mib"], 0), axis=1)
    total_sum = xp.sum(xp.where(a["chip_valid"], a["hbm_total_mib"], 0), axis=1)
    safe_total = xp.maximum(total_sum, 1)
    actual = xp.where(total_sum > 0, free_sum * 100 // safe_total, 0) * w.actual
    headroom = xp.maximum(total_sum - a["claimed_hbm_mib"], 0)
    allocate = (
        xp.where(total_sum > 0, headroom * 100 // safe_total, 0) * w.allocate
    )

    raw = xp.where(feasible, basic + actual + allocate, 0).astype(xp.int32)

    # --- normalize (min-max to [0,100], all-equal guard) ---
    # Fillers must sit outside BOTH reductions' ranges: raw scores can be
    # negative under most-allocated's negated weights, so the `highest`
    # filler is -big, not -1 (a -1 filler would beat an all-negative
    # feasible set and crush the span).
    big = xp.iinfo(xp.int32).max
    lowest = xp.min(xp.where(feasible, raw, big))
    highest = xp.max(xp.where(feasible, raw, -big))
    lowest = xp.where(highest == lowest, lowest - 1, lowest)
    span = xp.maximum(highest - lowest, 1)
    normalized = xp.where(feasible, (raw - lowest) * 100 // span, 0).astype(xp.int32)

    # Anti-fragmentation tier (config.SLICE_PROTECT_TIER): added AFTER
    # normalization so the tier dominates without crushing within-tier
    # metric resolution. Non-topology pods strictly prefer hosts outside
    # multi-host ICI slices.
    protect = xp.where(
        (wants_topology == 0) & ~a["in_slice"],
        SLICE_PROTECT_TIER * w.slice_protect,
        0,
    ).astype(xp.int32)
    final = xp.where(feasible, normalized + protect, 0).astype(xp.int32)

    # --- select: highest score, ties -> later row (lexicographically
    # greatest name, matching the Python driver's (score, name) max).
    # argmax returns the FIRST max, so take it over the reversed array (no
    # `final * n + idx` combined key — that overflows int32 at the fleet
    # scales the sharded path serves). ---
    n = final.shape[0]
    masked = xp.where(feasible, final, -1)
    best = (n - 1 - xp.argmax(masked[::-1])).astype(xp.int32)
    best = xp.where(xp.any(feasible), best, -1)

    claimable = xp.maximum(count_avail + freed - invisible, 0).astype(xp.int32)

    return feasible, reasons, raw, final, best, claimable


# Single-device jit; yoda_tpu.parallel re-jits kernel_impl with node-axis
# shardings over a device mesh (the reductions become ICI collectives).
_kernel = functools.partial(jax.jit, static_argnames=("weights",))(kernel_impl)


def kernel_packed(static: dict, dyn, reqv, weights: Weights):
    """kernel_impl with transfer-minimal I/O: per-cycle node vectors arrive
    as ONE [4, N] int32 array (DYN_KEYS rows), request scalars as ONE [5]
    int32 vector, and all outputs leave as ONE [6, N] int32 array (rows:
    feasible, reasons, raw, final, best broadcast, claimable). Under a
    remote-device transport every host<->device transfer is a round trip, so
    the packing — not the FLOPs — is what makes the device path fast (the
    reference's analogous hot-loop cost was per-node API round trips,
    pkg/yoda/scheduler.go:70,108)."""
    a = dict(static)
    a["fresh"] = dyn[0].astype(bool)
    a["reserved_chips"] = dyn[1]
    a["claimed_hbm_mib"] = dyn[2]
    a["host_ok"] = dyn[3].astype(bool)
    feasible, reasons, raw, final, best, claimable = kernel_impl(
        a, reqv[0], reqv[1], reqv[2], reqv[3], reqv[4], weights=weights
    )
    return jnp.stack(
        [
            feasible.astype(jnp.int32),
            reasons,
            raw,
            final,
            jnp.full_like(final, best),
            claimable,
        ]
    )


# Module-level jit so every DeviceFleetKernel instance shares one compile
# cache (the cache keys include the committed device, bucket shape, and the
# hashable Weights).
_kernel_packed = functools.partial(jax.jit, static_argnames=("weights",))(
    kernel_packed
)


def kernel_packed_burst(static: dict, dyn, host_ok_k, reqs_k, weights: Weights):
    """K requests against ONE fleet snapshot in one dispatch — the
    multi-pod amortization of :func:`kernel_packed` (VERDICT r3 #1: the
    fleet scan and the dispatch floor are paid once per K-pod burst, not
    per pod). Shared per-cycle rows arrive as the same [4, N] dynamics
    array (row 3, the per-pod host_ok, is ignored); per-pod admission as
    ``host_ok_k`` [K, N] and requests as ``reqs_k`` [K, 5]. Output:
    [K, 6, N] — row layout per request as in :func:`kernel_packed`.
    vmap turns the per-request evaluation into one batched XLA program;
    the [N, C] chip grids are read once and broadcast over K."""

    def one(host_ok, reqv):
        a = dict(static)
        a["fresh"] = dyn[0].astype(bool)
        a["reserved_chips"] = dyn[1]
        a["claimed_hbm_mib"] = dyn[2]
        a["host_ok"] = host_ok.astype(bool)
        feasible, reasons, raw, final, best, claimable = kernel_impl(
            a, reqv[0], reqv[1], reqv[2], reqv[3], reqv[4], weights=weights
        )
        return jnp.stack(
            [
                feasible.astype(jnp.int32),
                reasons,
                raw,
                final,
                jnp.full_like(final, best),
                claimable,
            ]
        )

    return jax.vmap(one)(host_ok_k, reqs_k)


_kernel_packed_burst = functools.partial(jax.jit, static_argnames=("weights",))(
    kernel_packed_burst
)


def burst_bucket(k: int, minimum: int = 1) -> int:
    """Compile bucket for a K-request burst dispatch: the configured burst
    width while K fits (so singleton bursts and gang-fused dispatches share
    ONE compiled executable per fleet bucket), else the next power of two
    (a gang larger than batch_requests pays one extra compile per new
    bucket, amortized across every later gang of that scale)."""
    if k <= minimum:
        return max(minimum, 1)
    return 1 << max(k - 1, 1).bit_length()


def stack_joint_burst(
    host_ok_groups: "list[np.ndarray]",
    request_groups: "list[list[KernelRequest]]",
    minimum: int = 1,
) -> "tuple[np.ndarray, list[KernelRequest], list[int]]":
    """Stack G gangs' per-member rows into ONE padded burst (the cross-gang
    joint dispatch, ISSUE 2): group g's members occupy flat rows
    ``offsets[g]:offsets[g+1]`` of the returned [K, N] admission matrix and
    K-long request list, padded to :func:`burst_bucket` so joint,
    single-gang fused, and singleton-burst dispatches all share compiled
    executables per fleet bucket (padding rows carry all-False host_ok and
    are infeasible everywhere). ``host_ok_groups[g]`` is that gang's
    [k_g, N] admission rows. Returns (host_ok_k, requests, offsets) with
    ``len(offsets) == G + 1``."""
    flat_req: list[KernelRequest] = []
    offsets = [0]
    for reqs in request_groups:
        flat_req.extend(reqs)
        offsets.append(len(flat_req))
    if not flat_req:
        raise ValueError("stack_joint_burst needs at least one member row")
    k = burst_bucket(len(flat_req), minimum)
    n = int(host_ok_groups[0].shape[-1])
    host_ok_k = np.zeros((k, n), dtype=np.int32)
    row = 0
    for ok_rows in host_ok_groups:
        for r in np.asarray(ok_rows, dtype=np.int32).reshape(-1, n):
            host_ok_k[row] = r
            row += 1
    pad = KernelRequest(1, 0, 0, 0, 0)
    requests = flat_req + [pad] * (k - len(flat_req))
    return host_ok_k, requests, offsets


def evaluate_joint_via_burst(
    kern,
    dyn: np.ndarray,
    host_ok_groups: "list[np.ndarray]",
    request_groups: "list[list[KernelRequest]]",
    minimum: int = 1,
) -> "list[list[KernelResult]]":
    """Evaluate G gangs' members in ONE device round-trip through a
    kernel's ``evaluate_burst``: the per-gang rows are stacked into one
    padded burst (:func:`stack_joint_burst`) and the flat results are
    regrouped per gang. Shared by every burst-capable backend's
    ``evaluate_joint`` (XLA, mesh-sharded, Pallas/Mosaic)."""
    host_ok_k, requests, offsets = stack_joint_burst(
        host_ok_groups, request_groups, minimum
    )
    flat = kern.evaluate_burst(dyn, host_ok_k, requests)
    return [
        flat[offsets[g] : offsets[g + 1]] for g in range(len(request_groups))
    ]


def joint_fit_vectors(
    requests: "list[KernelRequest]", offsets: "list[int]"
) -> "tuple[np.ndarray, np.ndarray, np.ndarray]":
    """Per-row side inputs for :func:`joint_fit_scan`, aligned with a
    :func:`stack_joint_burst` stacking: chip demand per member row
    (``max(number, 1)``, the same floor the host fit gate applies), a
    group-start flag at each ``offsets[g]``, and a validity flag (padding
    rows are invalid: zero demand, never picked, and they cannot fail the
    — nonexistent — gang they pad)."""
    k = len(requests)
    chips = np.array([max(r.number, 1) for r in requests], dtype=np.int32)
    starts = np.zeros(k, dtype=np.int32)
    valid = np.zeros(k, dtype=np.int32)
    for g in range(len(offsets) - 1):
        starts[offsets[g]] = 1
    valid[: offsets[-1]] = 1
    chips *= valid
    return chips, starts, valid


def joint_fit_scan(
    feas_k, scores_k, claim_k, chips_k, starts_k, valid_k, xp=jnp
):
    """The host-side joint fit gate (plugins/yoda/batch.py
    ``_joint_gang_fits``, plain gangs) as a scan over the stacked member
    rows — the block-plan half of the fused decision kernel. Semantics are
    member-for-member identical to the Python loop: each gang starts from
    the chips consumed by every earlier FITTING gang, each member greedily
    claims the highest-scoring node with enough claimable chips left (ties
    -> first index, matching ``np.argmax``), and a gang with any
    unplaceable member consumes nothing. Inputs are the per-member
    [K, N] feasibility/score/claimable rows (kernel_packed_burst layout)
    plus the :func:`joint_fit_vectors` side inputs. Returns
    ``(picks, group_ok, sim)``: the node index each row claimed (-1 when
    it claimed nothing), the gang's running fit verdict after each row
    (read it at the gang's LAST row), and the [N] chips consumed by all
    fitting gangs. ``xp`` selects jnp (jax.lax.scan, jittable — the fused
    device program) or numpy (the host twin every fallback rung shares)."""
    if xp is jnp:
        feas_k = feas_k.astype(bool)
        starts_k = starts_k.astype(bool)
        valid_k = valid_k.astype(bool)
        n = feas_k.shape[-1]
        zeros = jnp.zeros(n, dtype=jnp.int32)

        def step(carry, xs):
            sim, tent, gok = carry
            feas, scores, claim, chips, start, valid = xs
            # Group boundary: commit the previous gang iff it fit, then
            # restart the tentative ledger from the committed state.
            sim = jnp.where(start & gok, tent, sim)
            gok = start | gok
            tent = jnp.where(start, sim, tent)
            ok = feas & ((claim.astype(jnp.int32) - tent) >= chips) & valid
            any_ok = ok.any()
            pick = jnp.argmax(jnp.where(ok, scores, -1)).astype(jnp.int32)
            take = any_ok & gok & valid
            tent = tent.at[pick].add(jnp.where(take, chips, 0))
            gok = gok & (any_ok | ~valid)
            return (sim, tent, gok), (jnp.where(take, pick, -1), gok)

        (sim, tent, gok), (picks, gok_k) = jax.lax.scan(
            step,
            (zeros, zeros, jnp.bool_(True)),
            (feas_k, scores_k, claim_k, chips_k, starts_k, valid_k),
        )
        sim = jnp.where(gok, tent, sim)
        return picks, gok_k, sim

    feas_k = np.asarray(feas_k).astype(bool)
    scores_k = np.asarray(scores_k)
    claim_k = np.asarray(claim_k).astype(np.int32)
    chips_k = np.asarray(chips_k)
    starts_k = np.asarray(starts_k).astype(bool)
    valid_k = np.asarray(valid_k).astype(bool)
    k, n = feas_k.shape
    sim = np.zeros(n, dtype=np.int32)
    tent = sim.copy()
    gok = True
    picks = np.full(k, -1, dtype=np.int32)
    gok_k = np.zeros(k, dtype=bool)
    for i in range(k):
        if starts_k[i]:
            if gok:
                sim = tent.copy()
            gok = True
            tent = sim.copy()
        if valid_k[i]:
            ok = feas_k[i] & ((claim_k[i] - tent) >= chips_k[i])
            any_ok = bool(ok.any())
            if any_ok and gok:
                pick = int(np.argmax(np.where(ok, scores_k[i], -1)))
                tent[pick] += int(chips_k[i])
                picks[i] = pick
            gok = gok and any_ok
        gok_k[i] = gok
    if gok:
        sim = tent.copy()
    return picks, gok_k, sim


def kernel_joint_plan(
    static: dict, dyn, host_ok_k, reqs_k, chips_k, starts_k, valid_k,
    weights: Weights,
):
    """Admission + score + block-plan in ONE program: the K-row burst
    evaluation (:func:`kernel_packed_burst`) feeding the joint fit scan
    (:func:`joint_fit_scan`) without leaving the device — this is the
    fused decision kernel that retires the last per-member Python loop on
    the gang serve path. Returns ``(packed [K, 6, N], picks [K],
    group_ok [K] int32, sim [N])``."""
    packed = kernel_packed_burst(static, dyn, host_ok_k, reqs_k, weights=weights)
    picks, gok_k, sim = joint_fit_scan(
        packed[:, 0].astype(bool), packed[:, 3], packed[:, 5],
        chips_k, starts_k, valid_k,
    )
    return packed, picks, gok_k.astype(jnp.int32), sim


_kernel_joint_plan = functools.partial(jax.jit, static_argnames=("weights",))(
    kernel_joint_plan
)


def evaluate_joint_plan_via_burst(
    kern,
    dyn: np.ndarray,
    host_ok_groups: "list[np.ndarray]",
    request_groups: "list[list[KernelRequest]]",
    minimum: int = 1,
) -> "tuple[list[list[KernelResult]], list[bool], list[np.ndarray]]":
    """Fit-gated joint evaluation for backends without a fully fused
    lowering (numpy fallback, Pallas, mesh-sharded): member rows go
    through the backend's own ``evaluate_burst`` (still one dispatch), and
    the block-plan scan runs host-side over the trimmed results — picks
    and fits are identical to the fused program's, since padding rows are
    infeasible everywhere. Returns ``(results_per_gang, fit_per_gang,
    picks_per_gang)``."""
    host_ok_k, requests, offsets = stack_joint_burst(
        host_ok_groups, request_groups, minimum
    )
    flat = kern.evaluate_burst(dyn, host_ok_k, requests)
    chips_k, starts_k, valid_k = joint_fit_vectors(requests, offsets)
    m = offsets[-1]
    picks, gok_k, _sim = joint_fit_scan(
        np.stack([r.feasible for r in flat[:m]]),
        np.stack([r.scores for r in flat[:m]]),
        np.stack([r.claimable for r in flat[:m]]),
        chips_k[:m], starts_k[:m], valid_k[:m],
        xp=np,
    )
    g_count = len(request_groups)
    grouped = [flat[offsets[g] : offsets[g + 1]] for g in range(g_count)]
    fits = [bool(gok_k[offsets[g + 1] - 1]) for g in range(g_count)]
    picks_g = [picks[offsets[g] : offsets[g + 1]] for g in range(g_count)]
    return grouped, fits, picks_g


def row_update_bucket(n_rows: int) -> int:
    """Compile bucket for a row-update scatter: next power of two, so a
    steady trickle of 1-3 changed rows per cycle shares one compiled
    scatter executable per fleet bucket."""
    return 1 << max(n_rows - 1, 0).bit_length()


def pack_row_update(
    arrays: "FleetArrays", rows: "list[int]", bucket: int
) -> "tuple[np.ndarray, dict]":
    """Host-side payload for an in-place static row update: the changed
    rows' STATIC_NODE_KEYS + CHIP_KEYS values, padded to ``bucket`` by
    repeating the first row (duplicate scatter indices carrying identical
    payloads are deterministic)."""
    idx = np.asarray(
        list(rows) + [rows[0]] * (bucket - len(rows)), dtype=np.int32
    )
    payload = {
        k: np.asarray(getattr(arrays, k))[idx]
        for k in STATIC_NODE_KEYS + CHIP_KEYS
    }
    return idx, payload


def apply_row_update(static: dict, idx, payload: dict):
    """Scatter changed rows into the device-resident static arrays. Jitted
    with the static dict DONATED (double-buffered in-place update: XLA
    reuses the old buffers instead of allocating a second fleet copy) —
    the pjit/donation discipline the device-resident fleet state rides
    (ops/resident.py FleetStateCache)."""
    return {k: static[k].at[idx].set(payload[k]) for k in static}


_row_update = functools.partial(jax.jit, donate_argnums=(0,))(apply_row_update)


def pack_request(request: "KernelRequest") -> np.ndarray:
    return np.array(
        [
            request.number,
            request.hbm_mib,
            request.clock_mhz,
            request.generation_rank,
            request.wants_topology,
        ],
        dtype=np.int32,
    )


def result_from_packed(names: list[str], packed: np.ndarray) -> KernelResult:
    """Unpack the [6, N] kernel_packed output, trimmed to the real fleet."""
    n = len(names)
    best = int(packed[4, 0]) if packed.shape[1] else -1
    return KernelResult(
        feasible=packed[0, :n].astype(bool),
        reasons=packed[1, :n],
        raw_scores=packed[2, :n],
        scores=packed[3, :n],
        best_index=best if 0 <= best < n else -1,
        claimable=packed[5, :n],
    )


class FleetKernelLike(Protocol):
    """The device-resident evaluator contract YodaBatch drives: upload the
    metrics-version-static arrays once, then evaluate per cycle with O(1)
    host<->device round trips. Satisfied by :class:`DeviceFleetKernel`
    (single device) and ``parallel.ShardedDeviceFleetKernel`` (mesh).
    Kernels may additionally offer ``update_rows(arrays, rows)`` — apply
    only the changed rows to the resident static state via a donated
    scatter instead of re-uploading the fleet (the incremental path
    FleetStateCache prefers; kernels without it get a full put_static)."""

    @property
    def names(self) -> list[str]: ...

    def put_static(self, arrays: FleetArrays) -> None: ...

    def evaluate(self, dyn: np.ndarray, request: "KernelRequest") -> "KernelResult": ...


class DeviceFleetKernel:
    """Single-device evaluator with device-resident fleet state.

    The [N, C] chip grids and static node vectors are uploaded once per
    metrics version (:meth:`put_static`); each :meth:`evaluate` then costs
    O(1) host<->device round trips regardless of fleet size — one packed
    dynamics upload, one request upload, one dispatch, one packed fetch.
    ``device=None`` runs on the process default device (the TPU under the
    driver); pass ``jax.devices("cpu")[0]`` to pin the kernel to host
    (sub-millisecond for small fleets, where accelerator dispatch latency
    dominates the integer math).
    """

    def __init__(self, weights: Weights, device=None) -> None:
        self.weights = weights
        self.device = device
        # Explicit device_put is only needed to steer placement AWAY from
        # the default device (e.g. pinning to host CPU while the process
        # default is the TPU); when the target IS the default, jit's own
        # dispatch transfers the numpy args without an extra round trip.
        self._needs_put = device is not None and device != jax.devices()[0]
        self._jitted = _kernel_packed
        self._static: dict | None = None
        self._names: list[str] = []

    @property
    def names(self) -> list[str]:
        return self._names

    def put_static(self, arrays: FleetArrays) -> None:
        """Upload the metrics-version-static arrays to the device."""
        host = {k: getattr(arrays, k) for k in STATIC_NODE_KEYS + CHIP_KEYS}
        self._static = (
            jax.device_put(host, self.device) if self.device is not None
            else jax.device_put(host)
        )
        self._names = list(arrays.names)

    def update_rows(self, arrays: FleetArrays, rows: "list[int]") -> None:
        """Apply ONLY the given (already re-filled) rows of ``arrays`` to
        the device-resident static state, in place via a donated scatter
        (:func:`apply_row_update`) — O(changed x C) host->device transfer
        instead of the O(N x C) full re-upload. The caller guarantees the
        fleet's names/buckets are unchanged since the last put_static
        (FleetStateCache re-stacks otherwise)."""
        if self._static is None or not rows:
            if self._static is None:
                self.put_static(arrays)
            return
        idx, payload = pack_row_update(
            arrays, rows, row_update_bucket(len(rows))
        )
        if self._needs_put:
            idx = jax.device_put(idx, self.device)
            payload = jax.device_put(payload, self.device)
        self._static = _row_update(self._static, idx, payload)

    def evaluate(
        self,
        dyn: np.ndarray,           # [4, N] int32, DYN_KEYS rows
        request: "KernelRequest",
    ) -> KernelResult:
        if self._static is None:
            raise RuntimeError("put_static() must run before evaluate()")
        reqv = pack_request(request)
        if self._needs_put:
            dyn = jax.device_put(dyn, self.device)
            reqv = jax.device_put(reqv, self.device)
        packed = self._jitted(self._static, dyn, reqv, weights=self.weights)
        return result_from_packed(self._names, np.asarray(packed))

    def evaluate_burst(
        self,
        dyn: np.ndarray,            # [4, N] int32 (row 3 unused)
        host_ok_k: np.ndarray,      # [K, N] int32/bool per-pod admission
        requests: "list[KernelRequest]",
    ) -> list[KernelResult]:
        """K requests in ONE dispatch (kernel_packed_burst). K is a compile
        bucket: callers pad to a fixed batch size (padding rows with
        host_ok all-False are infeasible everywhere and cost nothing
        host-side). Returns one trimmed KernelResult per request."""
        if self._static is None:
            raise RuntimeError("put_static() must run before evaluate_burst()")
        reqs_k = np.stack([pack_request(r) for r in requests])
        host_ok_k = host_ok_k.astype(np.int32)
        if self._needs_put:
            dyn = jax.device_put(dyn, self.device)
            host_ok_k = jax.device_put(host_ok_k, self.device)
            reqs_k = jax.device_put(reqs_k, self.device)
        packed = np.asarray(
            _kernel_packed_burst(
                self._static, dyn, host_ok_k, reqs_k, weights=self.weights
            )
        )
        return [
            result_from_packed(self._names, packed[k])
            for k in range(len(requests))
        ]

    def evaluate_joint(
        self,
        dyn: np.ndarray,
        host_ok_groups: "list[np.ndarray]",
        request_groups: "list[list[KernelRequest]]",
        minimum: int = 1,
    ) -> "list[list[KernelResult]]":
        """G gangs' member rows in ONE dispatch (cross-gang joint
        placement): stacked into one padded burst and regrouped per gang
        (:func:`evaluate_joint_via_burst`)."""
        return evaluate_joint_via_burst(
            self, dyn, host_ok_groups, request_groups, minimum
        )

    def evaluate_joint_plan(
        self,
        dyn: np.ndarray,
        host_ok_groups: "list[np.ndarray]",
        request_groups: "list[list[KernelRequest]]",
        minimum: int = 1,
    ) -> "tuple[list[list[KernelResult]], list[bool], list[np.ndarray]]":
        """G gangs' admission + scoring + cross-gang block-plan fit gate
        in ONE fused dispatch (:func:`kernel_joint_plan`): the host-side
        per-member fit loop becomes an in-program scan, so a joint gang
        cycle costs one round trip regardless of member count."""
        if self._static is None:
            raise RuntimeError(
                "put_static() must run before evaluate_joint_plan()"
            )
        host_ok_k, requests, offsets = stack_joint_burst(
            host_ok_groups, request_groups, minimum
        )
        chips_k, starts_k, valid_k = joint_fit_vectors(requests, offsets)
        reqs_k = np.stack([pack_request(r) for r in requests])
        host_ok_k = host_ok_k.astype(np.int32)
        if self._needs_put:
            dyn = jax.device_put(dyn, self.device)
            host_ok_k = jax.device_put(host_ok_k, self.device)
            reqs_k = jax.device_put(reqs_k, self.device)
            chips_k = jax.device_put(chips_k, self.device)
            starts_k = jax.device_put(starts_k, self.device)
            valid_k = jax.device_put(valid_k, self.device)
        packed, picks, gok_k, _sim = _kernel_joint_plan(
            self._static, dyn, host_ok_k, reqs_k, chips_k, starts_k,
            valid_k, weights=self.weights,
        )
        packed = np.asarray(packed)
        picks = np.asarray(picks)
        gok = np.asarray(gok_k).astype(bool)
        g_count = len(request_groups)
        grouped = [
            [
                result_from_packed(self._names, packed[k])
                for k in range(offsets[g], offsets[g + 1])
            ]
            for g in range(g_count)
        ]
        fits = [bool(gok[offsets[g + 1] - 1]) for g in range(g_count)]
        picks_g = [
            picks[offsets[g] : offsets[g + 1]] for g in range(g_count)
        ]
        return grouped, fits, picks_g


class NumpyFleetKernel:
    """Pure-host evaluator with the same output contract as the jitted
    kernels — the last rung of the dispatch fallback chain
    (plugins/yoda/batch.py): when the primary backend (Pallas/mesh/XLA
    device) and the XLA host kernel both fail, the scheduler keeps serving
    from this evaluator at numpy speed instead of crashing the loop. It
    shares :func:`kernel_impl` through the ``xp`` namespace parameter, so
    the math cannot drift from the device semantics; no jax machinery is
    touched on this path, which is the point — a wedged runtime or a
    lowering bug cannot take it down with the device kernels."""

    def __init__(self, weights: Weights) -> None:
        self.weights = weights
        self._static: dict | None = None
        self._names: list[str] = []

    @property
    def names(self) -> list[str]:
        return self._names

    def put_static(self, arrays: FleetArrays) -> None:
        # References, not copies: in-place row updates by the batch
        # plugin's incremental static refresh stay visible.
        self._static = {
            k: np.asarray(getattr(arrays, k))
            for k in STATIC_NODE_KEYS + CHIP_KEYS
        }
        self._names = list(arrays.names)

    def update_rows(self, arrays: FleetArrays, rows: "list[int]") -> None:
        """No device state: put_static stored REFERENCES into ``arrays``,
        so the caller's in-place row refills are already visible. Re-sync
        only if the arrays object itself was swapped."""
        if self._static is None or self._static.get("chip_valid") is not (
            arrays.chip_valid
        ):
            self.put_static(arrays)

    def _packed(self, dyn: np.ndarray, reqv: np.ndarray) -> np.ndarray:
        a = dict(self._static)
        a["fresh"] = np.asarray(dyn[0]).astype(bool)
        a["reserved_chips"] = np.asarray(dyn[1])
        a["claimed_hbm_mib"] = np.asarray(dyn[2])
        a["host_ok"] = np.asarray(dyn[3]).astype(bool)
        feasible, reasons, raw, final, best, claimable = kernel_impl(
            a,
            int(reqv[0]), int(reqv[1]), int(reqv[2]), int(reqv[3]),
            int(reqv[4]),
            weights=self.weights,
            xp=np,
        )
        return np.stack(
            [
                feasible.astype(np.int32),
                np.asarray(reasons, dtype=np.int32),
                np.asarray(raw, dtype=np.int32),
                np.asarray(final, dtype=np.int32),
                np.full_like(np.asarray(final, dtype=np.int32), best),
                np.asarray(claimable, dtype=np.int32),
            ]
        )

    def evaluate(self, dyn: np.ndarray, request: "KernelRequest") -> KernelResult:
        if self._static is None:
            raise RuntimeError("put_static() must run before evaluate()")
        return result_from_packed(self._names, self._packed(dyn, pack_request(request)))

    def evaluate_burst(
        self,
        dyn: np.ndarray,
        host_ok_k: np.ndarray,
        requests: "list[KernelRequest]",
    ) -> list[KernelResult]:
        """K requests, one host loop — no amortization to protect here
        (this path only runs in degraded mode), just the same results."""
        if self._static is None:
            raise RuntimeError("put_static() must run before evaluate_burst()")
        dyn = np.asarray(dyn)
        out: list[KernelResult] = []
        for k, request in enumerate(requests):
            row_dyn = np.stack(
                [dyn[0], dyn[1], dyn[2], np.asarray(host_ok_k[k], dtype=np.int32)]
            )
            out.append(
                result_from_packed(
                    self._names, self._packed(row_dyn, pack_request(request))
                )
            )
        return out

    def evaluate_joint(
        self,
        dyn: np.ndarray,
        host_ok_groups: "list[np.ndarray]",
        request_groups: "list[list[KernelRequest]]",
        minimum: int = 1,
    ) -> "list[list[KernelResult]]":
        return evaluate_joint_via_burst(
            self, dyn, host_ok_groups, request_groups, minimum
        )

    def evaluate_joint_plan(
        self,
        dyn: np.ndarray,
        host_ok_groups: "list[np.ndarray]",
        request_groups: "list[list[KernelRequest]]",
        minimum: int = 1,
    ) -> "tuple[list[list[KernelResult]], list[bool], list[np.ndarray]]":
        """Degraded-mode twin of the fused plan kernel: the numpy burst
        loop plus the host-side fit scan, same results contract."""
        return evaluate_joint_plan_via_burst(
            self, dyn, host_ok_groups, request_groups, minimum
        )


def fused_filter_score(
    arrays: FleetArrays,
    request: KernelRequest | TpuRequest,
    *,
    weights: Weights | None = None,
) -> KernelResult:
    if isinstance(request, TpuRequest):
        request = KernelRequest.from_request(request)
    outputs = _kernel(
        arrays_dict(arrays),
        jnp.int32(request.number),
        jnp.int32(request.hbm_mib),
        jnp.int32(request.clock_mhz),
        jnp.int32(request.generation_rank),
        jnp.int32(request.wants_topology),
        weights=weights or Weights(),
    )
    return result_from_outputs(arrays, outputs)
