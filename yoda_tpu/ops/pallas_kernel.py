"""Pallas TPU implementation of the fused fleet-evaluation hot op.

The XLA kernel (ops/kernel.py ``kernel_impl``) expresses the whole
filter+collect+score computation in jnp and lets XLA fuse it; this module
implements the same computation as a hand-written Pallas TPU kernel —
the "pallas for the hot ops" path for locally-attached TPUs at large
fleet scales, where owning the memory schedule matters:

- chip grids are laid out **[metric, C, N]** (metrics x chips x nodes)
  so the node axis rides the 128-wide lane dimension and the chip axis
  the 8-deep sublane dimension — per-node chip reductions become single
  sublane reductions on the VPU, and the fleet axis tiles cleanly;
- one ``pallas_call`` runs a **two-phase sequential grid**
  ``(phase, node-block)``: phase 0 walks the blocks accumulating the
  cluster-wide collection maxima (reference collection.go:30-57) into
  SMEM scalars — TPU grids execute sequentially, so scratch carries
  state across steps — and phase 1 re-walks the blocks computing
  feasibility, reasons, raw scores, and claimable chips against those
  maxima, all in VMEM;
- the cheap [N]-vector epilogue (min-max normalization, slice-protect
  tier, deterministic argmax) runs in numpy on the host, byte-identical
  to ``kernel_impl``'s tail.

Parity: bit-identical outputs to ``kernel_impl`` for all int32 inputs
(asserted by tests/test_pallas.py across randomized fleets). On non-TPU
backends the kernel runs in interpret mode (tests); on TPU it compiles
with Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from yoda_tpu.api.requests import TpuRequest
from yoda_tpu.config import SLICE_PROTECT_TIER, Weights
from yoda_tpu.ops.arrays import FleetArrays
from yoda_tpu.ops.kernel import (
    CHIP_KEYS,
    KernelRequest,
    KernelResult,
    NODE_KEYS,
    pack_request,
)

# Row order of the stacked [9, C, N] chip-grid input.
_CHIP_ROWS = CHIP_KEYS  # (valid, healthy, used, free, total, clock, bw, tflops, power)
_N_CHIP_ROWS = len(_CHIP_ROWS)
# Row order of the stacked node-vector input (exactly the 8 sublanes).
_NODE_ROWS = NODE_KEYS  # (valid, in_slice, fresh, host_ok, gen, reserved, claimed, ext)

_LANES = 128     # last-dim tile
_SUBLANES = 8    # int32 sublane tile

try:  # pallas is an optional heavyweight import; fail soft at import time
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
    # jax renamed TPUCompilerParams -> CompilerParams across releases;
    # support both so the kernel builds on either side of the rename.
    _CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
except Exception:  # pragma: no cover - environment without pallas
    HAVE_PALLAS = False


def _pad_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _eval_block(
    req, chips, nodes, host_ok, store, maxima, phase, j, *, weights: Weights
):
    """The shared per-block computation. ``req`` is this request's (5,)
    scalar vector; ``chips`` a (9, Cp, BN) VMEM block; ``nodes`` an
    (8, BN) VMEM block (its host_ok row is superseded by the ``host_ok``
    (BN,) mask — per-request in the burst variant); ``store(row, value)``
    writes one output row; ``maxima`` an (8,) SMEM scratch holding the six
    collection maxima across sequential grid steps of one request."""
    number = req[0]
    hbm_mib = req[1]
    clock_mhz = req[2]
    gen_rank = req[3]

    valid = chips[0] > 0
    healthy = valid & (chips[1] > 0)
    used = chips[2] > 0
    free = chips[3]
    total = chips[4]
    clock = chips[5]

    node_valid = nodes[0] > 0
    fresh = nodes[2] > 0
    node_gen = nodes[4]
    reserved = nodes[5]
    claimed = nodes[6]
    ext_chips = nodes[7]

    hbm_ok = healthy & (free >= hbm_mib)
    clock_ok = healthy & (clock >= clock_mhz)
    qual = hbm_ok & clock_ok

    def rows(x):  # chip-axis (sublane) reduction -> (BN,)
        return jnp.sum(x.astype(jnp.int32), axis=0)

    count_healthy = rows(healthy)
    count_hbm = rows(hbm_ok)
    count_clock = rows(clock_ok)
    apparently_used = rows(healthy & used)
    # kernel_impl parity: external-tenant chips absorb no reservation and
    # earn no stale-freed credit.
    absorbable = jnp.clip(apparently_used - ext_chips, 0)
    invisible = jnp.clip(reserved - absorbable, 0)
    stale_freed = jnp.clip(absorbable - reserved, 0)
    freed_candidates = jnp.clip(
        rows(healthy & used & (clock >= clock_mhz) & (total >= hbm_mib))
        - ext_chips,
        0,
    )
    freed = jnp.minimum(stale_freed, jnp.clip(freed_candidates - reserved, 0))
    count_avail = rows(qual & ~used)

    fits_chips = count_healthy >= number
    fits_hbm = (hbm_mib == 0) | ((count_hbm + freed) >= number)
    fits_clock = (clock_mhz == 0) | (count_clock >= number)
    fits_reserved = (count_avail + freed - invisible) >= number
    fits_gen = node_gen >= gen_rank

    feasible = (
        node_valid
        & host_ok
        & fresh
        & fits_gen
        & fits_chips
        & fits_hbm
        & fits_clock
        & fits_reserved
    )

    cmask = feasible[None, :] & qual

    @pl.when(phase == 0)
    def _collect():
        @pl.when(j == 0)
        def _init():
            for k in range(6):
                maxima[k] = 1  # masked_max clamps to >= 1 (kernel.py parity)

        # (metric index in chips stack, maxima slot)
        for slot, row in enumerate((6, 5, 7, 8, 3, 4)):  # bw, clock, tflops, power, free, total
            bm = jnp.max(jnp.where(cmask, chips[row], 0))
            maxima[slot] = jnp.maximum(maxima[slot], bm)

    @pl.when(phase == 1)
    def _score():
        w = weights
        max_bw = maxima[0]
        max_clock = maxima[1]
        max_tflops = maxima[2]
        max_power = maxima[3]
        max_free = maxima[4]
        max_total = maxima[5]

        def norm(x, mx):
            return x * 100 // jnp.maximum(mx, 1)

        chip_scores = (
            norm(chips[6], max_bw) * w.hbm_bandwidth
            + norm(clock, max_clock) * w.clock
            + norm(chips[7], max_tflops) * w.tflops
            + norm(chips[8], max_power) * w.power
            + norm(free, max_free) * w.hbm_free
            + norm(total, max_total) * w.hbm_total
        )
        basic = jnp.sum(jnp.where(qual, chip_scores, 0), axis=0)

        free_sum = jnp.sum(jnp.where(valid, free, 0), axis=0)
        total_sum = jnp.sum(jnp.where(valid, total, 0), axis=0)
        safe_total = jnp.maximum(total_sum, 1)
        actual = (
            jnp.where(total_sum > 0, free_sum * 100 // safe_total, 0)
            * w.actual
        )
        headroom = jnp.clip(total_sum - claimed, 0)
        allocate = (
            jnp.where(total_sum > 0, headroom * 100 // safe_total, 0)
            * w.allocate
        )
        raw = jnp.where(feasible, basic + actual + allocate, 0).astype(
            jnp.int32
        )

        # First failing predicate, reason codes from ops.kernel. A
        # where-chain, not jnp.select: Mosaic's select lowering argmaxes
        # over the condition stack, unimplemented for int32 lanes — the
        # reversed chain gives the same first-match semantics.
        reasons = jnp.zeros_like(raw)
        for cond, code in reversed(
            [
                (~node_valid, 1),
                (~host_ok, 8),
                (~fresh, 2),
                (~fits_gen, 3),
                (~fits_chips, 4),
                (~fits_hbm, 5),
                (~fits_clock, 6),
                (~fits_reserved, 7),
            ]
        ):
            reasons = jnp.where(cond, code, reasons)
        reasons = reasons.astype(jnp.int32)

        claimable = jnp.clip(count_avail + freed - invisible, 0).astype(
            jnp.int32
        )
        store(0, feasible.astype(jnp.int32))
        store(1, reasons)
        store(2, raw)
        store(3, claimable)
        for r in range(4, 8):
            store(r, jnp.zeros_like(raw))


def _kernel_body(req, chips, nodes, out, maxima, *, weights: Weights):
    """Single-request body: grid (phase, node-block)."""
    phase = pl.program_id(0)
    j = pl.program_id(1)

    def store(r, v):
        out[r] = v

    _eval_block(
        req, chips, nodes, nodes[3] > 0, store, maxima, phase, j,
        weights=weights,
    )


def _kernel_body_burst(reqs, chips, nodes, host_ok, out, maxima, *, weights: Weights):
    """K-request body: grid (request, phase, node-block). The chip grids
    and shared node rows are revisited per request (they stay VMEM-resident
    across the sequential TPU grid); ``host_ok`` carries each request's own
    admission row (in sublane 0 of its (1, 8, BN) block — the sublane axis
    exists only to satisfy Mosaic's (8, 128) tiling, see
    ``_pallas_eval_burst``), and the SMEM maxima re-initialize at each
    request's phase-0 first block, so every request gets its own collection
    pass — bit-identical to K independent single-request dispatches."""
    k = pl.program_id(0)
    phase = pl.program_id(1)
    j = pl.program_id(2)

    def store(r, v):
        out[0, r] = v

    _eval_block(
        reqs[k], chips, nodes, host_ok[0, 0] > 0, store, maxima, phase, j,
        weights=weights,
    )


@functools.partial(
    jax.jit, static_argnames=("weights", "block_n", "interpret")
)
def _pallas_eval(chips, nodes, reqv, *, weights: Weights, block_n: int, interpret: bool):
    """chips [9, Cp, Np] int32, nodes [8, Np] int32, reqv (5,) int32 ->
    out [8, Np] int32 (rows: feasible, reasons, raw, claimable)."""
    n_rows, cp, n_pad = chips.shape
    nb = n_pad // block_n
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(2, nb),
        in_specs=[
            pl.BlockSpec(
                (n_rows, cp, block_n), lambda p, j, req: (0, 0, j)
            ),
            pl.BlockSpec((8, block_n), lambda p, j, req: (0, j)),
        ],
        out_specs=pl.BlockSpec((8, block_n), lambda p, j, req: (0, j)),
        scratch_shapes=[pltpu.SMEM((8,), jnp.int32)],
    )
    return pl.pallas_call(
        functools.partial(_kernel_body, weights=weights),
        out_shape=jax.ShapeDtypeStruct((8, n_pad), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
    )(reqv, chips, nodes)


@functools.partial(
    jax.jit, static_argnames=("weights", "block_n", "interpret")
)
def _pallas_eval_burst(
    chips, nodes, host_ok_k, reqs_k, *, weights: Weights, block_n: int, interpret: bool
):
    """K requests against one fleet in ONE Mosaic dispatch (VERDICT r4 #2):
    chips [9, Cp, Np] int32, nodes [8, Np] int32 (shared rows; its host_ok
    row is ignored), host_ok_k [K, Np] int32 per-request admission, reqs_k
    [K, 5] int32 -> out [K, 8, Np] int32. The request axis is an OUTER
    grid dimension, so the two-phase collection runs per request over the
    same VMEM-resident fleet blocks — the kernel_packed_burst analog with
    an explicit grid instead of vmap.

    The per-request admission rows are lowered as [K, 8, Np] with the real
    row in sublane 0: Mosaic requires every block's LAST TWO dims to tile
    (8, 128) (or equal the array's), and the natural (1, block_n) slice of
    a [K, Np] array violates the sublane half — the exact lowering failure
    BENCH_r05 recorded as ``pallas_burst_error``. The single-request path
    never hit it because its node stack is already 8 sublanes deep; this
    pads the burst's admission input the same way (7 dead sublanes per
    request, ~0.1% of the chip-grid bytes)."""
    n_rows, cp, n_pad = chips.shape
    k_pad = reqs_k.shape[0]
    nb = n_pad // block_n
    host_ok_3d = jnp.zeros(
        (k_pad, _SUBLANES, n_pad), host_ok_k.dtype
    ).at[:, 0, :].set(host_ok_k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(k_pad, 2, nb),
        in_specs=[
            pl.BlockSpec(
                (n_rows, cp, block_n), lambda k, p, j, reqs: (0, 0, j)
            ),
            pl.BlockSpec((8, block_n), lambda k, p, j, reqs: (0, j)),
            pl.BlockSpec(
                (1, _SUBLANES, block_n), lambda k, p, j, reqs: (k, 0, j)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 8, block_n), lambda k, p, j, reqs: (k, 0, j)
        ),
        scratch_shapes=[pltpu.SMEM((8,), jnp.int32)],
    )
    return pl.pallas_call(
        functools.partial(_kernel_body_burst, weights=weights),
        out_shape=jax.ShapeDtypeStruct((k_pad, 8, n_pad), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")
        ),
    )(reqs_k, chips, nodes, host_ok_3d)


def _stack_inputs(a: dict, *, block_n: int) -> tuple[np.ndarray, np.ndarray]:
    """Lower the kernel input dict to the pallas layout: chips transposed
    to [9, Cp, Np] (nodes on lanes), node vectors stacked to [8, Np]."""
    n, c = a["chip_valid"].shape
    n_pad = _pad_to(max(n, 1), block_n)
    c_pad = _pad_to(max(c, 1), _SUBLANES)
    chips = np.zeros((_N_CHIP_ROWS, c_pad, n_pad), dtype=np.int32)
    for i, k in enumerate(_CHIP_ROWS):
        chips[i, :c, :n] = np.asarray(a[k], dtype=np.int32).T
    nodes = np.zeros((8, n_pad), dtype=np.int32)
    for i, k in enumerate(_NODE_ROWS):
        nodes[i, :n] = np.asarray(a[k], dtype=np.int32)
    return chips, nodes


def _epilogue(
    arrays: FleetArrays, out: np.ndarray, request: KernelRequest, weights: Weights
) -> KernelResult:
    """Host-side [N]-vector tail: min-max normalize, slice-protect tier,
    deterministic (score, name-order) argmax — kernel_impl parity."""
    n = arrays.n_nodes
    feasible = out[0, :n].astype(bool)
    reasons = out[1, :n]
    raw = out[2, :n].astype(np.int64)
    claimable = out[3, :n]

    big = np.iinfo(np.int32).max
    lowest = int(np.min(np.where(feasible, raw, big))) if n else 0
    highest = int(np.max(np.where(feasible, raw, -big))) if n else 0
    if highest == lowest:
        lowest -= 1
    span = max(highest - lowest, 1)
    normalized = np.where(feasible, (raw - lowest) * 100 // span, 0)
    in_slice = np.asarray(arrays.in_slice[:n], dtype=bool)
    protect = np.where(
        (request.wants_topology == 0) & ~in_slice,
        SLICE_PROTECT_TIER * weights.slice_protect,
        0,
    )
    final = np.where(feasible, normalized + protect, 0).astype(np.int32)

    best = -1
    if feasible.any():
        masked = np.where(feasible, final, -1)
        best = int(n - 1 - np.argmax(masked[::-1]))
    return KernelResult(
        feasible=feasible,
        reasons=reasons,
        raw_scores=raw.astype(np.int32),
        scores=final,
        best_index=best,
        claimable=claimable,
    )


class PallasFleetKernel:
    """FleetKernelLike backed by the Pallas TPU kernel.

    ``put_static`` lowers and uploads the stacked chip grids once per
    metrics version; ``evaluate`` merges the per-cycle dynamics rows into
    the node stack, dispatches the two-phase kernel, and finishes with the
    numpy epilogue. ``interpret=None`` auto-selects: compiled Mosaic on a
    TPU default backend, interpret mode elsewhere (tests/CPU)."""

    def __init__(
        self,
        weights: Weights,
        *,
        block_n: int = 512,
        interpret: bool | None = None,
    ) -> None:
        if not HAVE_PALLAS:
            raise RuntimeError("pallas is unavailable in this environment")
        self.weights = weights
        self.block_n = max(_LANES, _pad_to(block_n, _LANES))
        if interpret is None:
            # A broken accelerator runtime (libtpu init failure, dead
            # tunnel) must not take backend auto-selection down with it:
            # fall to interpret mode — correct, slow, and survivable; the
            # batch plugin's dispatch fallback chain demotes to the XLA
            # host kernel if even that fails.
            try:
                interpret = jax.default_backend() != "tpu"
            except Exception:  # noqa: BLE001 — degraded, not fatal
                interpret = True
        self.interpret = interpret
        self._chips = None
        self._nodes_static: np.ndarray | None = None
        self._names: list[str] = []
        self._arrays: FleetArrays | None = None

    @property
    def names(self) -> list[str]:
        return self._names

    def put_static(self, arrays: FleetArrays) -> None:
        from yoda_tpu.ops.kernel import arrays_dict

        a = arrays_dict(arrays)
        chips, nodes = _stack_inputs(a, block_n=self.block_n)
        self._chips = jax.device_put(chips)
        self._nodes_static = nodes
        self._names = list(arrays.names)
        self._arrays = arrays

    def evaluate(self, dyn: np.ndarray, request: KernelRequest) -> KernelResult:
        if self._chips is None or self._arrays is None:
            raise RuntimeError("put_static() must run before evaluate()")
        n = len(self._names)
        nodes = self._nodes_static.copy()
        # DYN_KEYS rows -> node-stack rows (fresh, reserved, claimed, host_ok).
        nodes[2, :n] = dyn[0, :n]
        nodes[5, :n] = dyn[1, :n]
        nodes[6, :n] = dyn[2, :n]
        nodes[3, :n] = dyn[3, :n]
        reqv = pack_request(request)  # single source of the scalar layout
        out = _pallas_eval(
            self._chips,
            nodes,
            reqv,
            weights=self.weights,
            block_n=self.block_n,
            interpret=self.interpret,
        )
        return _epilogue(self._arrays, np.asarray(out), request, self.weights)

    def evaluate_burst(
        self,
        dyn: np.ndarray,            # [4, N] int32 (row 3, host_ok, unused)
        host_ok_k: np.ndarray,      # [K, N] per-request admission
        requests: "list[KernelRequest]",
    ) -> "list[KernelResult]":
        """K requests in ONE Mosaic dispatch — the Pallas analog of
        DeviceFleetKernel.evaluate_burst (same contract: K is the caller's
        compile bucket, padding rows carry all-False host_ok). Closes the
        kernel_backend=pallas + batch_requests composition gap (pre-r5 the
        batcher silently fell back to per-pod dispatch)."""
        if self._chips is None or self._arrays is None:
            raise RuntimeError("put_static() must run before evaluate_burst()")
        n = len(self._names)
        n_pad = self._nodes_static.shape[1]
        nodes = self._nodes_static.copy()
        nodes[2, :n] = dyn[0, :n]
        nodes[5, :n] = dyn[1, :n]
        nodes[6, :n] = dyn[2, :n]
        k = len(requests)
        hk = np.zeros((k, n_pad), dtype=np.int32)
        hk[:, : host_ok_k.shape[1]] = np.asarray(host_ok_k, dtype=np.int32)[
            :, :n_pad
        ]
        reqs_k = np.stack([pack_request(r) for r in requests])
        out = np.asarray(
            _pallas_eval_burst(
                self._chips,
                nodes,
                hk,
                reqs_k,
                weights=self.weights,
                block_n=self.block_n,
                interpret=self.interpret,
            )
        )
        return [
            _epilogue(self._arrays, out[i], requests[i], self.weights)
            for i in range(k)
        ]

    def evaluate_joint(
        self,
        dyn: np.ndarray,
        host_ok_groups: "list[np.ndarray]",
        request_groups: "list[list[KernelRequest]]",
        minimum: int = 1,
    ) -> "list[list[KernelResult]]":
        """G gangs' member rows in ONE Mosaic dispatch (cross-gang joint
        placement): the per-gang admission rows stack into one padded
        burst — reusing ``evaluate_burst``'s [K, 8, Np] sublane padding,
        the BENCH_r05 lowering fix — and the flat results regroup per
        gang (ops.kernel.evaluate_joint_via_burst)."""
        from yoda_tpu.ops.kernel import evaluate_joint_via_burst

        return evaluate_joint_via_burst(
            self, dyn, host_ok_groups, request_groups, minimum
        )

    def evaluate_joint_plan(
        self,
        dyn: np.ndarray,
        host_ok_groups: "list[np.ndarray]",
        request_groups: "list[list[KernelRequest]]",
        minimum: int = 1,
    ) -> "tuple[list[list[KernelResult]], list[bool], list[np.ndarray]]":
        """Fit-gated joint pass on the Mosaic backend: member rows through
        the Pallas burst program (one dispatch), block-plan scan host-side
        (ops.kernel.evaluate_joint_plan_via_burst) — the same split as
        this backend's ``_epilogue``, which already finishes selection on
        host."""
        from yoda_tpu.ops.kernel import evaluate_joint_plan_via_burst

        return evaluate_joint_plan_via_burst(
            self, dyn, host_ok_groups, request_groups, minimum
        )


def fused_filter_score_pallas(
    arrays: FleetArrays,
    request: KernelRequest | TpuRequest,
    *,
    weights: Weights | None = None,
    block_n: int = 512,
    interpret: bool | None = None,
) -> KernelResult:
    """One-shot wrapper (tests / parity checks): lower, dispatch, epilogue."""
    if isinstance(request, TpuRequest):
        request = KernelRequest.from_request(request)
    weights = weights or Weights()
    kern = PallasFleetKernel(weights, block_n=block_n, interpret=interpret)
    kern.put_static(arrays)
    # The arrays' OWN dynamic rows, verbatim (dyn_packed would recompute
    # freshness and neutralize reservations — different semantics than
    # evaluating the arrays as-is, which is what parity tests compare).
    dyn = np.stack(
        [
            np.asarray(arrays.fresh, dtype=np.int32),
            np.asarray(arrays.reserved_chips, dtype=np.int32),
            np.asarray(arrays.claimed_hbm_mib, dtype=np.int32),
            np.asarray(arrays.host_ok, dtype=np.int32),
        ]
    )
    return kern.evaluate(dyn, request)
