"""Fleet metrics as padded structure-of-arrays for the fused kernel.

Shapes are static per (node-bucket, chip-bucket) so XLA compiles once per
bucket and reuses the executable as the fleet grows. HBM is stored in MiB as
int32 (2^31 MiB = 2 PiB max — ample) so all score arithmetic is exact integer
math matching the Python plugin semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np

from yoda_tpu.framework.interfaces import Snapshot

MIB = 1 << 20

_MIN_NODE_BUCKET = 8
_MIN_CHIP_BUCKET = 4


def _bucket(n: int, minimum: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def bucket_rows(n_nodes: int, *, multiple_of: int = 1) -> int:
    """Padded row count for a fleet of ``n_nodes``: power-of-two growth from
    the minimum bucket, rounded up to ``multiple_of`` (the mesh-sharded
    kernel needs rows divisible by the mesh size —
    parallel.ShardedDeviceFleetKernel)."""
    b = _bucket(max(n_nodes, 1), _MIN_NODE_BUCKET)
    return -(-b // multiple_of) * multiple_of


@dataclass
class FleetArrays:
    """Structure-of-arrays view of the fleet. ``names[i]`` maps row i back to
    the node; rows >= len(names) are padding (valid=False)."""

    names: list[str]
    # [N] node-level
    node_valid: np.ndarray        # bool
    generation_rank: np.ndarray   # int32
    in_slice: np.ndarray          # bool (host belongs to a multi-host ICI slice)
    fresh: np.ndarray             # bool
    host_ok: np.ndarray           # bool: Node-object admission (not cordoned;
                                  # per-pod taint/toleration results override
                                  # this via the dyn vector at evaluation time)
    last_updated: np.ndarray      # float64 unix (for dynamic re-freshness)
    reserved_chips: np.ndarray    # int32 (chips held by in-flight pods)
    claimed_hbm_mib: np.ndarray   # int32 (HBM claimed by placed pods' labels)
    ext_chips: np.ndarray         # int32 (hardware-read used chips with no
                                  # running pod behind them — external
                                  # tenants; absorb no reservation, earn no
                                  # stale-freed credit)
    # [N, C] chip-level
    chip_valid: np.ndarray        # bool (false for padding columns)
    chip_healthy: np.ndarray      # bool
    chip_used: np.ndarray         # bool (byte-exact hbm_free < hbm_total)
    hbm_free_mib: np.ndarray      # int32
    hbm_total_mib: np.ndarray     # int32
    clock_mhz: np.ndarray         # int32
    hbm_bandwidth: np.ndarray     # int32
    tflops: np.ndarray            # int32
    power_w: np.ndarray           # int32

    @property
    def n_nodes(self) -> int:
        return len(self.names)

    def _apparently_used(self) -> np.ndarray:
        """Per-node count of healthy chips whose metrics show consumption
        (kernel_impl's apparently_used, host-side)."""
        return np.sum(self.chip_healthy & self.chip_used, axis=1).astype(np.int32)

    def _neutral_reserved(self) -> np.ndarray:
        """The reserved_chips pin that makes BOTH reservation corrections
        vanish when no accounting source exists: metrics-visible usage
        minus the external-tenant chips (kernel_impl: absorbable usage).
        Pinning to raw apparently_used would leave invisible == ext_chips
        and double-subtract externally-used chips (already outside
        ``unused``). :meth:`_neutral_reserved_row` is the per-row form
        (incremental updates) — one formula, two shapes."""
        return np.clip(self._apparently_used() - self.ext_chips, 0, None).astype(
            np.int32
        )

    def _neutral_reserved_row(self, i: int) -> int:
        """Row form of :meth:`_neutral_reserved` for fill_row's O(C)
        incremental path."""
        used = int(np.sum(self.chip_healthy[i] & self.chip_used[i]))
        return max(used - int(self.ext_chips[i]), 0)

    @property
    def padded_shape(self) -> tuple[int, int]:
        return self.chip_valid.shape

    @classmethod
    def from_snapshot(
        cls,
        snapshot: Snapshot,
        *,
        reserved_fn: Callable[[str], int] | None = None,
        max_metrics_age_s: float = 0.0,
        now: float | None = None,
        node_bucket: int | None = None,
        chip_bucket: int | None = None,
    ) -> "FleetArrays":
        """Lower a snapshot. ``reserved_fn`` supplies in-flight reservations
        (accounting plugin); ``max_metrics_age_s`` > 0 marks stale nodes
        unfresh (0 = staleness checking disabled, every node fresh)."""
        import time as _time

        infos = snapshot.infos()
        names = [ni.name for ni in infos]
        max_chips = max((ni.tpu.chip_count for ni in infos if ni.tpu), default=0)
        n_pad = node_bucket or _bucket(max(len(names), 1), _MIN_NODE_BUCKET)
        c_pad = chip_bucket or _bucket(max(max_chips, 1), _MIN_CHIP_BUCKET)
        if n_pad < len(names) or c_pad < max_chips:
            raise ValueError(
                f"bucket ({n_pad},{c_pad}) too small for fleet "
                f"({len(names)} nodes, {max_chips} chips)"
            )

        node_valid = np.zeros(n_pad, dtype=bool)
        gen = np.zeros(n_pad, dtype=np.int32)
        in_slice = np.zeros(n_pad, dtype=bool)
        fresh = np.zeros(n_pad, dtype=bool)
        host_ok = np.zeros(n_pad, dtype=bool)
        last_updated = np.zeros(n_pad, dtype=np.float64)
        reserved = np.zeros(n_pad, dtype=np.int32)
        claimed = np.zeros(n_pad, dtype=np.int32)
        ext_chips = np.zeros(n_pad, dtype=np.int32)
        chip_valid = np.zeros((n_pad, c_pad), dtype=bool)
        healthy = np.zeros((n_pad, c_pad), dtype=bool)
        chip_used = np.zeros((n_pad, c_pad), dtype=bool)
        hbm_free = np.zeros((n_pad, c_pad), dtype=np.int32)
        hbm_total = np.zeros((n_pad, c_pad), dtype=np.int32)
        clock = np.zeros((n_pad, c_pad), dtype=np.int32)
        bw = np.zeros((n_pad, c_pad), dtype=np.int32)
        tflops = np.zeros((n_pad, c_pad), dtype=np.int32)
        power = np.zeros((n_pad, c_pad), dtype=np.int32)

        now = _time.time() if now is None else now
        arrays = cls(
            names=names,
            node_valid=node_valid,
            generation_rank=gen,
            in_slice=in_slice,
            fresh=fresh,
            host_ok=host_ok,
            last_updated=last_updated,
            reserved_chips=reserved,
            claimed_hbm_mib=claimed,
            ext_chips=ext_chips,
            chip_valid=chip_valid,
            chip_healthy=healthy,
            chip_used=chip_used,
            hbm_free_mib=hbm_free,
            hbm_total_mib=hbm_total,
            clock_mhz=clock,
            hbm_bandwidth=bw,
            tflops=tflops,
            power_w=power,
        )
        for i, ni in enumerate(infos):
            arrays.fill_row(
                i,
                ni,
                max_metrics_age_s=max_metrics_age_s,
                now=now,
                reserved_fn=reserved_fn,
            )
        return arrays

    def fill_row(
        self,
        i: int,
        ni,
        *,
        max_metrics_age_s: float = 0.0,
        now: float | None = None,
        reserved_fn: Callable[[str], int] | None = None,
    ) -> None:
        """(Re)compute row ``i`` from a NodeInfo in place — the per-node
        half of :meth:`from_snapshot`, also used for INCREMENTAL updates
        when one node's CR changed (a single agent refresh must not cost a
        full O(N x C) fleet rebuild; plugins/yoda/batch.py
        ``_refresh_static``). Chip columns are zeroed first so a CR that
        SHRANK (fewer chips) leaves no stale columns behind."""
        import time as _time

        c_pad = self.chip_valid.shape[1]
        for grid in (
            self.chip_valid, self.chip_healthy, self.chip_used,
            self.hbm_free_mib, self.hbm_total_mib, self.clock_mhz,
            self.hbm_bandwidth, self.tflops, self.power_w,
        ):
            grid[i] = 0
        tpu = ni.tpu
        if tpu is None:
            self.node_valid[i] = False  # never feasible
            return
        now = _time.time() if now is None else now
        self.node_valid[i] = True
        # No-pod-context default: cordon only. Taint/toleration admission
        # is per pod and arrives via the dyn vector (dyn_packed host_ok).
        self.host_ok[i] = ni.node is None or not ni.node.unschedulable
        self.generation_rank[i] = tpu.generation_rank
        self.in_slice[i] = bool(tpu.slice_id)
        self.last_updated[i] = tpu.last_updated_unix
        self.fresh[i] = (
            True
            if max_metrics_age_s <= 0
            else tpu.fresh(max_age_s=max_metrics_age_s, now=now)
        )
        self.claimed_hbm_mib[i] = min(
            _claimed_hbm_mib(ni), np.iinfo(np.int32).max
        )
        self.ext_chips[i] = max(int(tpu.external_used_chips), 0)
        for j, chip in enumerate(tpu.chips[:c_pad]):
            self.chip_valid[i, j] = True
            self.chip_healthy[i, j] = chip.healthy
            self.chip_used[i, j] = chip.hbm_free < chip.hbm_total
            self.hbm_free_mib[i, j] = chip.hbm_free // MIB
            self.hbm_total_mib[i, j] = chip.hbm_total // MIB
            self.clock_mhz[i, j] = chip.clock_mhz
            self.hbm_bandwidth[i, j] = chip.hbm_bandwidth_gbps
            self.tflops[i, j] = chip.tflops_bf16
            self.power_w[i, j] = chip.power_w
        if reserved_fn is not None:
            self.reserved_chips[i] = reserved_fn(ni.name)
        else:
            # No accounting: pin reserved to the absorbable usage so the
            # kernel's invisible-reservation and stale-freed corrections
            # both vanish (kernel_impl comment).
            self.reserved_chips[i] = self._neutral_reserved_row(i)

    def with_dynamic(
        self,
        reserved_fn: Callable[[str], int] | None,
        claimed_fn: Callable[[str], int] | None = None,
        *,
        max_metrics_age_s: float = 0.0,
        now: float | None = None,
        host_ok: np.ndarray | None = None,
    ) -> "FleetArrays":
        """Cheap per-cycle refresh of the per-node reservation/claim/freshness
        vectors (the [N, C] chip metrics are reused between metrics updates,
        so pod binds cost O(N), not O(N x C)). Freshness is re-evaluated
        against the CURRENT time so a node whose agent stops publishing goes
        stale even while the cached arrays are reused. ``host_ok`` overrides
        the static cordon-only admission vector with a per-pod one."""
        import time as _time

        out = dict(vars(self))
        if host_ok is not None:
            out["host_ok"] = host_ok
        if reserved_fn is not None:
            reserved = np.zeros_like(self.reserved_chips)
            for i, name in enumerate(self.names):
                reserved[i] = reserved_fn(name)
        else:
            # No accounting source: pin reserved to the absorbable usage
            # so the kernel's invisible-reservation AND stale-freed
            # corrections both vanish (a fully-occupied node must not look
            # free just because nothing claims it — kernel_impl comment).
            reserved = self._neutral_reserved()
        out["reserved_chips"] = reserved
        if claimed_fn is not None:
            claimed = np.zeros_like(self.claimed_hbm_mib)
            for i, name in enumerate(self.names):
                claimed[i] = claimed_fn(name)
            out["claimed_hbm_mib"] = claimed
        if max_metrics_age_s > 0:
            now = _time.time() if now is None else now
            out["fresh"] = (now - self.last_updated) <= max_metrics_age_s
        return FleetArrays(**out)

    def dyn_packed(
        self,
        reserved_fn: Callable[[str], int] | None,
        claimed_fn: Callable[[str], int] | None = None,
        *,
        max_metrics_age_s: float = 0.0,
        now: float | None = None,
        host_ok: np.ndarray | None = None,
        last_updated: "Mapping[str, float] | None" = None,
    ) -> np.ndarray:
        """The per-cycle node vectors as ONE [4, N] int32 array (rows =
        ops.kernel.DYN_KEYS: fresh, reserved_chips, claimed_hbm_mib,
        host_ok) for the device-resident kernel — same semantics as
        :meth:`with_dynamic`, packed so a scheduling cycle uploads a single
        array. ``host_ok`` carries the per-pod Node-object admission
        (cordon + taints vs THIS pod's tolerations); default: the static
        cordon-only view.

        ``reserved_fn`` / ``claimed_fn`` may each be a per-node callable OR
        a ``{node: value}`` Mapping — the mapping form lets callers take
        ONE consistent snapshot of the accountant under one lock
        (ChipAccountant.chips_by_node) instead of N locked calls per
        dispatch, which dominates the kernel itself at large fleets."""
        import time as _time
        from typing import Mapping as _Mapping

        n = self.node_valid.shape[0]
        dyn = np.zeros((4, n), dtype=np.int32)
        if max_metrics_age_s > 0:
            now = _time.time() if now is None else now
            if last_updated is not None:
                # Live timestamps (InformerCache.last_updated_map): the
                # baked self.last_updated goes stale when heartbeat
                # republishes deliberately skip the metrics-version bump.
                # One vectorized compare — no per-node scalar stores.
                get = last_updated.get
                n_real = len(self.names)
                ts = np.fromiter(
                    (get(name, 0.0) for name in self.names),
                    np.float64,
                    n_real,
                )
                dyn[0, :n_real] = (now - ts) <= max_metrics_age_s
            else:
                dyn[0] = (now - self.last_updated) <= max_metrics_age_s
        else:
            dyn[0] = self.fresh
        if reserved_fn is not None:
            if isinstance(reserved_fn, _Mapping):
                get = reserved_fn.get
                for i, name in enumerate(self.names):
                    dyn[1, i] = get(name, 0)
            else:
                for i, name in enumerate(self.names):
                    dyn[1, i] = reserved_fn(name)
        else:
            # No accounting: neutralize both reservation corrections (see
            # with_dynamic).
            dyn[1] = self._neutral_reserved()
        cap = np.iinfo(np.int32).max
        if claimed_fn is not None:
            if isinstance(claimed_fn, _Mapping):
                get = claimed_fn.get
                for i, name in enumerate(self.names):
                    dyn[2, i] = min(get(name, 0), cap)
            else:
                for i, name in enumerate(self.names):
                    dyn[2, i] = min(claimed_fn(name), cap)
        else:
            dyn[2] = self.claimed_hbm_mib
        dyn[3] = self.host_ok if host_ok is None else host_ok
        return dyn


def _claimed_hbm_mib(ni) -> int:
    """HBM claimed by pods already placed on the node (reference
    CalculateAllocateScore input, pkg/yoda/score/algorithm.go:77-80)."""
    from yoda_tpu.api.requests import LabelParseError, pod_request

    total = 0
    for pod in ni.pods:
        try:
            r = pod_request(pod)
        except LabelParseError:
            continue
        total += (r.hbm_per_chip // MIB) * r.effective_chips
    return total
