"""Device-resident, incrementally-maintained fleet state.

The serve loop's pre-dispatch cost used to be O(fleet) per cycle no matter
how little changed: every metrics bump re-read the snapshot into host
arrays and re-uploaded the whole fleet to the kernel's device, and every
dispatch rebuilt the [4, N] dynamics vector with an O(N) Python loop over
the accountant / informer maps. :class:`FleetStateCache` replaces all of
that with delta maintenance:

- The informer's epoch/delta feed (``InformerCache.changes_since``) names
  exactly which nodes' CR values changed since the epoch the resident
  state reflects; only those rows are re-filled host-side and scattered
  into the device-resident static arrays in place (``update_rows`` — a
  jitted ``.at[idx].set`` with the old buffers DONATED, so the update is
  double-buffered on device instead of re-allocating a fleet copy).
- A full re-stack (``FleetArrays.from_snapshot`` + ``put_static``)
  happens ONLY on epoch skew (the consumer fell behind the bounded delta
  ring, or holds state from another informer), on a structural delta
  (node added/removed — bucketed row indices may shift), on chip-bucket
  growth, or when the delta touches too much of the fleet for row-wise
  refill to beat the vectorized rebuild.
- The per-cycle dynamics rows (reserved chips, claimed HBM) are likewise
  maintained from the accountant's and informer's claim delta feeds:
  at low churn a cycle applies O(changed) scalar writes instead of
  copying O(fleet) maps.

Compile shapes stay bucketed exactly as before (ops/arrays.bucket_rows,
including the mesh-multiple discipline), so churn never recompiles; the
cache works identically over the single-device, mesh-sharded, and numpy
kernels (kernels without ``update_rows`` degrade to a full upload).
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np

from yoda_tpu.ops.arrays import FleetArrays, bucket_rows

_INT32_MAX = np.iinfo(np.int32).max


class FleetStateCache:
    """Incrementally-maintained mirror of the fleet + its device-resident
    kernel state. ``sync(snapshot)`` brings both up to the snapshot's
    metrics epoch (delta row refill, or full re-stack when the delta feed
    cannot serve); ``dyn_packed()`` returns the per-cycle [4, N] dynamics
    array, maintained from the reservation/claim delta feeds.

    ``kern_fn(arrays)`` is consulted on every re-stack and returns the
    kernel the fleet should run on at that shape (the batch plugin's
    platform policy); the returned kernel gets a full ``put_static``,
    delta syncs use its ``update_rows`` when offered.
    """

    def __init__(
        self,
        *,
        changes_fn: Callable,           # InformerCache.changes_since
        kern_fn: Callable,              # arrays -> kernel for this shape
        max_metrics_age_s: float = 0.0,
        mesh_multiple: "int | None" = None,
        reserved_delta_fn: "Callable | None" = None,
        reserved_map_fn: "Callable | None" = None,
        reserved_fn: "Callable | None" = None,
        claimed_delta_fn: "Callable | None" = None,
        claimed_map_fn: "Callable | None" = None,
        claimed_fn: "Callable | None" = None,
        last_updated_map_fn: "Callable | None" = None,
    ) -> None:
        self.changes_fn = changes_fn
        self.kern_fn = kern_fn
        self.max_metrics_age_s = max_metrics_age_s
        self.mesh_multiple = mesh_multiple
        self.reserved_delta_fn = reserved_delta_fn
        self.reserved_map_fn = reserved_map_fn
        self.reserved_fn = reserved_fn          # per-node fallback
        self.claimed_delta_fn = claimed_delta_fn
        self.claimed_map_fn = claimed_map_fn
        self.claimed_fn = claimed_fn            # per-node fallback
        self.last_updated_map_fn = last_updated_map_fn
        self.arrays: FleetArrays | None = None
        self.kern = None
        self.epoch = 0                  # informer metrics epoch reflected
        self._index: dict[str, int] = {}
        # Dynamics state: the [4, N] vector reused across cycles, plus the
        # claim-feed epochs its rows 1/2 are current to, and the rows the
        # last syncs refilled (their BAKED dyn inputs — freshness, and the
        # reserved/claimed fallbacks when no live source is wired — must
        # be refreshed in the reused vector).
        self._dyn: np.ndarray | None = None
        self._res_epoch = -1
        self._claim_epoch = -1
        self._stale_rows: set[int] = set()
        # Counters (yoda_snapshot_reuse_total / yoda_restack_total /
        # yoda_delta_apply_ms via the batch plugin's lazy metrics).
        self.reuse = 0                  # syncs answered by the cached epoch
        self.restacks = 0               # full from_snapshot + put_static
        self.delta_syncs = 0            # syncs served by row refill
        self.rows_applied = 0           # rows scattered in place, total
        self.last_delta_ms = 0.0        # wall ms of the last delta sync
        self.last_restack_ms = 0.0      # wall ms of the last full re-stack

    # --- static state ---

    def sync(self, snapshot) -> FleetArrays:
        """Bring the resident state up to ``snapshot``'s metrics epoch and
        return the host mirror arrays."""
        target = getattr(snapshot, "metrics_version", None) or snapshot.version
        if (
            self.arrays is not None
            and self.kern is not None
            and self.epoch == target
        ):
            self.reuse += 1
            return self.arrays
        t0 = time.perf_counter()
        delta = self.changes_fn(self.epoch) if self.arrays is not None else None
        if delta is None or delta.structural:
            return self._restack(snapshot, target, t0)
        a = self.arrays
        # Beyond ~a quarter of the fleet the per-row refill costs what the
        # vectorized rebuild does — re-stack instead (same heuristic as
        # the pre-resident incremental path).
        if len(delta.changed) > max(len(a.names) // 4, 8):
            return self._restack(snapshot, target, t0)
        rows: list[int] = []
        for name in delta.changed:
            i = self._index.get(name)
            # The delta may run ahead of the snapshot (the informer moved
            # on while this cycle's snapshot was cached): a changed node
            # the snapshot cannot resolve, or one this mirror has no row
            # for, forces the safe path.
            if i is None or name not in snapshot:
                return self._restack(snapshot, target, t0)
            ni = snapshot.get(name)
            if ni.tpu is None or ni.tpu.chip_count > a.padded_shape[1]:
                return self._restack(snapshot, target, t0)  # bucket outgrown
            rows.append(i)
        now = time.time()
        for i in rows:
            a.fill_row(
                i,
                snapshot.get(a.names[i]),
                max_metrics_age_s=self.max_metrics_age_s,
                now=now,
            )
        if rows:
            if hasattr(self.kern, "update_rows"):
                self.kern.update_rows(a, rows)
            else:  # kernels without the scatter path: full re-upload
                self.kern.put_static(a)
            self.rows_applied += len(rows)
            self._stale_rows.update(rows)
        self.delta_syncs += 1
        # The snapshot's epoch, NOT the feed's current one: changes that
        # landed after the snapshot was cut are re-applied next sync
        # instead of silently skipped.
        self.epoch = target
        self.last_delta_ms = (time.perf_counter() - t0) * 1e3
        return a

    def _restack(self, snapshot, target: int, t0: float) -> FleetArrays:
        arrays = FleetArrays.from_snapshot(
            snapshot,
            max_metrics_age_s=self.max_metrics_age_s,
            node_bucket=(
                bucket_rows(len(snapshot), multiple_of=self.mesh_multiple)
                if self.mesh_multiple
                else None
            ),
        )
        kern = self.kern_fn(arrays)
        kern.put_static(arrays)
        self.kern = kern
        self.arrays = arrays
        self._index = {nm: i for i, nm in enumerate(arrays.names)}
        self._dyn = None  # shapes/rows moved: rebuild the dynamics vector
        self.restacks += 1
        self.epoch = target
        self.last_restack_ms = (time.perf_counter() - t0) * 1e3
        return arrays

    # --- per-cycle dynamics ---

    def _apply_row_delta(
        self,
        row: np.ndarray,
        delta_fn: "Callable | None",
        map_fn: "Callable | None",
        node_fn: "Callable | None",
        prev_epoch: int,
        cap: "int | None" = None,
    ) -> int:
        """Bring one dynamics row up to its feed's current epoch: apply
        the changed nodes' values in place, or rebuild the row from the
        full map (or the per-node fallback) when the feed cannot serve —
        consumer too far behind, or no feed wired. Returns the epoch the
        row is now current to."""
        a = self.arrays
        cur, changes = delta_fn(prev_epoch) if delta_fn else (0, None)
        if changes is None:
            if map_fn is not None:
                get = map_fn().get
                src = (get(nm, 0) for nm in a.names)
            elif node_fn is not None:
                src = (node_fn(nm) for nm in a.names)
            else:
                src = (0 for _ in a.names)
            n_real = len(a.names)
            row[:] = 0
            vals = np.fromiter(src, np.int64, n_real)
            if cap is not None:
                vals = np.minimum(vals, cap)
            row[:n_real] = vals
        else:
            idx = self._index
            for nm, v in changes.items():
                i = idx.get(nm)
                if i is not None:
                    row[i] = v if cap is None else min(v, cap)
        return cur

    def dyn_packed(self, *, host_ok: "np.ndarray | None" = None) -> np.ndarray:
        """The per-cycle [4, N] dynamics array (ops.kernel.DYN_KEYS rows),
        semantically identical to ``FleetArrays.dyn_packed`` over the live
        reservation/claim sources, but maintained in place: at low churn a
        cycle costs O(changed reservations), not O(fleet). The freshness
        row is the one O(N)-per-cycle exception, and only when a staleness
        gate is configured (it compares every node's live timestamp
        against now — exactly what the non-resident path paid).

        The returned array is reused across cycles — callers must copy
        anything they keep (the burst sets already do)."""
        a = self.arrays
        if a is None:
            raise RuntimeError("sync() must run before dyn_packed()")
        n = a.node_valid.shape[0]
        has_reserved_src = bool(
            self.reserved_delta_fn or self.reserved_map_fn or self.reserved_fn
        )
        has_claimed_src = bool(
            self.claimed_delta_fn or self.claimed_map_fn or self.claimed_fn
        )
        if self._dyn is None or self._dyn.shape[1] != n:
            self._dyn = np.zeros((4, n), dtype=np.int32)
            if self.max_metrics_age_s <= 0:
                self._dyn[0] = a.fresh
            # Without a live source, a row tracks the BAKED arrays values
            # (fill_row maintains them per refill): neutral reserved and
            # placed-pod claims — FleetArrays.dyn_packed's None-source
            # semantics.
            if not has_reserved_src:
                self._dyn[1] = a.reserved_chips
            if not has_claimed_src:
                self._dyn[2] = a.claimed_hbm_mib
            self._res_epoch = -1    # force row rebuilds from the maps
            self._claim_epoch = -1
            self._stale_rows.clear()
        dyn = self._dyn
        if self._stale_rows:
            # Rows refilled since the last cycle: refresh their baked
            # entries in the reused vector (O(refilled)).
            for i in self._stale_rows:
                if self.max_metrics_age_s <= 0:
                    dyn[0, i] = a.fresh[i]
                if not has_reserved_src:
                    dyn[1, i] = a.reserved_chips[i]
                if not has_claimed_src:
                    dyn[2, i] = a.claimed_hbm_mib[i]
            self._stale_rows.clear()
        if has_reserved_src:
            self._res_epoch = self._apply_row_delta(
                dyn[1], self.reserved_delta_fn, self.reserved_map_fn,
                self.reserved_fn, self._res_epoch,
            )
        if has_claimed_src:
            self._claim_epoch = self._apply_row_delta(
                dyn[2], self.claimed_delta_fn, self.claimed_map_fn,
                self.claimed_fn, self._claim_epoch, cap=_INT32_MAX,
            )
        if self.max_metrics_age_s > 0:
            now = time.time()
            if self.last_updated_map_fn is not None:
                # Live timestamps (heartbeat republishes deliberately skip
                # the metrics-version bump, so the baked ones age).
                get = self.last_updated_map_fn().get
                n_real = len(a.names)
                ts = np.fromiter(
                    (get(nm, 0.0) for nm in a.names), np.float64, n_real
                )
                dyn[0] = 0
                dyn[0, :n_real] = (now - ts) <= self.max_metrics_age_s
            else:
                dyn[0] = (now - a.last_updated) <= self.max_metrics_age_s
        # (With no staleness gate, row 0 was seeded from a.fresh and row
        # refills keep it current — nothing ages.)
        dyn[3] = a.host_ok if host_ok is None else host_ok
        return dyn
