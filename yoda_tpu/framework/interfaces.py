"""Extension-point interfaces and framework datatypes.

Modeled on the modern kube-scheduler framework (the v1alpha1→v1 semantics
trap is documented in yoda_tpu/framework/__init__.py). The reference plugin
implements QueueSort, Filter, "PostFilter" (modern PreScore), Score, and
ScoreExtensions (reference pkg/yoda/scheduler.go:29-33); this framework adds
the extension points the reference lacks and the BASELINE configs require:
PreFilter, modern PostFilter (preemption), Reserve/Unreserve, Permit, Bind.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Mapping, Protocol, Sequence

from yoda_tpu.api.types import K8sNode, PodSpec, TpuNodeMetrics

if TYPE_CHECKING:
    from yoda_tpu.framework.cyclestate import CycleState

MAX_NODE_SCORE = 100  # framework.MaxNodeScore parity (used at reference scheduler.go:137)


class Code(enum.Enum):
    SUCCESS = "Success"
    ERROR = "Error"
    UNSCHEDULABLE = "Unschedulable"
    UNSCHEDULABLE_AND_UNRESOLVABLE = "UnschedulableAndUnresolvable"
    WAIT = "Wait"
    SKIP = "Skip"


@dataclass(frozen=True)
class Status:
    """Result of one plugin at one extension point (upstream framework.Status
    analog; the reference constructs these at e.g. scheduler.go:79-83)."""

    code: Code = Code.SUCCESS
    message: str = ""

    @property
    def success(self) -> bool:
        return self.code == Code.SUCCESS

    @property
    def rejected(self) -> bool:
        return self.code in (Code.UNSCHEDULABLE, Code.UNSCHEDULABLE_AND_UNRESOLVABLE)

    @classmethod
    def ok(cls) -> "Status":
        # Shared frozen instance: ok() is the hottest Status constructor
        # (every node of every cycle) and carries no per-call data.
        return _STATUS_OK

    @classmethod
    def unschedulable(cls, message: str) -> "Status":
        return cls(Code.UNSCHEDULABLE, message)

    @classmethod
    def unresolvable(cls, message: str) -> "Status":
        return cls(Code.UNSCHEDULABLE_AND_UNRESOLVABLE, message)

    @classmethod
    def error(cls, message: str) -> "Status":
        return cls(Code.ERROR, message)

    @classmethod
    def wait(cls, message: str = "") -> "Status":
        return cls(Code.WAIT, message)

    @classmethod
    def skip(cls) -> "Status":
        return cls(Code.SKIP)


_STATUS_OK = Status(Code.SUCCESS)


@dataclass
class NodeInfo:
    """A node plus its scheduler-visible state: the TPU metrics CR and the
    pods already placed there (the reference reads placed pods' labels for
    allocation scoring, reference pkg/yoda/score/algorithm.go:77-80)."""

    name: str
    tpu: TpuNodeMetrics | None = None
    pods: list[PodSpec] = field(default_factory=list)
    # The v1.Node object when the cluster backend watches Nodes; None in
    # minimal test setups (admission checks then pass vacuously).
    node: K8sNode | None = None


class Snapshot:
    """Immutable-per-cycle view of the cluster — the analog of the upstream
    ``SnapshotSharedLister`` the reference reads in Score (reference
    pkg/yoda/scheduler.go:101). Built once per cycle from the informer cache;
    NO API-server reads happen during a cycle (the fix for the reference's
    per-node live Gets, scheduler.go:70,108 — SURVEY.md §3.2 hot-loop)."""

    def __init__(
        self,
        nodes: Mapping[str, NodeInfo],
        *,
        version: int = 0,
        namespaces: "Mapping[str, Mapping[str, str]] | None" = None,
        pvcs: "Mapping[str, object] | None" = None,
        pvs: "Mapping[str, object] | None" = None,
        order: "list[str] | None" = None,
    ) -> None:
        self._nodes = dict(nodes)
        # ``order``: the node names ALREADY in sorted order, supplied by a
        # builder that maintains it incrementally (InformerCache keeps a
        # bisect-maintained name list) — re-sorting O(N log N) per snapshot
        # build was the next serve-path wall at fleet scale. Bare
        # constructions (tests, ad-hoc snapshots) omit it and pay the sort.
        self._order = sorted(self._nodes) if order is None else order
        # Monotonic cache key bumped by the informer on any node/pod/metrics
        # change; lets the batch plugin reuse lowered fleet arrays across
        # cycles (0 = uncacheable).
        self.version = version
        # Namespace name -> labels (from the Namespace watch), consumed by
        # pod-affinity namespaceSelector terms (api.affinity). None = no
        # Namespace data available.
        self.namespaces = dict(namespaces) if namespaces else None
        # "namespace/name" -> K8sPvc (from the PVC watch), consumed by the
        # minimal volume filter (filter_plugin.node_fits_volumes). None =
        # no PVC data available (backends without the watch: volume
        # constraints are not enforced, as in the round-3 state). An EMPTY
        # dict is meaningful — the watch is live and no claims exist —
        # so only a true None collapses to None.
        self.pvcs = dict(pvcs) if pvcs is not None else None
        # PV name -> K8sPv (from the PersistentVolume watch): lets the
        # volume filter enforce a bound claim's REAL PV nodeAffinity
        # instead of the claim's zone-label stand-in. Same None-vs-empty
        # contract as pvcs.
        self.pvs = dict(pvs) if pvs is not None else None
        # Node names fenced from NEW placements by the node health monitor
        # (SUSPECT / DRAINING / DOWN — yoda_tpu/nodehealth). Populated by
        # the informer's fence_fn at snapshot build; admission call sites
        # (batch _host_admission, the Filter chain, gang planning, the
        # rebalancer's fit checks) veto these hosts. Fence flips
        # invalidate the snapshot, so the set is never stale per build.
        self.fenced: frozenset = frozenset()

    def get(self, name: str) -> NodeInfo:
        return self._nodes[name]

    def names(self) -> list[str]:
        return list(self._order)

    def infos(self) -> list[NodeInfo]:
        return [self._nodes[n] for n in self._order]

    def __len__(self) -> int:
        return len(self._nodes)

    def __contains__(self, name: str) -> bool:
        return name in self._nodes


class QueuedPodLike(Protocol):
    pod: PodSpec


class Plugin:
    name: str = "plugin"


class QueueSortPlugin(Plugin):
    def less(self, a: "QueuedPodLike", b: "QueuedPodLike") -> bool:
        """True if pod ``a`` should be scheduled before pod ``b``."""
        raise NotImplementedError


class PreFilterPlugin(Plugin):
    def pre_filter(self, state: "CycleState", pod: PodSpec, snapshot: Snapshot) -> Status:
        raise NotImplementedError


class FilterPlugin(Plugin):
    def filter(self, state: "CycleState", pod: PodSpec, node: NodeInfo) -> Status:
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    """Modern PostFilter: runs when NO node passed Filter — preemption."""

    def post_filter(
        self,
        state: "CycleState",
        pod: PodSpec,
        snapshot: Snapshot,
        filtered_statuses: Mapping[str, Status],
    ) -> tuple[str | None, Status]:
        """Returns (nominated_node_name or None, status)."""
        raise NotImplementedError


class PreScorePlugin(Plugin):
    def pre_score(
        self,
        state: "CycleState",
        pod: PodSpec,
        snapshot: Snapshot,
        feasible: Sequence[str],
    ) -> Status:
        raise NotImplementedError


class ScorePlugin(Plugin):
    def score(self, state: "CycleState", pod: PodSpec, node: NodeInfo) -> tuple[int, Status]:
        raise NotImplementedError

    def normalize(
        self, state: "CycleState", pod: PodSpec, scores: dict[str, int]
    ) -> Status:
        """In-place min-max rescale to [0, MAX_NODE_SCORE] by default —
        parity with the reference's NormalizeScore including its all-equal
        guard (reference pkg/yoda/scheduler.go:122-147, minus the unguarded
        ``scores[0]`` panic on an empty list, SURVEY.md §3.4 quirk 6)."""
        if not scores:
            return Status.ok()
        lowest = min(scores.values())
        highest = max(scores.values())
        if highest == lowest:
            lowest -= 1
        for name, s in scores.items():
            scores[name] = (s - lowest) * MAX_NODE_SCORE // (highest - lowest)
        return Status.ok()


class BatchFilterScorePlugin(Plugin):
    """TPU-native fast path with no upstream analog: filter AND score every
    node in one fused, device-compiled computation over the fleet's metric
    arrays, instead of a Python loop of per-node calls. A plugin implementing
    this is used by the framework INSTEAD of its FilterPlugin/ScorePlugin
    methods on the hot path; the per-node methods remain as the semantic
    reference and for fallback."""

    def filter_and_score_batch(
        self, state: "CycleState", pod: PodSpec, snapshot: Snapshot
    ) -> tuple[dict[str, Status], dict[str, int]]:
        """Returns (per-node filter status, per-node raw score for feasible
        nodes)."""
        raise NotImplementedError


class ReservePlugin(Plugin):
    def reserve(self, state: "CycleState", pod: PodSpec, node_name: str) -> Status:
        raise NotImplementedError

    def unreserve(self, state: "CycleState", pod: PodSpec, node_name: str) -> None:
        raise NotImplementedError


class PermitPlugin(Plugin):
    def permit(
        self, state: "CycleState", pod: PodSpec, node_name: str
    ) -> tuple[Status, float]:
        """Returns (status, timeout_seconds). Status WAIT parks the pod on the
        framework waitlist until approved/rejected or timeout."""
        raise NotImplementedError


class BindPlugin(Plugin):
    def bind(self, state: "CycleState", pod: PodSpec, node_name: str) -> Status:
        raise NotImplementedError


def summarize_failure(statuses: Mapping[str, Status]) -> str:
    """Aggregate per-node failure messages like the upstream fitError text."""
    counts: dict[str, int] = {}
    for s in statuses.values():
        if not s.success:
            counts[s.message or s.code.value] = counts.get(s.message or s.code.value, 0) + 1
    parts = [f"{n} node(s): {msg}" for msg, n in sorted(counts.items(), key=lambda kv: -kv[1])]
    return "; ".join(parts) if parts else "no nodes available"
