"""Scheduler shard-out: fleet partitioning + pod routing for N parallel
serve loops (ISSUE 14).

One serve loop per cluster was the throughput ceiling the ROADMAP named:
ingest is batched (~40x), dispatch is device-resident, binds are
pipelined — yet every placement decision still serialized through one
process-wide loop. This module supplies the two pure-logic pieces that
let ``standalone.build_sharded_stacks`` run N loops against one cluster:

- :class:`ShardMap` — deterministic ICI slice/pool -> shard assignment by
  rendezvous (highest-random-weight) hashing over a keyed blake2 digest.
  The assignment is a pure function of (pool id, shard count): fleet
  change moves NOTHING (a new slice lands on its hash-chosen shard, a
  deleted slice takes only itself away), and changing ``shard_count``
  moves ~1/N of the pools — the rendezvous property. Hosts outside any
  multi-host slice form single-host pools (``host:<name>``).
- :class:`ShardRouter` — watch-fed routing of pending pods to exactly ONE
  shard's scheduling queue. Every member of a gang routes to the same
  shard (rendezvous over the gang name across the shards whose partition
  could host the gang whole); a gang NO single shard can host — a mesh
  larger than any shard's partition — routes to the serialized GLOBAL
  lane, whose stack sees the whole fleet, so no workload regresses.
  Routing is advisory capacity-shape feasibility only: admission (and
  ultimately the optimistic shard commit at the shared ChipAccountant)
  gates reality.

Correctness note: partitions are disjoint by construction, so two shards
never contend for a node in the steady state — the optimistic
claim->validate->commit protocol exists for the windows where they DO
see the same nodes: the serialized global lane placing a cross-shard
gang over every partition, and the stale-shard-map window a rendezvous
rebalance opens (modeled by ``ShardMap(overlap=...)`` in the
cross_shard_contention chaos mode).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Callable, Iterable, Mapping

from yoda_tpu.api.requests import LabelParseError, gang_name_of, pod_request
from yoda_tpu.api.types import PodSpec, TpuNodeMetrics
from yoda_tpu.plugins.yoda.topology import normalize_dims

#: The serialized fallback lane for gangs no single shard can host. Its
#: stack sees the WHOLE fleet and stages/commits like any shard, so its
#: placements contend with every shard through the accountant's
#: optimistic commit — never through shared locks.
GLOBAL_LANE = "global"


def _digest(*parts: str) -> int:
    """Stable 64-bit hash — deliberately NOT Python's randomized str
    hash: the slice->shard assignment must survive process restarts and
    replay identically under any PYTHONHASHSEED."""
    h = hashlib.blake2b("|".join(parts).encode(), digest_size=8)
    return int.from_bytes(h.digest(), "big")


def shard_name(i: int) -> str:
    return f"s{i}"


class ShardMap:
    """Deterministic pool -> shard assignment (rendezvous hashing).

    ``overlap`` maps a pool id to EXTRA shard indices that also see it in
    their partition — the stale-assignment window a live rendezvous
    rebalance opens (two shards briefly believing they own one slice).
    Production leaves it empty; the cross_shard_contention chaos mode
    pins it open to prove the commit protocol holds under overlap.
    """

    def __init__(
        self,
        shard_count: int,
        *,
        overlap: "Mapping[str, Iterable[int]] | None" = None,
    ) -> None:
        if shard_count < 1:
            raise ValueError(f"shard_count must be >= 1, got {shard_count}")
        self.shard_count = shard_count
        self.overlap = {
            pool: tuple(extra) for pool, extra in (overlap or {}).items()
        }
        # pool -> primary shard memo (pure function; the dict is only a
        # cache, so the benign last-write-wins race under concurrent
        # fills is harmless).
        self._memo: dict[str, int] = {}

    @staticmethod
    def pool_of(name: str, tpu: "TpuNodeMetrics | None") -> str:
        """The partition unit a node belongs to: its ICI slice when it is
        part of one, else a single-host pool — a slice is never split
        across shards, so every topology block plans within one shard."""
        slice_id = tpu.slice_id if tpu is not None else ""
        return slice_id or f"host:{name}"

    def shard_of_pool(self, pool: str) -> int:
        s = self._memo.get(pool)
        if s is None:
            s = max(
                range(self.shard_count),
                key=lambda i: _digest("pool", pool, str(i)),
            )
            self._memo[pool] = s
        return s

    def shards_of_pool(self, pool: str) -> tuple[int, ...]:
        primary = self.shard_of_pool(pool)
        extra = tuple(
            i for i in self.overlap.get(pool, ()) if i != primary
        )
        return (primary, *extra)

    def node_filter(
        self, shard: int
    ) -> "Callable[[str, TpuNodeMetrics], bool]":
        """The informer snapshot predicate for one shard's partition —
        a pure function of the CR's slice id, safe under the informer
        lock."""

        def _filter(name: str, tpu: TpuNodeMetrics) -> bool:
            return shard in self.shards_of_pool(self.pool_of(name, tpu))

        return _filter


class _PoolAgg:
    """Per-pool capacity aggregate (one slice or one single-host pool)."""

    __slots__ = ("hosts", "chips", "max_node_chips", "dims", "node_chips")

    def __init__(self) -> None:
        self.hosts = 0
        self.chips = 0
        self.max_node_chips = 0
        self.dims = (0, 0, 0)  # slice host-grid extents (max coord + 1)
        self.node_chips: dict[int, int] = {}  # node capacity -> host count


def _blocks_in_grid(
    grid: tuple[int, int, int], want: tuple[int, int, int]
) -> int:
    """How many disjoint axis-aligned ``want`` blocks a fully-free
    ``grid`` holds — maximized over axis permutations (exact for the
    axis-aligned packing the topology planner performs on a free grid;
    occupancy is admission's job, not routing's)."""
    import itertools

    best = 0
    for perm in set(itertools.permutations(want)):
        n = 1
        for g, w in zip(grid, perm):
            n *= g // w if w else 0
        best = max(best, n)
    return best


class ShardRouter:
    """Watch-fed router: every pending pod to exactly one shard queue.

    Registered as a cluster watcher BEFORE any stack's informer, so the
    fleet registry is current when an informer routes a pod from the
    same event batch. ``route`` is called under informer locks — it
    takes only its own lock and touches no other component (the lock-
    ordering DAG allows same-level sibling acquisition, never a reach
    back into a component lock).
    """

    #: Occupancy tie-break quantum: queue depths bucket at log2 of
    #: (depth // QUANTUM), so routing ignores depth noise below a real
    #: backlog and behaves exactly like pure rendezvous on balanced or
    #: drained fleets — only genuine skew (a starved shard hundreds of
    #: entries deep) re-steers new arrivals.
    OCCUPANCY_QUANTUM = 64

    #: Bound on remembered gang routing decisions (whole-gang
    #: consistency under the occupancy tie-break: the first member's
    #: answer pins the gang until a structural generation bump).
    MAX_GANG_MEMO = 4096

    def __init__(self, shard_map: ShardMap) -> None:
        self.map = shard_map
        self._lock = threading.Lock()
        # node -> (pool, coords, healthy chips); the pool aggregates are
        # rebuilt lazily when dirty (structural change), never per route.
        self._nodes: dict[str, tuple[str, tuple[int, int, int], int]] = {}
        self._dirty = True
        self._pools: dict[str, _PoolAgg] = {}
        self._by_shard: dict[int, list[str]] = {}
        self.generation = 0  # bumped per aggregate rebuild (reroute gate)
        # Occupancy-aware routing (ISSUE 15 satellite): per-shard live
        # queue depth, wired by build_sharded_stacks (ShardSet.queue_
        # depth). None = pure rendezvous. Rendezvous "ties" are broken
        # by depth BUCKET: among capacity-feasible shards, only those in
        # the lowest occupancy bucket stay candidates, then rendezvous
        # picks deterministically — deterministic given the depth
        # snapshot, and starved work stops defaulting to the global lane.
        self.depth_fn: "Callable[[int], int] | None" = None
        # gang routing key -> (generation, lane): every member of a gang
        # must compute the SAME lane even as depths move between member
        # arrivals; the memo pins the first member's answer until a
        # structural fleet change (generation bump) or a map swap.
        self._gang_memo: "OrderedDict[str, tuple[int, str]]" = OrderedDict()

    def swap_map(self, new_map: ShardMap) -> None:
        """Install a new rendezvous map (live shard resize): aggregates
        rebuild lazily, gang memos drop (fresh decisions under the new
        topology), and the generation bumps so reroute passes treat
        every queued entry as re-routable."""
        with self._lock:
            self.map = new_map
            self._dirty = True
            self._gang_memo.clear()
            self.generation += 1

    def pools_snapshot(self) -> "list[str]":
        """The live pool ids (resize movement accounting)."""
        with self._lock:
            if self._dirty:
                self._rebuild_locked()
            return list(self._pools)

    # --- watch feed ---

    def observe(self, event) -> None:
        if event.kind != "TpuNodeMetrics":
            return
        tpu = event.obj
        with self._lock:
            if event.type == "deleted":
                if self._nodes.pop(tpu.name, None) is not None:
                    self._dirty = True
                return
            pool = self.map.pool_of(tpu.name, tpu)
            rec = (pool, tpu.topology_coords, len(tpu.healthy_chips()))
            if self._nodes.get(tpu.name) != rec:
                self._nodes[tpu.name] = rec
                self._dirty = True

    def observe_batch(self, events) -> None:
        for event in events:
            self.observe(event)

    # --- aggregates ---

    def _rebuild_locked(self) -> None:
        pools: dict[str, _PoolAgg] = {}
        for _name, (pool, coords, chips) in self._nodes.items():
            agg = pools.get(pool)
            if agg is None:
                agg = pools[pool] = _PoolAgg()
            agg.hosts += 1
            agg.chips += chips
            agg.max_node_chips = max(agg.max_node_chips, chips)
            agg.node_chips[chips] = agg.node_chips.get(chips, 0) + 1
            agg.dims = tuple(
                max(d, c + 1) for d, c in zip(agg.dims, coords)
            )
        by_shard: dict[int, list[str]] = {}
        for pool in pools:
            for s in self.map.shards_of_pool(pool):
                by_shard.setdefault(s, []).append(pool)
        self._pools = pools
        self._by_shard = by_shard
        self._dirty = False
        self.generation += 1

    def _shard_pools_locked(self, shard: int) -> "list[_PoolAgg]":
        return [self._pools[p] for p in self._by_shard.get(shard, ())]

    # --- routing ---

    def route(self, pod: PodSpec) -> str:
        """The shard lane this pod belongs to: ``s<i>`` or GLOBAL_LANE.
        Deterministic (keyed rendezvous over the gang name / pod uid
        across feasible shards) and whole-gang-consistent — every member
        computes the same answer. Never raises: anything unroutable
        (malformed labels, empty fleet) belongs to the global lane,
        whose full-fleet stack runs the normal admission machinery."""
        try:
            return self._route_inner(pod)
        except Exception:  # noqa: BLE001 — unroutable -> global lane
            return GLOBAL_LANE

    def _route_inner(self, pod: PodSpec) -> str:
        try:
            req = pod_request(pod)
        except LabelParseError:
            return GLOBAL_LANE
        with self._lock:
            if self._dirty:
                self._rebuild_locked()
            gang = req.gang
            if gang is None:
                feasible = [
                    s
                    for s in range(self.map.shard_count)
                    if any(
                        a.max_node_chips >= req.effective_chips
                        for a in self._shard_pools_locked(s)
                    )
                ]
                key = pod.uid or pod.key
            elif gang.topology is not None:
                want = normalize_dims(gang.topology)
                feasible = [
                    s
                    for s in range(self.map.shard_count)
                    if sum(
                        _blocks_in_grid(a.dims, want)
                        for a in self._shard_pools_locked(s)
                        if a.dims != (0, 0, 0)
                    )
                    >= gang.slices
                ]
                key = gang_name_of(pod.labels) or pod.uid
            else:
                # Plain gang: enough member slots across the partition
                # for the whole gang (floor(cap/chips) per host class).
                need = gang.size
                per = max(req.effective_chips, 1)
                feasible = []
                for s in range(self.map.shard_count):
                    slots = sum(
                        n * (cap // per)
                        for a in self._shard_pools_locked(s)
                        for cap, n in a.node_chips.items()
                    )
                    if slots >= need:
                        feasible.append(s)
                key = gang_name_of(pod.labels) or pod.uid
            is_gang = gang is not None
            gen = self.generation
            if is_gang:
                memo = self._gang_memo.get(key)
                if memo is not None and memo[0] == gen:
                    # Whole-gang consistency: later members (and reroute
                    # passes) repeat the first member's answer until a
                    # structural change invalidates it.
                    self._gang_memo.move_to_end(key)
                    return memo[1]
            lane = self._pick_locked(feasible, key)
            if is_gang:
                self._gang_memo[key] = (gen, lane)
                while len(self._gang_memo) > self.MAX_GANG_MEMO:
                    self._gang_memo.popitem(last=False)
            return lane

    def _pick_locked(self, feasible: "list[int]", key: str) -> str:
        """Choose among capacity-feasible shards: lowest occupancy
        BUCKET first (quantized live queue depth — the tie-break that
        steers work off starved shards), then keyed rendezvous.
        Deterministic given the depth snapshot; pure rendezvous when no
        depth source is wired or depths are balanced."""
        if not feasible:
            return GLOBAL_LANE
        candidates = feasible
        depth_fn = self.depth_fn
        if depth_fn is not None and len(feasible) > 1:
            buckets: dict[int, int] = {}
            for s in feasible:
                try:
                    depth = max(int(depth_fn(s)), 0)
                except Exception:  # noqa: BLE001 — a sick depth source reads as empty
                    depth = 0
                buckets[s] = (depth // self.OCCUPANCY_QUANTUM).bit_length()
            best = min(buckets.values())
            candidates = [s for s in feasible if buckets[s] == best]
        chosen = max(
            candidates, key=lambda s: _digest("route", key, str(s))
        )
        return shard_name(chosen)


class WorkerSupervisor:
    """Shard worker process lifecycle for ``shard_mode=process``: spawn
    one OS process per shard lane, poll liveness, respawn dead workers
    with exponential backoff (a replacement warm-starts like a promoted
    standby — its informer resyncs and its staged residue was already
    the parent journal's to recover), and kill/stop on teardown.

    ``spawn_fn(shard_index) -> subprocess.Popen`` is injected so the
    supervisor never knows whether it is launching a production kube
    worker, a bench spec worker, or a chaos driver.
    """

    RESPAWN_BACKOFF_S = 0.5
    RESPAWN_BACKOFF_MAX_S = 15.0

    def __init__(
        self,
        spawn_fn: "Callable[[int], object]",
        shard_count: int,
        *,
        max_respawns: "int | None" = None,
        clock=None,
    ) -> None:
        import time as _time

        self.spawn_fn = spawn_fn
        self.shard_count = int(shard_count)
        self.max_respawns = max_respawns
        self.clock = clock if clock is not None else _time.monotonic
        self._lock = threading.Lock()
        self._procs: "dict[int, object]" = {}
        self._restarts: "dict[int, int]" = {}
        self._next_spawn_at: "dict[int, float]" = {}
        self._stopping = False

    def start(self) -> None:
        for i in range(self.shard_count):
            self._spawn(i)

    def _spawn(self, i: int) -> None:
        proc = self.spawn_fn(i)
        with self._lock:
            self._procs[i] = proc
            self._next_spawn_at.pop(i, None)

    def poll(self) -> "list[int]":
        """One supervision pass: respawn every dead worker whose
        backoff has elapsed (and whose respawn budget remains).
        Returns the shard indices respawned this pass."""
        if self._stopping:
            return []
        respawned: "list[int]" = []
        now = self.clock()
        with self._lock:
            rows = list(self._procs.items())
        for i, proc in rows:
            if proc is not None and proc.poll() is None:
                continue  # alive
            with self._lock:
                restarts = self._restarts.get(i, 0)
                if (
                    self.max_respawns is not None
                    and restarts >= self.max_respawns
                ):
                    continue
                due = self._next_spawn_at.get(i)
                if due is None:
                    backoff = min(
                        self.RESPAWN_BACKOFF_S * (2 ** restarts),
                        self.RESPAWN_BACKOFF_MAX_S,
                    )
                    self._next_spawn_at[i] = now + backoff
                    continue
                if now < due:
                    continue
                self._restarts[i] = restarts + 1
            self._spawn(i)
            respawned.append(i)
        return respawned

    def alive(self) -> int:
        with self._lock:
            return sum(
                1
                for p in self._procs.values()
                if p is not None and p.poll() is None
            )

    def kill(self, i: int, sig: "int | None" = None) -> None:
        """Hard-kill one worker (chaos surface: SIGKILL by default)."""
        import signal as _signal

        with self._lock:
            proc = self._procs.get(i)
        if proc is None or proc.poll() is not None:
            return
        proc.send_signal(sig if sig is not None else _signal.SIGKILL)

    def stop(self, *, timeout_s: float = 10.0) -> None:
        """Graceful teardown: SIGTERM everyone, wait, then SIGKILL the
        stragglers. No respawns after this."""
        import signal as _signal

        self._stopping = True
        with self._lock:
            procs = [
                p
                for p in self._procs.values()
                if p is not None and p.poll() is None
            ]
        for p in procs:
            try:
                p.send_signal(_signal.SIGTERM)
            except (OSError, ValueError):
                pass
        deadline = self.clock() + timeout_s
        for p in procs:
            remaining = max(deadline - self.clock(), 0.1)
            try:
                p.wait(timeout=remaining)
            except Exception:  # noqa: BLE001 — straggler: escalate below
                try:
                    p.kill()
                    p.wait(timeout=5.0)
                except Exception:  # noqa: BLE001 — already gone
                    pass

    def debug(self) -> "list[dict]":
        with self._lock:
            rows = []
            for i in sorted(self._procs):
                p = self._procs[i]
                rows.append(
                    {
                        "shard": shard_name(i),
                        "pid": getattr(p, "pid", None),
                        "alive": bool(p is not None and p.poll() is None),
                        "restarts": self._restarts.get(i, 0),
                    }
                )
        return rows
