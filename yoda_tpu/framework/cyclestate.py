"""Per-cycle scratch state shared across extension points.

The analog of the upstream ``framework.CycleState`` the reference writes its
max-collection data into under key ``"Max"`` with explicit Lock/Unlock
(reference pkg/yoda/collection/collection.go:53-55) and whose entries must
implement ``Clone`` (collection.go:23-28). Same contract here; the lock is a
real RLock because binding and Permit approval run off the cycle thread.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Protocol, runtime_checkable


@runtime_checkable
class StateData(Protocol):
    def clone(self) -> "StateData": ...


# Scheduler shard-out (framework/shards.py): the cycle's owning shard,
# written by a sharded Scheduler at cycle start and read by the shared
# ChipAccountant's Reserve hook — a claim made under a shard tag is
# STAGED (pending the optimistic commit validation) instead of final.
# Absent on unsharded stacks, so shard_count=1 never stages anything.
SHARD_STATE_KEY = "yoda-shard/id"


@dataclass(frozen=True)
class ShardTag:
    shard: str

    def clone(self) -> "ShardTag":
        return self


class CycleState:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._data: dict[str, StateData] = {}

    def write(self, key: str, value: StateData) -> None:
        with self._lock:
            self._data[key] = value

    def read(self, key: str) -> StateData:
        with self._lock:
            try:
                return self._data[key]
            except KeyError:
                raise KeyError(f"no state for key {key!r} in CycleState") from None

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._data

    def clone(self) -> "CycleState":
        c = CycleState()
        with self._lock:
            for k, v in self._data.items():
                c._data[k] = v.clone()
        return c
