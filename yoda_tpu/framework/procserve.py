"""Multi-process shard serve: GIL-free scheduling over the durable
claim journal (ISSUE 19).

PR 14's shard-out runs N serve loops as THREADS in one interpreter, so
aggregate throughput saturates against the GIL once binds stop being
I/O. This module splits the lanes across OS processes while keeping the
crash-consistency story of the durable claim journal (PR 18) intact:

- The PARENT control-plane process keeps the global lane, the
  journal-owning ChipAccountant (the single CommitLog writer), the
  reconciler/rebalancer/nodehealth loops, and the metrics server.
- Each SHARD WORKER process runs its own informer/queue/BindExecutor
  serve loop over its rendezvous partition (a pure function of
  ``shard_count`` — workers compute routing independently, zero
  coordination) and reaches the commit point through the thin commit
  RPC below: stage-at-Reserve, first-staged-wins ``commit``,
  rollback/release. Every decision is journaled by the parent before it
  applies, so a ``kill -9``'d worker's staged residue is recovered by
  journal replay plus the reconciler's warm path, and a replacement
  worker warm-starts exactly like a promoted standby.

Wire protocol: one request, one response, one persistent connection
per worker (the serve loop's stage/commit calls serialize on it, which
is the ordering the optimistic protocol wants anyway), behind the
:class:`CommitTransport` seam (ISSUE 20) — newline-delimited JSON over
a local Unix domain socket (``kind="unix"``, the PR 19 wire format,
byte-identical), or length-prefixed JSON over TCP (``kind="tcp"``,
the multi-host path: ``commit_listen`` / ``commit_endpoint`` knobs)
with connect/read deadlines so a flapping link degrades to refused
commits, never a hung serve loop. The parent handles each connection
on its own daemon thread; handler work is a dict probe plus one
accountant call, so the transport — not the GIL — is the only
serialization point workers share.

Epoch term: every response is stamped with the parent's integer term
(bumped by standby promotion, journal/tail.py). The check is
bidirectional — a worker refuses any parent whose stamped term
REGRESSES below the highest it has seen, and a deposed parent refuses
any state-mutating request carrying a NEWER term than its own (the
classic fencing token: a stale parent's lingering socket can keep
answering, but it can never journal a commit again).

Fencing: a worker binds only while :class:`WorkerFence` says so —
leadership/resync verdict shipped back on every heartbeat AND parent
liveness (heartbeat freshness, term monotonicity, and — local
transport only — a ``getppid`` re-parent check), so orphaned workers
stop binding even when the parent dies without a word. Fail-closed: a
worker that cannot hear the parent is fenced. Remote (TCP) workers
skip the ``getppid`` check: across machines it fences on the WRONG
parent — their fence is heartbeat verdict + term + staleness only.

The yodalint ``journal-discipline`` pass recognizes exactly one
non-accountant module on the commit path: :class:`CommitRPCServer`'s
handlers in this file. Everything else — the client, the worker
entries — must go through the accountant's public surface.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import socket
import struct
import sys
import threading
import time

from yoda_tpu.cluster.retry import BackoffPolicy


class CommitRPCError(RuntimeError):
    """A commit RPC failed (socket death, parent refusal, a handler
    error, or a term fence). Callers treat it as a refused decision —
    never as state."""


def _encode(msg: dict) -> bytes:
    return json.dumps(msg, separators=(",", ":")).encode() + b"\n"


# --- the CommitTransport seam (ISSUE 20) ---

# TCP frame header: 4-byte LE payload length. Bounded so a corrupt
# header cannot allocate unbounded memory — the largest legitimate
# frame is a 100k-claim snapshot ship (~10 MB); 64 MiB is headroom.
_TCP_HDR = struct.Struct("<I")
_TCP_MAX_FRAME = 64 * 1024 * 1024


class UnixTransport:
    """The PR 19 wire format, byte-identical: newline-delimited JSON
    over a local AF_UNIX stream socket."""

    kind = "unix"

    def __init__(self, path: str) -> None:
        self.path = path

    def listen(self) -> socket.socket:
        try:
            os.unlink(self.path)
        except OSError:
            pass
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.bind(self.path)
        s.listen(64)
        return s

    def connect(self, timeout_s: float) -> socket.socket:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(timeout_s)
        s.connect(self.path)
        return s

    def cleanup(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def endpoint(self) -> str:
        return self.path

    def send(self, sock: socket.socket, msg: dict) -> None:
        sock.sendall(_encode(msg))

    def recv(self, rfile) -> "bytes | None":
        """One framed payload from the buffered reader; None on EOF."""
        line = rfile.readline()
        return line if line else None


class TcpTransport:
    """Length-prefixed JSON over TCP — the multi-host commit path.

    ``[4-byte LE length][payload]`` framing (newline framing would
    forbid newlines inside snapshot ships and pay a scan per frame).
    Connect and read deadlines are mandatory: a half-open link must
    surface as a timed-out read (= a refused commit) on the worker,
    never a hung serve loop. ``TCP_NODELAY`` is set on both sides — the
    protocol is strict request/response, so Nagle only adds latency."""

    kind = "tcp"

    def __init__(
        self, host: str, port: int, *, connect_timeout_s: float = 5.0
    ) -> None:
        self.host = host
        self.port = int(port)
        self.connect_timeout_s = connect_timeout_s

    def listen(self) -> socket.socket:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(64)
        # port 0 = kernel-assigned (tests): record what we actually got
        # so ``endpoint()`` hands workers a reachable address.
        self.port = s.getsockname()[1]
        return s

    def connect(self, timeout_s: float) -> socket.socket:
        s = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s
        )
        s.settimeout(timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def cleanup(self) -> None:
        pass  # nothing on disk

    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def send(self, sock: socket.socket, msg: dict) -> None:
        payload = json.dumps(msg, separators=(",", ":")).encode()
        sock.sendall(_TCP_HDR.pack(len(payload)) + payload)

    def recv(self, rfile) -> "bytes | None":
        hdr = rfile.read(_TCP_HDR.size)
        if not hdr:
            return None  # clean EOF
        if len(hdr) < _TCP_HDR.size:
            return None  # torn header mid-close
        (length,) = _TCP_HDR.unpack(hdr)
        if length == 0 or length > _TCP_MAX_FRAME:
            raise OSError(f"commit transport: bad frame length {length}")
        payload = rfile.read(length)
        if len(payload) < length:
            return None  # connection died mid-frame
        return payload


def make_transport(endpoint: str):
    """``"host:port"`` (optionally ``tcp://``-prefixed) builds the TCP
    transport; anything else is an AF_UNIX socket path. The one parse
    the server, the client, and cli.py all share — the knobs
    (``commit_listen`` / ``commit_endpoint``) are plain strings."""
    ep = endpoint[6:] if endpoint.startswith("tcp://") else endpoint
    if not ep.startswith("/"):
        host, sep, port = ep.rpartition(":")
        if sep and host and port.isdigit():
            return TcpTransport(host, int(port))
    return UnixTransport(endpoint)


class CommitRPCServer:
    """Parent-side commit RPC endpoint wrapping the journal-owning
    accountant. One daemon accept thread + one daemon handler thread
    per worker connection; every handler is a dict probe plus one
    accountant call (which journals write-ahead under its own lock).

    Also the parent's worker registry: heartbeats carry each worker's
    pid/queue-depth/cycle/bind snapshot, and ``debug()`` serves the
    ``/debug/shards`` process view (pid, lane, last-heartbeat, staged
    count). ``fence_fn`` is the parent's serve fence — leadership AND
    warm-start resync — refusing commits while fenced and echoed to
    workers on every heartbeat, so workers fence on it too.

    ``socket_path`` is really an endpoint string: an AF_UNIX path
    (default, single-host) or ``"host:port"`` for the TCP transport —
    ``make_transport`` decides. ``term`` is the parent's epoch term,
    stamped on every response; ``set_term`` installs a promoted term.
    """

    def __init__(
        self,
        accountant,
        socket_path: str,
        *,
        metrics=None,
        fence_fn=None,
        expected_workers: int = 0,
        clock=time.monotonic,
        term: int = 1,
    ) -> None:
        self.accountant = accountant
        self.socket_path = socket_path
        self.transport = make_transport(socket_path)
        self.term = int(term)
        self.metrics = metrics
        self.fence_fn = fence_fn
        self.expected_workers = int(expected_workers)
        self.clock = clock
        self.workers: dict[str, dict] = {}   # lane -> registry row
        self.reports: dict[str, dict] = {}   # lane -> shipped result
        self._lock = threading.Lock()
        self._listener: "socket.socket | None" = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._stopping = False
        # Start-line barrier (bench/test synchronization): workers park
        # here until every expected worker arrives, so process startup
        # skew never pollutes a timed drain.
        self._barrier_cond = threading.Condition()
        self._barrier_counts: dict[str, int] = {}

    # --- lifecycle ---

    def start(self) -> None:
        self._listener = self.transport.listen()
        if self.metrics is not None:
            self.metrics.commit_term.set(float(self.term))
        t = threading.Thread(
            target=self._accept_loop, name="commit-rpc-accept", daemon=True
        )
        self._threads.append(t)
        t.start()

    @property
    def endpoint(self) -> str:
        """The reachable endpoint string (TCP reports the kernel-assigned
        port after a ``:0`` bind) — what the parent hands its workers."""
        return self.transport.endpoint()

    def set_term(self, term: int) -> None:
        """Install a new epoch term (the promotion path): every response
        from here on is stamped with it, and any request still carrying
        an older worker-side term is simply behind — only requests
        carrying a NEWER term than ours mark US as the stale parent."""
        self.term = int(term)
        if self.metrics is not None:
            self.metrics.commit_term.set(float(self.term))

    def stop(self) -> None:
        self._stopping = True
        with self._barrier_cond:
            self._barrier_cond.notify_all()
        if self._listener is not None:
            # shutdown BEFORE close: a thread blocked in accept() holds
            # the kernel file description open past close(), leaving the
            # port in LISTEN forever — the promoted standby could then
            # never bind the same address. shutdown wakes the accept.
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        self.transport.cleanup()

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed: shutdown
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name="commit-rpc-conn",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        transport = self.transport
        try:
            while True:
                try:
                    raw = transport.recv(rfile)
                except OSError:
                    return  # torn frame / dead socket: drop the conn
                if raw is None or self._stopping:
                    return
                t0 = time.perf_counter()
                try:
                    req = json.loads(raw)
                    resp = self._dispatch(req)
                except Exception as e:  # noqa: BLE001 — a bad request must not kill the conn
                    req = {}
                    resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                resp.setdefault("term", self.term)
                op = str(req.get("op", "?"))
                lane = str(req.get("shard", ""))
                if self.metrics is not None:
                    self.metrics.commit_rpc_calls.inc(
                        op=op, shard=lane, transport=transport.kind
                    )
                    self.metrics.commit_rpc_latency.observe(
                        (time.perf_counter() - t0) * 1e3,
                        op=op, transport=transport.kind,
                    )
                try:
                    transport.send(conn, resp)
                except OSError:
                    return  # worker died mid-reply: its residue is journaled
        finally:
            try:
                rfile.close()
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    # --- dispatch ---

    # Ops that mutate claim state: all of them carry the term fence —
    # a request stamped with a NEWER term than ours proves a promoted
    # parent exists somewhere, so WE are the stale side of a partition
    # and must refuse before touching the accountant or the journal.
    _MUTATING_OPS = frozenset(
        {"stage", "commit", "release", "residue", "residue_sync"}
    )

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        lane = str(req.get("shard", ""))
        if op in self._MUTATING_OPS:
            worker_term = int(req.get("term", 0) or 0)
            if worker_term > self.term:
                why = (
                    f"stale parent: term {self.term} deposed by "
                    f"term {worker_term}"
                )
                if op == "commit":
                    # Shaped like a fence refusal, not an error: the
                    # worker rolls back + requeues, same as any refused
                    # commit. Nothing is journaled here.
                    return {"ok": True, "committed": False, "why": why}
                return {"ok": False, "error": why}
        if op == "stage":
            seq = self.accountant.stage(
                req["uid"],
                req["node"],
                int(req["chips"]),
                lane or req.get("lane", ""),
                req.get("gang", ""),
            )
            return {"ok": True, "seq": seq}
        if op == "commit":
            # The parent's own leader fence gates the commit point: a
            # fenced ex-leader's accountant must not validate placements
            # the new leader no longer backs (the worker additionally
            # fences itself on the heartbeat verdict, but that check is
            # advisory-latency — THIS one is authoritative).
            if self.fence_fn is not None and not bool(self.fence_fn()):
                return {
                    "ok": True,
                    "committed": False,
                    "why": "parent fenced (not leading or not resynced)",
                }
            committed, why = self.accountant.commit_staged(
                list(req.get("uids", ()))
            )
            if not committed and self.metrics is not None:
                self.metrics.commit_rpc_conflicts.inc(shard=lane)
            return {"ok": True, "committed": committed, "why": why}
        if op == "release":
            # The parent decides rollback-vs-release from its OWN
            # (authoritative, journal-backed) claim state.
            self.accountant.release(req["uid"])
            return {"ok": True}
        if op == "residue":
            return {
                "ok": True,
                "found": self.accountant.commit_residue(req["uid"]),
            }
        if op == "hello":
            self._note_worker(lane, req, hello=True)
            return {"ok": True}
        if op == "heartbeat":
            self._note_worker(lane, req)
            serve = True if self.fence_fn is None else bool(self.fence_fn())
            return {"ok": True, "serve": serve}
        if op == "report":
            with self._lock:
                self.reports[lane] = dict(req.get("result") or {})
            return {"ok": True}
        if op == "barrier":
            return self._op_barrier(req)
        if op == "tail":
            return self._op_tail(req)
        if op == "residue_sync":
            return self._op_residue_sync(lane, req)
        if op == "debug":
            return {"ok": True, "workers": self.debug()["workers"]}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _op_tail(self, req: dict) -> dict:
        """Journal shipping (the hot standby's feed, journal/tail.py):
        frames appended after ``since`` served straight from the
        journal's in-memory ship ring, or a full mirror snapshot when
        the ring no longer reaches back (a fresh follower, or one that
        fell too far behind)."""
        journal = getattr(self.accountant, "journal", None)
        if journal is None or not hasattr(journal, "frames_since"):
            return {
                "ok": False,
                "error": "journal shipping needs a journal-backed parent",
            }
        got = journal.frames_since(int(req.get("since", 0)))
        if got is None:
            snap = journal.ship_state()
            return {"ok": True, "snapshot": snap, "tail_seq": snap["tail_seq"]}
        frames, tail_seq = got
        return {"ok": True, "frames": frames, "tail_seq": tail_seq}

    def _op_residue_sync(self, lane: str, req: dict) -> dict:
        """Reconcile a reconnecting worker's staged-intent log against
        this (possibly just-promoted) parent's claim state — partition
        residue repaired NOW instead of waiting for the reconciler's
        warm path. Set semantics over the shipped uids:

        - a parent-side STAGED claim for this lane absent from the
          shipped set was abandoned by the worker: released here;
        - a shipped uid the parent holds STAGED stays staged;
        - a shipped uid the parent holds COMMITTED tells the worker to
          finalize its mirror (verdict ``committed``);
        - a shipped uid the parent never heard of (staged under the old
          term, lost in the partition) is adopted through the normal
          validated stage path — fresh seq, so first-staged-wins
          ordering stays sound.
        """
        shipped = {str(row["uid"]): row for row in req.get("staged", ())}
        staged_now = self.accountant.staged_uids()
        for uid, owner in staged_now.items():
            if owner == lane and uid not in shipped:
                self.accountant.release(uid)
        verdicts: dict[str, str] = {}
        for uid, row in shipped.items():
            if uid in staged_now:
                verdicts[uid] = "staged"
            elif self.accountant.has_claim(uid):
                verdicts[uid] = "committed"
            else:
                self.accountant.stage(
                    uid, str(row["node"]), int(row["chips"]), lane,
                    str(row.get("gang", "")),
                )
                verdicts[uid] = "staged"
        return {"ok": True, "verdicts": verdicts}

    def _note_worker(self, lane: str, req: dict, *, hello: bool = False) -> None:
        now = self.clock()
        with self._lock:
            row = self.workers.setdefault(lane, {"lane": lane})
            row["pid"] = int(req.get("pid", row.get("pid", 0)))
            row["last_heartbeat"] = now
            if hello:
                row["connected_at"] = now
            for k in ("queue_depth", "cycles", "binds", "staged"):
                if k in req:
                    row[k] = int(req[k])

    def _op_barrier(self, req: dict) -> dict:
        name = str(req.get("name", "default"))
        deadline = time.monotonic() + float(req.get("timeout_s", 120.0))
        need = max(int(req.get("expected", self.expected_workers)), 1)
        with self._barrier_cond:
            self._barrier_counts[name] = (
                self._barrier_counts.get(name, 0) + 1
            )
            self._barrier_cond.notify_all()
            while self._barrier_counts[name] < need and not self._stopping:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {
                        "ok": False,
                        "error": (
                            f"barrier {name!r}: "
                            f"{self._barrier_counts[name]}/{need} arrived"
                        ),
                    }
                self._barrier_cond.wait(remaining)
        return {"ok": True}

    # --- introspection (GET /debug/shards) ---

    def debug(self) -> dict:
        """The process view: one row per worker lane — pid, lane,
        seconds since the last heartbeat, the worker's last serve-loop
        snapshot, and the parent accountant's live staged count for the
        lane (counted HERE, not trusted from the heartbeat: the staged
        residue of a dead worker must stay visible)."""
        staged_by_lane: dict[str, int] = {}
        for _uid, lane in self.accountant.staged_uids().items():
            staged_by_lane[lane] = staged_by_lane.get(lane, 0) + 1
        now = self.clock()
        with self._lock:
            rows = []
            for lane, row in sorted(self.workers.items()):
                hb = row.get("last_heartbeat")
                rows.append(
                    {
                        "lane": lane,
                        "pid": row.get("pid", 0),
                        "heartbeat_age_s": (
                            round(now - hb, 3) if hb is not None else None
                        ),
                        "queue_depth": row.get("queue_depth", 0),
                        "cycles": row.get("cycles", 0),
                        "binds": row.get("binds", 0),
                        "staged": staged_by_lane.get(lane, 0),
                    }
                )
        return {"enabled": True, "mode": "process", "workers": rows}


class CommitRPCClient:
    """Worker-side commit RPC client: one persistent connection, one
    request in flight (the serve loop's decisions serialize on the
    lane anyway). Reconnects lazily after a socket death — the parent
    respawning is indistinguishable from a blip — through full-jitter
    backoff (cluster/retry.py policy) so a dead parent is never
    hammered by a tight reconnect loop; the ``stop_event`` interrupts a
    pending backoff at once (SIGTERM must not wait it out). Raises
    :class:`CommitRPCError` when the parent cannot be reached, which
    every caller treats as a refused decision.

    Term tracking: every request carries the highest parent term this
    client has seen; every response's stamped term must be monotonic.
    A response whose term REGRESSES (a deposed parent's lingering
    socket still answering) raises — the call reads as refused and the
    connection drops, so the next call re-resolves the endpoint."""

    def __init__(
        self,
        socket_path: str,
        *,
        shard: str = "",
        timeout_s: float = 10.0,
        stop_event: "threading.Event | None" = None,
        reconnect_policy: "BackoffPolicy | None" = None,
        rng: "random.Random | None" = None,
    ) -> None:
        self.socket_path = socket_path
        self.transport = make_transport(socket_path)
        self.shard = shard
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock: "socket.socket | None" = None
        self._rfile = None
        self._stop = stop_event
        self._policy = reconnect_policy or BackoffPolicy(
            attempts=0, base_s=0.05, cap_s=2.0
        )
        self._rng = rng or random.Random()
        self._failures = 0      # consecutive transport failures
        self._term_seen = 0     # highest parent term observed

    @property
    def term_seen(self) -> int:
        return self._term_seen

    def _connect_locked(self) -> None:
        if self._failures:
            # Full-jitter reconnect backoff: attempt k (k = consecutive
            # failures - 1) sleeps uniform(0, min(base * 2**k, cap)).
            # The stop event firing mid-sleep aborts immediately as a
            # refused call — shutdown never waits a backoff out.
            delay = self._policy.delay_s(
                min(self._failures - 1, 16), self._rng
            )
            if self._stop is not None:
                if self._stop.wait(delay):
                    raise CommitRPCError(
                        "commit rpc: stopping during reconnect backoff"
                    )
            elif delay > 0:
                time.sleep(delay)
        s = self.transport.connect(self.timeout_s)
        self._sock = s
        self._rfile = s.makefile("rb")

    def _drop_locked(self) -> None:
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass
            self._rfile = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(self, op: str, **fields) -> dict:
        req = {"op": op, "shard": self.shard}
        req.update(fields)
        with self._lock:
            req.setdefault("term", self._term_seen)
            try:
                if self._sock is None:
                    # yodalint: ok lock-discipline the reconnect backoff sleeps under the client lock ON PURPOSE: the lock serializes exactly one reconnect attempt per client, the wait is stop-interruptible and capped (2 s), and every other would-be caller is headed for the same dead endpoint anyway
                    self._connect_locked()
                self.transport.send(self._sock, req)
                raw = self.transport.recv(self._rfile)
            except OSError as e:
                self._drop_locked()
                self._failures += 1
                raise CommitRPCError(f"commit rpc {op}: {e}") from e
            if raw is None:
                self._drop_locked()
                self._failures += 1
                raise CommitRPCError(
                    f"commit rpc {op}: connection closed by parent"
                )
            self._failures = 0
            try:
                resp = json.loads(raw)
            except ValueError as e:
                raise CommitRPCError(f"commit rpc {op}: bad reply") from e
            term = resp.get("term")
            if term is not None:
                term = int(term)
                if term < self._term_seen:
                    # Not a transport failure (no backoff bump): the
                    # endpoint answered — it is just no longer the
                    # parent. Drop the conn so the next call re-resolves.
                    self._drop_locked()
                    raise CommitRPCError(
                        f"commit rpc {op}: stale parent term {term} < "
                        f"{self._term_seen} (fenced)"
                    )
                self._term_seen = term
        if not resp.get("ok"):
            raise CommitRPCError(
                f"commit rpc {op}: {resp.get('error', 'refused')}"
            )
        return resp

    def close(self) -> None:
        with self._lock:
            self._drop_locked()

    # --- the RemoteAccountant collaborator surface ---

    def stage(self, uid, node, chips, shard, gang="") -> int:
        return int(
            self.call(
                "stage", uid=uid, node=node, chips=int(chips),
                shard=shard, gang=gang,
            )["seq"]
        )

    def commit(self, uids) -> "tuple[bool, str]":
        resp = self.call("commit", uids=list(uids))
        return bool(resp["committed"]), str(resp.get("why", ""))

    def release(self, uid) -> None:
        self.call("release", uid=uid)

    def residue(self, uid) -> bool:
        return bool(self.call("residue", uid=uid)["found"])

    def residue_sync(self, staged) -> "dict[str, str]":
        """Ship the worker's staged-intent log to a (newly promoted)
        parent; returns per-uid verdicts (``staged`` / ``committed``)."""
        resp = self.call("residue_sync", staged=list(staged))
        return dict(resp.get("verdicts") or {})

    def tail(self, since: int) -> dict:
        """One journal-shipping poll (the standby tailer's feed)."""
        return self.call("tail", since=int(since))

    # --- worker lifecycle surface ---

    def hello(self, pid: "int | None" = None) -> None:
        self.call("hello", pid=pid if pid is not None else os.getpid())

    def heartbeat(self, info: "dict | None" = None) -> bool:
        return bool(
            self.call("heartbeat", pid=os.getpid(), **(info or {}))["serve"]
        )

    def barrier(self, name: str = "default", *, timeout_s: float = 120.0,
                expected: "int | None" = None) -> None:
        fields = {"name": name, "timeout_s": timeout_s}
        if expected is not None:
            fields["expected"] = expected
        self.call("barrier", **fields)

    def report(self, result: dict) -> None:
        self.call("report", result=result)


class WorkerFence:
    """Per-worker serve fence: leadership AND parent liveness.

    ``serving()`` — wired as the worker scheduler's ``fence_fn`` — is
    True only while ALL hold:

    - the parent's last heartbeat verdict said serve (leadership held
      and the global warm-start resync complete),
    - that verdict is FRESH (within ``liveness_s`` — a worker that
      cannot hear the parent is fenced, fail-closed), and
    - LOCAL transport only: the parent process is still our parent
      (``getppid`` unchanged; a dead parent re-parents us, and an
      orphaned worker must stop binding even though its socket may
      linger). A REMOTE (TCP) worker was never forked by the parent —
      across machines the check fences on the WRONG parent, so it is
      skipped: term monotonicity (the client refuses a regressing
      term, which then reads as staleness here) plus heartbeat
      freshness are the remote fence, still fail-closed.

    The heartbeat loop runs on its own daemon thread and ships the
    worker's serve-loop snapshot (``info_fn``) for ``/debug/shards``.
    ``on_orphaned`` (optional) fires once when the parent is detected
    gone — production workers use it to exit instead of idling fenced.
    ``on_new_term`` (optional) fires when a heartbeat lands under a
    HIGHER parent term than before (standby promotion happened while
    we were partitioned) — production workers use it to ship their
    staged-intent log (``residue_sync``); a failed sync re-arms so the
    next beat retries.
    """

    def __init__(
        self,
        client: CommitRPCClient,
        *,
        shard: str,
        liveness_s: float = 3.0,
        period_s: float = 0.5,
        info_fn=None,
        on_orphaned=None,
        on_new_term=None,
        remote: "bool | None" = None,
        clock=time.monotonic,
    ) -> None:
        self.client = client
        self.shard = shard
        self.liveness_s = liveness_s
        self.period_s = period_s
        self.info_fn = info_fn
        self.on_orphaned = on_orphaned
        self.on_new_term = on_new_term
        if remote is None:
            remote = (
                getattr(getattr(client, "transport", None), "kind", "unix")
                == "tcp"
            )
        self.remote = bool(remote)
        self.clock = clock
        self._ppid = os.getppid()
        self._term = 0
        self._last_ok: "float | None" = None
        self._serve = False
        self._orphaned = False
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name=f"worker-fence-{self.shard}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.beat()
            self._stop.wait(self.period_s)

    def beat(self) -> None:
        """One heartbeat round-trip (the loop's body; tests drive it
        directly)."""
        if not self.remote and os.getppid() != self._ppid:
            self._orphaned = True
            self._serve = False
            if self.on_orphaned is not None:
                cb, self.on_orphaned = self.on_orphaned, None
                cb()
            return
        info = {}
        if self.info_fn is not None:
            try:
                info = self.info_fn()
            except Exception:  # noqa: BLE001 — a sick snapshot must not stop heartbeats
                info = {}
        try:
            self._serve = self.client.heartbeat(info)
            self._last_ok = self.clock()
        except CommitRPCError:
            # Leave _last_ok as-is: staleness fences after liveness_s.
            return
        term = getattr(self.client, "term_seen", 0)
        if term > self._term:
            prev, self._term = self._term, term
            # prev == 0 is the FIRST successful beat, not a promotion.
            if prev != 0 and self.on_new_term is not None:
                try:
                    self.on_new_term(term)
                except CommitRPCError:
                    self._term = prev  # re-arm: next beat retries the sync

    def serving(self) -> bool:
        if self._orphaned:
            return False
        if not self.remote and os.getppid() != self._ppid:
            return False
        if not self._serve or self._last_ok is None:
            return False
        return (self.clock() - self._last_ok) <= self.liveness_s


# --- worker process entries ---


def _worker_info_fn(stack):
    def info() -> dict:
        return {
            "queue_depth": len(stack.queue),
            "cycles": len(stack.scheduler.stats.results),
            "binds": stack.scheduler.stats.binds,
            "staged": stack.accountant.staged_count(),
        }

    return info


def _build_worker_stack(cluster, config, client, lane, *, stop_event=None):
    """One shard stack around a RemoteAccountant — the worker-process
    analog of one build_sharded_stacks lane. The accountant's watcher
    registers BEFORE build_stack's informer (the build_sharded_stacks
    discipline: reservation releases precede the informer's view of the
    same event)."""
    from yoda_tpu.plugins.yoda.accounting import RemoteAccountant
    from yoda_tpu.standalone import build_stack

    accountant = RemoteAccountant(
        client, scheduler_name=config.scheduler_name
    )
    cluster.add_watcher(accountant.handle)
    stack = build_stack(
        cluster=cluster,
        config=config,
        accountant=accountant,
        stop_event=stop_event,
        shard=lane,
    )
    return stack


def _run_spec_worker(spec: dict) -> int:
    """Bench/test worker: build a private FakeCluster fleet from the
    spec (the parent pre-partitioned hosts and pre-routed pods — the
    rendezvous map is a pure function, so the split is exactly what the
    in-process router would compute), drain a warmup round, park at the
    start barrier until every worker is built, then drain the timed
    round and ship the measurements back over the RPC."""
    from yoda_tpu.agent import FakeTpuAgent
    from yoda_tpu.api.types import PodSpec
    from yoda_tpu.cluster.fake import FakeCluster
    from yoda_tpu.config import SchedulerConfig
    from yoda_tpu.framework.shards import shard_name

    lane = shard_name(int(spec.get("shard_index", 0)))
    client = CommitRPCClient(spec["socket"], shard=lane)
    client.hello()
    config = SchedulerConfig.from_dict(dict(spec.get("config") or {}))
    cluster = FakeCluster(
        bind_latency_s=float(spec.get("bind_latency_s", 0.0))
    )
    stack = _build_worker_stack(cluster, config, client, lane)
    agent = FakeTpuAgent(cluster)
    for h in spec.get("hosts", ()):
        agent.add_host(
            h["name"],
            generation=h.get("generation", "v5e"),
            chips=int(h.get("chips", 8)),
        )
    agent.publish_all()

    def make_pods(rows):
        return [
            PodSpec(p["name"], labels=dict(p.get("labels") or {}))
            for p in rows
        ]

    def drain(pods, timeout_s=240.0) -> float:
        for pod in pods:
            cluster.create_pod(pod)
        t0 = time.monotonic()
        stack.scheduler.run_until_idle(max_wall_s=timeout_s)
        dt = time.monotonic() - t0
        bound = [p for p in cluster.list_pods() if p.node_name]
        if len(bound) != len(pods):
            raise RuntimeError(
                f"{lane}: {len(bound)}/{len(pods)} bound"
            )
        for p in bound:
            cluster.delete_pod(p.key)
        stack.scheduler.run_until_idle(max_wall_s=30.0)
        return dt

    heartbeat_info = _worker_info_fn(stack)
    warmup = make_pods(spec.get("warmup_pods", ()))
    if warmup:
        drain(warmup)
    timed = make_pods(spec.get("pods", ()))
    client.barrier(
        "timed",
        expected=spec.get("workers"),
        timeout_s=float(spec.get("barrier_timeout_s", 300.0)),
    )
    wall_s = drain(timed)
    slo = stack.metrics.slo.evaluate(time.monotonic())
    client.report(
        {
            "lane": lane,
            "pid": os.getpid(),
            "pods": len(timed),
            "wall_s": round(wall_s, 4),
            "pods_per_s": round(len(timed) / wall_s, 2) if wall_s else 0.0,
            "admission_p99_s": slo["fleet"]["admission_wait_p99_s"],
            "commit_conflicts": stack.accountant.commit_conflicts,
            "staged_residue": stack.accountant.staged_count(),
            **heartbeat_info(),
        }
    )
    stack.gang.close()
    client.close()
    return 0


def _run_drive_worker(spec: dict) -> int:
    """Scripted chaos driver: stage the spec'd claims over the RPC,
    announce STAGED on stdout, then execute stdin commands (COMMIT /
    RELEASE / EXIT) until told to stop. The chaos sweep SIGKILLs this
    process at deterministic points — at the STAGED barrier, or
    mid-commit while the parent holds the commit gate closed — to plant
    staged residue whose recovery the test then proves."""
    lane = spec["shard"]
    client = CommitRPCClient(spec["socket"], shard=lane)
    client.hello()
    for c in spec.get("claims", ()):
        client.stage(
            c["uid"], c["node"], int(c["chips"]), lane, c.get("gang", "")
        )
    print("STAGED", flush=True)
    for line in sys.stdin:
        cmd = line.strip().split(" ", 1)
        if not cmd[0]:
            continue
        if cmd[0] == "COMMIT":
            uids = (
                cmd[1].split(",")
                if len(cmd) > 1
                else [c["uid"] for c in spec.get("claims", ())]
            )
            try:
                ok, why = client.commit(uids)
            except CommitRPCError as e:
                ok, why = False, str(e)
            print(f"COMMITTED {int(ok)} {why}", flush=True)
        elif cmd[0] == "RELEASE" and len(cmd) > 1:
            client.release(cmd[1])
            print("RELEASED", flush=True)
        elif cmd[0] == "EXIT":
            break
    client.close()
    return 0


def _run_kube_worker(args) -> int:
    """Production worker (spawned by cli.py under shard_mode=process):
    one shard lane against the real API server, fenced on leadership
    AND parent liveness. Exits when the parent dies (orphan fencing) or
    on SIGTERM; staged residue either way is the parent's to recover
    via journal replay + reconciliation."""
    from yoda_tpu.cli import (
        _build_kube_cluster,
        _init_jax,
        _install_stop_handlers,
        _load_config,
    )
    from yoda_tpu.framework.shards import ShardMap, ShardRouter, shard_name

    config = _load_config(args.config)
    _init_jax(args.jax_platform)
    idx = int(args.shard_index)
    lane = shard_name(idx)
    stop = threading.Event()
    _install_stop_handlers(stop)
    client = CommitRPCClient(args.socket, shard=lane, stop_event=stop)
    client.hello()
    cluster = _build_kube_cluster()
    # The rendezvous map is a pure function of shard_count: this worker
    # computes its partition + routing locally, no coordination.
    shard_map = ShardMap(int(args.shard_count))
    router = ShardRouter(shard_map)
    cluster.add_watcher(router.observe, batch_fn=router.observe_batch)
    from yoda_tpu.plugins.yoda.accounting import RemoteAccountant
    from yoda_tpu.standalone import build_stack

    accountant = RemoteAccountant(
        client, scheduler_name=config.scheduler_name
    )
    cluster.add_watcher(accountant.handle)
    stack = build_stack(
        cluster=cluster,
        config=config,
        accountant=accountant,
        stop_event=stop,
        shard=lane,
        node_filter_fn=shard_map.node_filter(idx),
        pod_route_fn=lambda pod: router.route(pod) == lane,
    )
    def _sync_residue(term: int) -> None:
        # Reconnected under a NEW parent term (a standby promoted while
        # this worker was partitioned): ship the local staged-intent
        # log so the promoted parent reconciles our residue immediately
        # instead of waiting for the reconciler's warm path. A raised
        # CommitRPCError re-arms the fence to retry on the next beat.
        accountant.apply_residue_verdicts(
            client.residue_sync(accountant.staged_intents())
        )

    fence = WorkerFence(
        client,
        shard=lane,
        info_fn=_worker_info_fn(stack),
        on_orphaned=stop.set,
        on_new_term=_sync_residue,
    )
    stack.scheduler.fence_fn = fence.serving
    fence.start()
    print(
        f"yoda-tpu-scheduler: shard worker {lane} serving "
        f"(pid={os.getpid()})",
        file=sys.stderr,
    )
    try:
        stack.scheduler.serve_forever(stop)
    finally:
        fence.stop()
        stack.gang.close()
        if stack.ingestor is not None:
            stack.ingestor.stop()
        client.close()
        cluster.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m yoda_tpu.framework.procserve",
        description="yoda-tpu shard worker process (shard_mode=process)",
    )
    ap.add_argument(
        "--serve-spec",
        help="bench/test worker: JSON spec file (private FakeCluster "
        "fleet, timed drain, result shipped over the commit RPC)",
    )
    ap.add_argument(
        "--drive",
        help="scripted chaos driver: JSON spec file (stage claims, then "
        "execute stdin COMMIT/RELEASE/EXIT commands)",
    )
    ap.add_argument("--config", help="scheduler config YAML (kube worker)")
    ap.add_argument(
        "--socket",
        help="parent commit RPC endpoint: AF_UNIX socket path, or "
        "host:port for the TCP transport (commit_listen)",
    )
    ap.add_argument("--shard-index", type=int, default=0)
    ap.add_argument("--shard-count", type=int, default=1)
    ap.add_argument("--jax-platform", default="cpu")
    args = ap.parse_args(argv)
    if args.serve_spec:
        with open(args.serve_spec) as f:
            return _run_spec_worker(json.load(f))
    if args.drive:
        with open(args.drive) as f:
            return _run_drive_worker(json.load(f))
    if not args.socket:
        ap.error("--socket is required for a kube shard worker")
    return _run_kube_worker(args)


if __name__ == "__main__":
    raise SystemExit(main())
