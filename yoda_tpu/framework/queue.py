"""Scheduling queue: active heap ordered by the QueueSort plugin, with
backoff for unschedulable pods and (ISSUE 10) per-tenant DRF fair queuing.

The reference supplies only the ordering function (``Less``, reference
pkg/yoda/sort/sort.go:8-18) and inherits the queue machinery (active /
backoff / unschedulable pools, event-driven re-activation) from upstream;
this module is the from-scratch equivalent of that machinery, grown a
tenant model the upstream framework (KEP-624) lacks entirely: the active
pool is sharded per tenant (``framework/tenancy.tenant_of`` — namespace,
overridable via the ``tpu/tenant`` label), and every pop draws from the
LOWEST dominant-resource-share tenant first (DRF over chips/HBM,
``TenantLedger.dominant_share``), so a flooding tenant's backlog cannot
starve anyone: each bind raises its share and pushes it behind the
tenants it was flooding past. Per-tenant quota admission parks over-quota
entries in the unresolvable pool with a why-pending verdict; they retire
when capacity frees (the freeing event's ``move_all_to_active`` re-admits
them through a fresh quota check). With no ``tenant_of`` hook (fairness
off, the default) everything lives under one tenant key and behavior is
bit-identical to the single-queue implementation.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from yoda_tpu.api.requests import gang_name_of
from yoda_tpu.api.types import PodSpec
from yoda_tpu.framework.interfaces import QueueSortPlugin

# Upstream kube-scheduler defaults: initial 1s, doubling, capped at 10s.
INITIAL_BACKOFF_S = 1.0
MAX_BACKOFF_S = 10.0

# Cluster-event reactivation (move_all_to_active) retries a pod IMMEDIATELY
# through this many attempts — preemptors re-binding after evictions, pods
# waiting on one freed slot, and test drains all resolve within a few — and
# respects the pod's backoff timer beyond it (upstream's
# moveAllToActiveOrBackoffQueue semantics). Without the cutoff, a busy
# cluster's event stream hot-loops every chronically unschedulable pod
# through a full scheduling cycle per event: measured 229 wasted dispatches
# per successful bind under churn (r4).
IMMEDIATE_RETRY_ATTEMPTS = 5


@dataclass
class QueuedPodInfo:
    pod: PodSpec
    attempts: int = 0
    added_unix: float = 0.0
    unschedulable_message: str = ""

    def backoff_seconds(self) -> float:
        # Exponent capped: a chronically-retried entry (forced drain
        # loops can push attempts into the thousands) must saturate at
        # MAX_BACKOFF_S, not overflow float range at 2**1024.
        exp = min(max(self.attempts - 1, 0), 10)
        return min(INITIAL_BACKOFF_S * (2 ** exp), MAX_BACKOFF_S)


class _HeapItem:
    """heapq adapter: delegates ordering to the QueueSort plugin, with a
    monotonic tiebreak so equal-priority pods stay FIFO."""

    __slots__ = ("qpi", "seq", "less")

    def __init__(self, qpi: QueuedPodInfo, seq: int, less: Callable) -> None:
        self.qpi = qpi
        self.seq = seq
        self.less = less

    def __lt__(self, other: "_HeapItem") -> bool:
        if self.less(self.qpi, other.qpi):
            return True
        if self.less(other.qpi, self.qpi):
            return False
        return self.seq < other.seq


class SchedulingQueue:
    def __init__(
        self,
        sort_plugin: QueueSortPlugin | None = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        immediate_retry_attempts: int = IMMEDIATE_RETRY_ATTEMPTS,
        tenant_of: "Callable[[PodSpec], str] | None" = None,
        share_fn: "Callable[[str], float] | None" = None,
        quota_fn: "Callable[[str, PodSpec], str | None] | None" = None,
        on_quota_park: "Callable[[QueuedPodInfo, str], None] | None" = None,
        shed_fn: "Callable[[PodSpec], str | None] | None" = None,
        on_shed: "Callable[[QueuedPodInfo, str], None] | None" = None,
    ) -> None:
        if sort_plugin is not None:
            self._less = sort_plugin.less
        else:
            self._less = lambda a, b: a.pod.creation_seq < b.pod.creation_seq
        self._clock = clock
        # Config immediate_retry_attempts: 0 = strict upstream semantics
        # (every event move respects backoff); higher trades retry-storm
        # exposure for lower latency on late-resolving pods.
        self.immediate_retry_attempts = immediate_retry_attempts
        # Tenant fair queuing (off when tenant_of is None — everything
        # shares the "" tenant and ordering is the classic single heap):
        # - tenant_of(pod): which tenant an entry bills to;
        # - share_fn(tenant): dominant resource share in [0,1] — pops
        #   draw from the LOWEST share first (DRF); missing/raising hook
        #   reads as share 0 (FIFO among tenants);
        # - quota_fn(tenant, pod): why-pending verdict when admitting the
        #   pod would exceed the tenant's quota (None = admit). Verdicted
        #   entries park in the unresolvable pool and re-enter through
        #   move_all_to_active when capacity frees;
        # - on_quota_park(qpi, why): observability callback (counter +
        #   pending index). Fired under the queue lock — must not
        #   re-enter the queue.
        self._tenant_of = tenant_of
        self._share_fn = share_fn
        self._quota_fn = quota_fn
        self.on_quota_park = on_quota_park
        self.quota_parks = 0  # total entries quota-parked (metrics)
        # Overload shed (ISSUE 15, yoda_tpu/overload.py): shed_fn(pod)
        # returns a why-pending message when the entry must PARK at pop
        # time instead of scheduling (the brownout ladder's SHED level) —
        # checked per ITEM (unlike quota_fn's per-tenant probe, the
        # verdict depends on the pod's tier), parking into the
        # unresolvable pool so the entry requeues on the ladder's
        # step-down (an explicit move_all_to_active) like any other
        # capacity event. on_shed(qpi, why) is the observability hook
        # (counter + overload-shed pending verdict), fired under the
        # queue lock — it must not re-enter the queue.
        self._shed_fn = shed_fn
        self.on_shed = on_shed
        self.shed_parks = 0           # lifetime shed count
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._seq = itertools.count()
        # tenant -> active heap. Fairness off: single "" key, identical
        # ordering to the pre-tenant single heap.
        self._active: dict[str, list[_HeapItem]] = {}
        self._backoff: list[tuple[float, int, QueuedPodInfo]] = []  # (ready_at, seq, qpi)
        self._unschedulable: dict[str, QueuedPodInfo] = {}  # pod key -> qpi
        self._closed = False
        # Optional listener fired (outside the queue lock) whenever work
        # arrives or parked pods are reactivated — the scheduler's
        # event-bound drain (Scheduler.run_until_idle) waits on it instead
        # of polling.
        self.on_activity: Callable[[], None] | None = None

    # --- tenant helpers ---

    def _tenant(self, pod: PodSpec) -> str:
        if self._tenant_of is None:
            return ""
        try:
            return self._tenant_of(pod)
        except Exception:  # noqa: BLE001 — a bad hook must not wedge the queue
            return ""

    def _share(self, tenant: str) -> float:
        if self._share_fn is None:
            return 0.0
        try:
            return float(self._share_fn(tenant))
        except Exception:  # noqa: BLE001
            return 0.0

    def _tenant_order(self) -> "list[str]":
        """Non-empty tenants, lowest dominant share first (name tiebreak
        for determinism). One entry when fairness is off."""
        tenants = [t for t, h in self._active.items() if h]
        if self._tenant_of is None or len(tenants) <= 1:
            return tenants
        return sorted(tenants, key=lambda t: (self._share(t), t))

    def _shed(self, pod: PodSpec) -> "str | None":
        if self._shed_fn is None:
            return None
        try:
            return self._shed_fn(pod)
        except Exception:  # noqa: BLE001 — a bad hook must never wedge pops
            return None

    def _shed_park_locked(self, qpi: QueuedPodInfo, why: str) -> None:
        """Park a shed entry in the unresolvable pool (lock held): it
        re-enters on the ladder's step-down (move_all_to_active) or any
        capacity event, and re-takes the shed check at its next pop."""
        qpi.unschedulable_message = why
        self._unschedulable[qpi.pod.key] = qpi
        self.shed_parks += 1
        if self.on_shed is not None:
            try:
                self.on_shed(qpi, why)
            except Exception:  # noqa: BLE001 — observability must not wedge pops
                pass

    def overload_depth(self) -> int:
        """Entries actively contending for the serve path (active +
        backoff) — the overload monitor's queue-pressure signal. The
        parked-unresolvable pool is EXCLUDED on purpose: shed and
        quota-capped work is already parked by the ladder itself, and
        counting it would wedge the step-down that requeues it (the
        ladder would hold SHED forever against its own backlog)."""
        with self._lock:
            return sum(len(h) for h in self._active.values()) + len(
                self._backoff
            )

    def _quota_park_locked(self, qpi: QueuedPodInfo, why: str) -> None:
        """Park an over-quota entry in the unresolvable pool (lock held):
        no backoff ladder — it re-enters the active queue on the next
        capacity-freeing cluster event and re-takes the quota check."""
        qpi.unschedulable_message = why
        self._unschedulable[qpi.pod.key] = qpi
        self.quota_parks += 1
        if self.on_quota_park is not None:
            try:
                self.on_quota_park(qpi, why)
            except Exception:  # noqa: BLE001 — observability must not wedge pops
                pass

    def _pop_active_locked(self) -> "QueuedPodInfo | None":
        """Next admissible entry in (tenant share, priority, FIFO) order,
        quota-parking over-quota heads along the way. Lock held."""
        while True:
            order = self._tenant_order()
            if not order:
                return None
            tenant = order[0]
            heap = self._active[tenant]
            item = heapq.heappop(heap)
            if not heap:
                del self._active[tenant]
            shed_why = self._shed(item.qpi.pod)
            if shed_why is not None:
                self._shed_park_locked(item.qpi, shed_why)
                continue
            if self._quota_fn is not None:
                why = self._quota_fn(tenant, item.qpi.pod)
                if why is not None:
                    self._quota_park_locked(item.qpi, why)
                    continue
            return item.qpi

    def __len__(self) -> int:
        with self._lock:
            return (
                sum(len(h) for h in self._active.values())
                + len(self._backoff)
                + len(self._unschedulable)
            )

    def depths(self) -> tuple[int, int, int]:
        """(active, backoff, parked-unresolvable) pool sizes — the
        /metrics gauges operators read to tell a healthy queue from a
        retry backlog (deep backoff = chronic unschedulables throttled;
        deep parked = pods waiting on cluster events)."""
        with self._lock:
            return (
                sum(len(h) for h in self._active.values()),
                len(self._backoff),
                len(self._unschedulable),
            )

    def pending_retry_count(self) -> int:
        """Pods that will re-enter the active queue without an external
        event (active + backoff); excludes the parked-unresolvable pool."""
        with self._lock:
            return sum(len(h) for h in self._active.values()) + len(
                self._backoff
            )

    def has_parked(self) -> bool:
        """Anything waiting on an event or a timer (backoff OR
        unresolvable)? The ``move_all_to_active`` fast-skip reads this: on
        an idle or fully-drained cluster every heartbeat used to pay a
        locked full-queue sweep to move nothing."""
        with self._lock:
            return bool(self._backoff or self._unschedulable)

    def add(self, pod: PodSpec) -> None:
        with self._cond:
            self._push_active(QueuedPodInfo(pod=pod, added_unix=self._clock()))
            # Gang-arrival signal: a new member of gang G reactivates every
            # parked/backoff member of G IMMEDIATELY (bypassing their
            # backoff timers) — the late member triggers exactly one retry
            # of its siblings instead of leaving them to walk the
            # backoff-sleep ladder while the gang could now complete.
            gang = gang_name_of(pod.labels)
            if gang:
                self._promote_gang_locked(gang)
            self._cond.notify()
        self._fire_activity()

    def _fire_activity(self) -> None:
        cb = self.on_activity
        if cb is not None:
            cb()

    def _promote_gang_locked(self, gang: str) -> None:
        """Move every parked member of ``gang`` to the active queue now."""
        still: list[tuple[float, int, QueuedPodInfo]] = []
        moved = False
        for ready_at, seq, qpi in self._backoff:
            if gang_name_of(qpi.pod.labels) == gang:
                self._push_active(qpi)
                moved = True
            else:
                still.append((ready_at, seq, qpi))
        if moved:
            heapq.heapify(still)
            self._backoff = still
        for key in [
            k
            for k, q in self._unschedulable.items()
            if gang_name_of(q.pod.labels) == gang
        ]:
            self._push_active(self._unschedulable.pop(key))

    def _push_active(self, qpi: QueuedPodInfo) -> None:
        if qpi.added_unix == 0.0:
            # Entries rebuilt on requeue paths (permit rejection, gang
            # rollback, repair) arrive without a timestamp: stamp them so
            # the SLO engine's wait accounting never sees epoch zero.
            qpi.added_unix = self._clock()
        heap = self._active.setdefault(self._tenant(qpi.pod), [])
        heapq.heappush(heap, _HeapItem(qpi, next(self._seq), self._less))

    def _flush_backoff_locked(self) -> None:
        now = self._clock()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, qpi = heapq.heappop(self._backoff)
            self._push_active(qpi)

    def pop(self, timeout: float | None = None) -> QueuedPodInfo | None:
        """Pop the highest-priority active pod of the lowest-share tenant;
        blocks up to ``timeout`` (forever if None) until one is available
        or the queue is closed."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cond:
            while True:
                self._flush_backoff_locked()
                qpi = self._pop_active_locked()
                if qpi is not None:
                    qpi.attempts += 1
                    return qpi
                if self._closed:
                    return None
                # Wake up when the earliest backoff expires, a pod arrives,
                # or the caller's timeout passes.
                waits = []
                if self._backoff:
                    waits.append(max(self._backoff[0][0] - self._clock(), 0.0))
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return None
                    waits.append(remaining)
                self._cond.wait(timeout=min(waits) if waits else None)

    def pop_matching(
        self,
        pred: Callable[[PodSpec], bool],
        limit: int | None = None,
        *,
        include_backoff: bool = False,
    ) -> list[QueuedPodInfo]:
        """Pop every ACTIVE entry whose pod satisfies ``pred``, in queue
        order — (tenant share, priority, FIFO): tenants are visited
        lowest dominant share first, so the gang gather (and therefore
        the joint pass's placement precedence) inherits DRF fairness —
        the gang of a lightly-used tenant places before a flooding
        tenant's even when the flood arrived first. This is the gang-
        aware gather next to the scheduler's ``_pop_burst``: when a
        popped pod is a gang member, its co-queued siblings are pulled
        out so the whole gang runs back-to-back in one fused pass
        instead of one cycle per loop turn. Non-blocking; expired
        backoff entries are flushed first so a sibling whose retry timer
        just lapsed is gathered too. Over-quota tenants' matching
        entries are quota-parked, never gathered — usage only moves at
        bind time, so every member of a gang sees one consistent verdict
        within this single locked pass (whole gang gathers or whole gang
        parks).

        ``include_backoff`` additionally pulls matching entries whose
        backoff timer is STILL TICKING (appended after the active matches,
        ready-time order): a gang member's pop can fuse siblings that
        bounced into timed backoff one retry earlier, instead of leaving
        them to the gang-arrival signal or the backoff ladder."""
        with self._cond:
            self._flush_backoff_locked()
            taken: list[QueuedPodInfo] = []
            n_taken = 0
            for tenant in self._tenant_order():
                heap = self._active.get(tenant)
                if not heap:
                    continue
                quota_why = None
                if self._quota_fn is not None and any(
                    pred(item.qpi.pod) for item in heap
                ):
                    # One verdict per tenant per pass (usage is constant
                    # under the lock): probe with the first matching pod.
                    probe = next(
                        item.qpi.pod for item in heap if pred(item.qpi.pod)
                    )
                    quota_why = self._quota_fn(tenant, probe)
                t_taken: list[_HeapItem] = []
                keep: list[_HeapItem] = []
                for item in heap:
                    if not pred(item.qpi.pod):
                        keep.append(item)
                        continue
                    shed_why = self._shed(item.qpi.pod)
                    if shed_why is not None:
                        # Per-item (the verdict is tier-dependent): a
                        # prod gang gathers past a shed spot sibling
                        # tenant-mate without inheriting its verdict.
                        self._shed_park_locked(item.qpi, shed_why)
                    elif quota_why is not None:
                        self._quota_park_locked(item.qpi, quota_why)
                    elif limit is None or n_taken < limit:
                        t_taken.append(item)
                        n_taken += 1
                    else:
                        keep.append(item)
                if len(keep) != len(heap):
                    if keep:
                        heapq.heapify(keep)
                        self._active[tenant] = keep
                    else:
                        del self._active[tenant]
                t_taken.sort()  # heap-internal order -> queue order
                taken.extend(item.qpi for item in t_taken)
            back_taken: list[QueuedPodInfo] = []
            if include_backoff:
                still: list[tuple[float, int, QueuedPodInfo]] = []
                for entry in sorted(self._backoff):
                    if (
                        limit is None or n_taken + len(back_taken) < limit
                    ) and pred(entry[2].pod):
                        shed_why = self._shed(entry[2].pod)
                        if shed_why is not None:
                            self._shed_park_locked(entry[2], shed_why)
                        else:
                            back_taken.append(entry[2])
                    else:
                        still.append(entry)
                if back_taken:
                    heapq.heapify(still)
                    self._backoff = still
        out = taken + back_taken
        for qpi in out:
            qpi.attempts += 1
        return out

    def all_entries(self) -> "list[tuple[PodSpec, int]]":
        """Every queued (pod, attempts) across the three pools (one
        locked sweep) — the shard-set's reroute pass walks this to find
        entries whose owning lane changed with the fleet, and its rescue
        pass to find work a shard has repeatedly failed to place (hand
        it to the global lane, which sees the whole fleet)."""
        with self._lock:
            out: "list[tuple[PodSpec, int]]" = []
            for heap in self._active.values():
                out.extend(
                    (item.qpi.pod, item.qpi.attempts) for item in heap
                )
            out.extend((qpi.pod, qpi.attempts) for _, _, qpi in self._backoff)
            out.extend(
                (qpi.pod, qpi.attempts)
                for qpi in self._unschedulable.values()
            )
            return out

    def find(self, uid: str) -> "PodSpec | None":
        """The queued spec for a pod uid, wherever it is parked (active /
        backoff / unresolvable) — the drift reconciler recovers the full
        object this way when it must replay a dropped deletion for a pod
        that exists nowhere else anymore."""
        with self._lock:
            for heap in self._active.values():
                for item in heap:
                    if item.qpi.pod.uid == uid:
                        return item.qpi.pod
            for _, _, qpi in self._backoff:
                if qpi.pod.uid == uid:
                    return qpi.pod
            for qpi in self._unschedulable.values():
                if qpi.pod.uid == uid:
                    return qpi.pod
        return None

    def remove(self, uid: str) -> bool:
        """Drop every entry for the pod with this uid from all three pools
        — the delete-event fast path: a watch ``deleted`` removes the pod
        from the queue NOW instead of waiting for its next pop's
        pod-alive check (which, for a pod deep in backoff, could be 10 s
        of phantom queue depth away). Returns whether anything was
        removed."""
        removed = False
        with self._cond:
            for tenant, heap in list(self._active.items()):
                kept = [it for it in heap if it.qpi.pod.uid != uid]
                if len(kept) != len(heap):
                    removed = True
                    if kept:
                        heapq.heapify(kept)
                        self._active[tenant] = kept
                    else:
                        del self._active[tenant]
            backoff = [e for e in self._backoff if e[2].pod.uid != uid]
            if len(backoff) != len(self._backoff):
                heapq.heapify(backoff)
                self._backoff = backoff
                removed = True
            for key in [
                k
                for k, q in self._unschedulable.items()
                if q.pod.uid == uid
            ]:
                del self._unschedulable[key]
                removed = True
        if removed:
            self._fire_activity()
        return removed

    def tenant_wait_stats(self) -> "dict[str, tuple[int, float | None]]":
        """tenant -> (queued entries across all three pools, oldest
        ``added_unix`` on the queue clock, None when unknown) — the
        pending/starvation side of the SLO engine's SLIs. With fairness
        off everything reports under the single ``""`` tenant. One locked
        sweep; called on evaluation demand (scrape/HTTP/bench), never on
        the serve path."""
        with self._lock:
            out: dict[str, tuple[int, float | None]] = {}

            def note(qpi: QueuedPodInfo) -> None:
                tenant = self._tenant(qpi.pod)
                n, oldest = out.get(tenant, (0, None))
                t = qpi.added_unix if qpi.added_unix > 0.0 else None
                if t is not None and (oldest is None or t < oldest):
                    oldest = t
                out[tenant] = (n + 1, oldest)

            for heap in self._active.values():
                for item in heap:
                    note(item.qpi)
            for _, _, qpi in self._backoff:
                note(qpi)
            for qpi in self._unschedulable.values():
                note(qpi)
            return out

    def pending_gangs(self) -> "dict[str, tuple[int, int]]":
        """gang name -> (queued member count, min attempts over them),
        across all three pools. The federation spillover pass reads this
        to find gangs that are WHOLE in the queue (count >= declared size)
        and have already failed locally (min attempts >= 1) — candidates
        for migration to a secondary cluster."""
        with self._lock:
            out: dict[str, tuple[int, int]] = {}

            def count(qpi: QueuedPodInfo) -> None:
                gang = gang_name_of(qpi.pod.labels)
                if not gang:
                    return
                n, a = out.get(gang, (0, 1 << 30))
                out[gang] = (n + 1, min(a, qpi.attempts))

            for heap in self._active.values():
                for item in heap:
                    count(item.qpi)
            for _, _, qpi in self._backoff:
                count(qpi)
            for qpi in self._unschedulable.values():
                count(qpi)
            return out

    def take_gang(self, gang: str) -> list[QueuedPodInfo]:
        """Atomically remove EVERY entry of ``gang`` from all three pools
        and return them (attempt counts untouched — no scheduling cycle
        runs on this path). While taken, this queue cannot pop or bind the
        members, which is what makes cross-cluster spillover migration
        race-free: the home cluster provably cannot place a gang whose
        entries are in the migrator's hands. Give unmigrated entries back
        with :meth:`readd`."""
        taken: list[QueuedPodInfo] = []
        with self._cond:
            for tenant, heap in list(self._active.items()):
                kept: list[_HeapItem] = []
                for item in heap:
                    if gang_name_of(item.qpi.pod.labels) == gang:
                        taken.append(item.qpi)
                    else:
                        kept.append(item)
                if len(kept) != len(heap):
                    if kept:
                        heapq.heapify(kept)
                        self._active[tenant] = kept
                    else:
                        del self._active[tenant]
            keep_backoff: list[tuple[float, int, QueuedPodInfo]] = []
            for entry in self._backoff:
                if gang_name_of(entry[2].pod.labels) == gang:
                    taken.append(entry[2])
                else:
                    keep_backoff.append(entry)
            if len(keep_backoff) != len(self._backoff):
                heapq.heapify(keep_backoff)
                self._backoff = keep_backoff
            for key in [
                k
                for k, q in self._unschedulable.items()
                if gang_name_of(q.pod.labels) == gang
            ]:
                taken.append(self._unschedulable.pop(key))
        return taken

    def readd(self, qpi: QueuedPodInfo) -> None:
        """Return a :meth:`take_gang` entry to the active queue untouched
        (unlike :meth:`restore`, no attempt decrement — take_gang never
        incremented one)."""
        with self._cond:
            self._push_active(qpi)
            self._cond.notify()
        self._fire_activity()

    def restore(self, qpi: QueuedPodInfo) -> None:
        """Return a popped-but-unscheduled entry to the active queue (the
        burst pop un-pops gang members it encounters so their own pop runs
        the gang gather). The pop's attempt increment is reverted — no
        scheduling cycle ran."""
        qpi.attempts = max(qpi.attempts - 1, 0)
        with self._cond:
            self._push_active(qpi)
            self._cond.notify()

    def add_unschedulable(self, qpi: QueuedPodInfo, message: str = "") -> None:
        """Park a pod that failed a cycle. It re-enters the active queue
        after backoff (cheap retry loop) AND on any cluster event via
        ``move_all_to_active`` (the upstream event-driven path)."""
        qpi.unschedulable_message = message
        if qpi.added_unix == 0.0:
            qpi.added_unix = self._clock()
        with self._cond:
            ready_at = self._clock() + qpi.backoff_seconds()
            heapq.heappush(self._backoff, (ready_at, next(self._seq), qpi))
            self._cond.notify()

    def park_unresolvable(self, qpi: QueuedPodInfo, message: str = "") -> None:
        """Park a pod whose failure retries cannot fix (e.g. malformed
        labels): no backoff retry loop — it returns to the active queue only
        on an explicit cluster event (``move_all_to_active``), mirroring the
        upstream UnschedulableAndUnresolvable pool semantics."""
        qpi.unschedulable_message = message
        if qpi.added_unix == 0.0:
            qpi.added_unix = self._clock()
        with self._lock:
            self._unschedulable[qpi.pod.key] = qpi

    def move_all_to_active(self, *, force: bool = False) -> None:
        """Cluster changed (node/metrics/pod event): retry parked pods —
        immediately through ``immediate_retry_attempts``, after that only
        when the pod's own backoff timer has expired (chronic
        unschedulables keep their ready_at and flush on time via
        :meth:`pop`, bounding the per-pod retry rate at ~1/MAX_BACKOFF_S
        no matter how fast events arrive). ``force`` bypasses the cutoff —
        the deterministic-settlement driver (Scheduler.run_until_idle)
        uses it after a bind so its fixed-point check never concludes
        "idle" while a chronic pod could still fit freed capacity;
        production event paths never force."""
        with self._cond:
            now = self._clock()
            cutoff = (
                float("inf") if force else self.immediate_retry_attempts
            )
            still: list[tuple[float, int, QueuedPodInfo]] = []
            for ready_at, seq, qpi in self._backoff:
                if qpi.attempts <= cutoff or ready_at <= now:
                    self._push_active(qpi)
                else:
                    still.append((ready_at, seq, qpi))
            heapq.heapify(still)
            self._backoff = still
            for qpi in self._unschedulable.values():
                # Unresolvable-parked pods leave the pool on their first
                # event either way; chronic ones re-enter via the backoff
                # heap (fixed ready_at — later events cannot reset it).
                if qpi.attempts <= cutoff:
                    self._push_active(qpi)
                else:
                    heapq.heappush(
                        self._backoff,
                        (now + qpi.backoff_seconds(), next(self._seq), qpi),
                    )
            self._unschedulable.clear()
            self._cond.notify_all()
        self._fire_activity()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
