"""Crash-safe failover: warm-start state reconciliation + drift repair.

Every recovery mechanism before this PR (transactional gang rollback, the
completion-barrier pipeline, leader fencing) assumes the scheduler PROCESS
survives — reservations, gang permit groups, and the Permit waitlist are
in-memory only. A crash or lease handover mid-gang therefore left the new
leader blind: it could double-place onto chips the dead leader already
bound, and half-bound gangs were stranded until the permit timeout (the
partial-gang deadlock KEP-624 coscheduling exists to prevent; the
master-rebuilds-from-cluster pattern Gandiva-class schedulers use — see
PAPERS.md). This module closes that gap with two passes over CLUSTER TRUTH:

- :meth:`Reconciler.resync` — the **warm-start pass**. Runs on promotion
  (``Scheduler.on_serve_start``, before the serve loop admits any pod):
  re-LISTs pods where the backend supports it, charges every bound pod's
  reservation that local accounting is missing, releases claims with no
  live pod behind them, and classifies every PARTIALLY-BOUND gang —
  **adopt** (bound members kept, siblings' claims charged, remaining
  members complete the gang in place, bounded by
  ``failover_adopt_window_s``) or **roll back whole** via the existing
  unbind path. The policy is deterministic: adopt iff the window is > 0
  and every bound member's host is still present in cluster truth.
- :meth:`Reconciler.reconcile` — the **periodic drift pass**
  (``reconcile_period_s``). While running, repairs what the watch stream
  dropped: leaked reservations (pod deleted, release event lost), ghost
  bindings (bind event lost — cluster truth bound, cache not — and the
  reverse: cache entries for pods the cluster no longer has), Permit
  waits whose pod was deleted (cancelled immediately instead of eating
  the 120 s timeout), and adopted gangs still partial past their window
  (rolled back whole).

Both passes are idempotent and run against live scheduling: repairs go
through the SAME watch-event handlers the stream would have driven
(``accountant.handle`` / ``gang.handle`` / ``informer.handle``), in the
same registration order, so incremental bookkeeping stays consistent; and
every "gone" verdict is double-checked against ``cluster.get_pod`` before
acting, so a pod created or deleted between the LIST and the check is
never misclassified.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from yoda_tpu.api.requests import LabelParseError, gang_name_of, pod_request
from yoda_tpu.api.types import PodSpec
from yoda_tpu.cluster.fake import Event

log = logging.getLogger("yoda_tpu.reconciler")


@dataclass
class ResyncReport:
    """What one warm-start resync pass found and did (tests, logs)."""

    adopted_gangs: list[str] = field(default_factory=list)
    rolled_back_gangs: list[str] = field(default_factory=list)
    rebuilt_reservations: int = 0
    released_reservations: int = 0
    duration_ms: float = 0.0
    # True when this pass ran as the WARM divergence check against
    # journal-replayed state instead of the from-scratch rebuild.
    warm: bool = False


@dataclass
class DriftReport:
    """What one periodic reconcile round repaired."""

    leaked_reservations: int = 0
    ghost_pods: int = 0
    stranded_waits: int = 0
    expired_adoptions: list[str] = field(default_factory=list)


class Reconciler:
    """Rebuilds and repairs scheduler state from cluster truth.

    One per stack (``standalone.build_stack``). The accountant may be
    shared across profile stacks — its repairs are idempotent, so
    concurrent reconcilers converge; gang classification is restricted to
    this stack's ``scheduler_names`` so two profiles never both adopt or
    roll back the same gang.
    """

    def __init__(
        self,
        *,
        cluster,
        informer,
        accountant,
        gang,
        framework,
        queue,
        scheduler,
        metrics=None,
        adopt_window_s: float = 60.0,
        scheduler_names: "tuple[str, ...] | None" = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.cluster = cluster
        self.informer = informer
        self.accountant = accountant
        self.gang = gang
        self.framework = framework
        self.queue = queue
        self.scheduler = scheduler
        self.metrics = metrics
        self.adopt_window_s = adopt_window_s
        self.scheduler_names = frozenset(
            scheduler_names or (informer.scheduler_name,)
        )
        self.clock = clock
        # Set after the first successful resync — the /readyz half of the
        # warm-start contract (cli.py flips routing on it).
        self.resynced = threading.Event()
        # Lifecycle tracer shortcut (metrics.tracer when wired): repairs
        # land events on the affected gang's own trace — the resync
        # chapter of "one gang, one story".
        self._tracer = getattr(metrics, "tracer", None)
        self._lock = threading.Lock()
        # gang name -> clock deadline by which an ADOPTED partial gang
        # must have completed whole, or the drift pass rolls it back.
        self._adopt_deadlines: dict[str, float] = {}

    # --- shared plumbing ---

    def _list_truth(self, *, relist: bool = False) -> list[PodSpec]:
        """Cluster truth for pods. ``relist`` asks backends that cache a
        watch stream (KubeCluster) to re-LIST from the API first — the
        diff replays as corrective events through every registered
        watcher, which is what repairs drops between the API server and
        the local store. In-process backends' stores ARE the truth."""
        if relist:
            resync = getattr(self.cluster, "resync_pods", None)
            if resync is not None:
                try:
                    resync()
                except Exception:  # noqa: BLE001 — degraded, not fatal
                    log.exception(
                        "pod re-LIST failed; reconciling against the "
                        "watch cache instead"
                    )
        return self.cluster.list_pods()

    def _repair_event(self, etype: str, pod: PodSpec) -> None:
        """Inject a corrective event through the same handlers the watch
        stream drives, in stack registration order (accountant before
        gang before informer — reservation releases must precede the
        informer's view of the same event)."""
        ev = Event(etype, "Pod", pod)
        self.accountant.handle(ev)
        self.gang.handle(ev)
        self.informer.handle(ev)

    def _pod_truly_gone(self, pod_key: str) -> bool:
        """Double-check a 'gone' verdict against a point read — a pod
        created between the LIST and the diff must not be reaped."""
        try:
            return self.cluster.get_pod(pod_key) is None
        except Exception:  # noqa: BLE001 — unreadable backend: do nothing
            log.exception("point read of %s failed; skipping repair", pod_key)
            return False

    def _rollback_gang(self, name: str, bound: "list[PodSpec]", why: str) -> None:
        """Roll a partial gang back WHOLE through the existing unbind path:
        membership is dropped first (a stale bound entry must not satisfy
        the barrier mid-rollback; on_unbind_failed restores it if the
        unbind cannot land), then each landed bind is unbound, unreserved,
        and requeued (scheduler._rollback_bound)."""
        log.warning(
            "failover: rolling back partial gang %s (%d bound member(s)): %s",
            name, len(bound), why,
        )
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.add(
                f"gang:{name}", "resync-rollback",
                track="reconciler",
                attrs={"members": len(bound), "why": why[:200]},
            )
        for pod in bound:
            self.gang.drop_membership(pod)
            self.scheduler._rollback_bound(pod, pod.node_name, None, why)
        if self.metrics is not None:
            self.metrics.resync_rolled_back.inc()

    def _gang_truth(
        self, pods: "list[PodSpec]"
    ) -> "dict[str, tuple[int, list[PodSpec], list[PodSpec]]]":
        """gang name -> (declared size, bound members, unbound members),
        restricted to this stack's scheduler profiles."""
        out: dict[str, tuple[int, list[PodSpec], list[PodSpec]]] = {}
        groups: dict[str, list[PodSpec]] = {}
        for p in pods:
            name = gang_name_of(p.labels)
            if not name or p.scheduler_name not in self.scheduler_names:
                continue
            groups.setdefault(name, []).append(p)
        for name, members in groups.items():
            size = 0
            for p in members:
                try:
                    spec = pod_request(p).gang
                except LabelParseError:
                    continue
                if spec is not None:
                    size = spec.size
                    break
            if size <= 0:
                continue
            bound = [p for p in members if p.node_name]
            unbound = [p for p in members if not p.node_name]
            out[name] = (size, bound, unbound)
        return out

    # --- the warm-start pass ---

    def resync(self) -> ResyncReport:
        """List cluster truth and rebuild this scheduler's load-bearing
        state BEFORE any pod is admitted (wired as
        ``Scheduler.on_serve_start`` by cli.py). Idempotent: a warm
        standby whose caches tracked the whole time resyncs to a no-op."""
        t0 = self.clock()
        report = ResyncReport()
        # Warm start (durable claim journal, ISSUE 18): when the
        # accountant was seeded from a journal replay, the full-LIST
        # from-scratch rebuild collapses to a DIVERGENCE CHECK — one
        # bulk claims snapshot diffed against the watch cache's truth,
        # repair events only for the (rare) divergent pods, and no API
        # re-LIST blackout (the journal is the durable record; the
        # periodic drift pass still re-LISTs as backstop).
        warm = bool(getattr(self.accountant, "replayed", False))
        report.warm = warm
        pods = self._list_truth(relist=not warm)
        live = {p.uid for p in pods}

        # 1. Reservations: every bound pod must be charged. The watch
        # replay normally did this at stack build; this covers binds the
        # dead leader landed that the stream has not delivered yet.
        if warm:
            claims = self.accountant.claims_snapshot()
            for p in pods:
                if not p.node_name:
                    continue
                c = claims.get(p.uid)
                if c is None or c[0] != p.node_name:
                    self._repair_event("modified", p)
                    if c is None and self.accountant.has_claim(p.uid):
                        report.rebuilt_reservations += 1
        else:
            for p in pods:
                if not p.node_name:
                    continue
                missing = not self.accountant.has_claim(p.uid)
                if missing or not self.informer.counts_bound(p.uid):
                    self._repair_event("modified", p)
                if missing and self.accountant.has_claim(p.uid):
                    report.rebuilt_reservations += 1

        # 2. Claims with no live pod behind them (the dead leader reserved
        # and the pod is gone, or a drop): release.
        for uid in self.accountant.claimed_uids() - live:
            self.accountant.release(uid)
            report.released_reservations += 1
        if warm:
            # The dead leader's reserve that never reached a bind: a
            # restored COMMITTED claim whose pod is live but UNBOUND.
            # No bind event will ever finalize it, and no reserve is in
            # flight this early (resync precedes the first queue pop),
            # so it would sit as phantom usage forever — release; the
            # promoted scheduler re-reserves when it pops the pod.
            # STAGED claims stay: they are the mid-gang resume cohort
            # that step 3 below adopts or rolls back whole.
            bound_uids = {p.uid for p in pods if p.node_name}
            staged_uids = set(self.accountant.staged_uids())
            for uid in (
                self.accountant.claimed_uids() - bound_uids - staged_uids
            ):
                self.accountant.release(uid)
                report.released_reservations += 1

        # 3. Partially-bound gangs: adopt or roll back whole. With a
        # journal replay, a gang whose unbound members still hold STAGED
        # claims resumes from them — the mid-gang crash continues in
        # place instead of rolling the whole gang back.
        replayed_gangs = (
            getattr(self.accountant, "replayed_gangs", {}) if warm else {}
        )
        now = self.clock()
        hosts = {t.name for t in self.cluster.list_tpu_metrics()}
        for name, (size, bound, _unbound) in self._gang_truth(pods).items():
            if not bound or len(bound) >= size:
                continue  # nothing placed yet, or already complete
            hosts_alive = all(p.node_name in hosts for p in bound)
            if (self.adopt_window_s > 0 or name in replayed_gangs) and hosts_alive:
                window = (
                    self.adopt_window_s
                    if self.adopt_window_s > 0
                    # Adoption disabled but the journal holds the gang's
                    # staged claims: resume mid-gang anyway, bounded.
                    else 60.0
                )
                with self._lock:
                    self._adopt_deadlines.setdefault(name, now + window)
                report.adopted_gangs.append(name)
                log.info(
                    "failover: adopted partial gang %s (%d/%d bound; "
                    "%.0fs to complete before rollback)",
                    name, len(bound), size, self.adopt_window_s,
                )
                if self._tracer is not None and self._tracer.enabled:
                    self._tracer.add(
                        f"gang:{name}", "resync-adopt",
                        track="reconciler",
                        attrs={"bound": len(bound), "size": size},
                    )
                if self.metrics is not None:
                    self.metrics.resync_adopted.inc()
            else:
                why = (
                    f"failover resync: gang {name} partially bound "
                    f"({len(bound)}/{size}) and "
                    + (
                        "adoption is disabled"
                        if self.adopt_window_s <= 0
                        else "a bound member's host is gone"
                    )
                )
                self._rollback_gang(name, bound, why)
                report.rolled_back_gangs.append(name)

        report.duration_ms = (self.clock() - t0) * 1e3
        if self._tracer is not None and self._tracer.enabled:
            self._tracer.add(
                "loop:reconciler", "resync",
                track="reconciler",
                attrs={
                    "rebuilt": report.rebuilt_reservations,
                    "released": report.released_reservations,
                    "adopted": len(report.adopted_gangs),
                    "rolled_back": len(report.rolled_back_gangs),
                    "ms": round(report.duration_ms, 2),
                },
            )
        if self.metrics is not None:
            self.metrics.resync_rebuilt.inc(report.rebuilt_reservations)
            self.metrics.reconciler_leaked.inc(report.released_reservations)
            self.metrics.resync_duration_ms.set(report.duration_ms)
        self.resynced.set()
        log.info(
            "warm-start resync: %d reservation(s) rebuilt, %d released, "
            "%d gang(s) adopted, %d rolled back (%.1f ms)",
            report.rebuilt_reservations, report.released_reservations,
            len(report.adopted_gangs), len(report.rolled_back_gangs),
            report.duration_ms,
        )
        return report

    # --- the periodic drift pass ---

    def reconcile(self, *, relist: bool = True) -> DriftReport:
        """One drift-repair round. Safe against live scheduling: every
        repair is either an idempotent re-count or goes through the
        standard rejection/rollback paths."""
        report = DriftReport()
        pods = self._list_truth(relist=relist)
        truth_uids = {p.uid for p in pods}
        truth_keys = {p.key for p in pods}

        # Ghost bindings: cluster truth says bound, the cache does not —
        # the watch dropped the bind. Re-inject it. The reverse ADD gap
        # too: a pod CREATED while the watch was down (e.g. during a
        # cluster partition) exists in truth but never reached the
        # informer, so it was never queued — replay its add so it
        # schedules (the federation rejoin path depends on this: work
        # submitted to a partitioned cluster must surface on heal).
        live_cache = self.informer.live_uid_set()
        for p in pods:
            if p.node_name and not self.informer.counts_bound(p.uid):
                self._repair_event("modified", p)
                report.ghost_pods += 1
            elif p.uid not in live_cache:
                self._repair_event("added", p)
                report.ghost_pods += 1

        # Reverse ghosts: the cache believes a pod alive that cluster
        # truth no longer has — the watch dropped the deletion. Replay it
        # (releases the claim, drops gang membership, cancels its Permit
        # wait via the delete fast path, and prunes its queue entries
        # through the stack's on_change hook).
        for w in self.framework.waiting_pods():
            if w.pod.uid not in truth_uids and self._pod_truly_gone(w.pod.key):
                if self.framework.cancel_waiting(
                    w.pod.key,
                    f"pod {w.pod.key} was deleted while parked at permit "
                    "(reconciler)",
                ):
                    report.stranded_waits += 1
        for uid in self.informer.live_uid_set() - truth_uids:
            pod = self._cached_pod(uid)
            if pod is None or not self._pod_truly_gone(pod.key):
                continue
            self._repair_event("deleted", pod)
            self.queue.remove(uid)
            report.ghost_pods += 1

        # Leaked reservations: a claim with no live pod anywhere.
        for uid in self.accountant.claimed_uids() - truth_uids:
            if uid in self.informer.live_uid_set():
                continue  # the informer will release it through its path
            self.accountant.release(uid)
            report.leaked_reservations += 1

        # Shard-commit residue (scheduler shard-out): a claim still
        # STAGED whose pod cluster truth shows BOUND means the staging
        # shard died between the bind landing and its commit — truth
        # outranks the optimistic protocol, so finalize it (a staged
        # claim for a pod that is gone releases through the leaked-claim
        # path above; one that is merely unbound keeps its in-flight
        # staging — its own commit or rollback is still coming).
        staged = getattr(self.accountant, "staged_uids", None)
        if staged:
            bound_uids = {p.uid for p in pods if p.node_name}
            for uid in staged():
                if uid in bound_uids:
                    self.accountant.commit_residue(uid)

        # Adopted gangs past their window and still partial: roll back.
        now = self.clock()
        gangs = self._gang_truth(pods)
        with self._lock:
            deadlines = dict(self._adopt_deadlines)
        for name, deadline in deadlines.items():
            size, bound, _unbound = gangs.get(name, (0, [], []))
            if not bound or (size and len(bound) >= size):
                with self._lock:
                    self._adopt_deadlines.pop(name, None)
                continue
            if now < deadline:
                continue
            with self._lock:
                self._adopt_deadlines.pop(name, None)
            self._rollback_gang(
                name,
                bound,
                f"adopted gang {name} still partial ({len(bound)}/{size}) "
                f"after the {self.adopt_window_s:.0f}s failover adopt window",
            )
            report.expired_adoptions.append(name)

        if self._tracer is not None and self._tracer.enabled and (
            report.leaked_reservations
            or report.ghost_pods
            or report.stranded_waits
            or report.expired_adoptions
        ):
            # Only non-no-op rounds are recorded: an idle 30 s drift loop
            # must not age real lifecycle spans out of the ring.
            self._tracer.add(
                "loop:reconciler", "reconcile",
                track="reconciler",
                attrs={
                    "leaked": report.leaked_reservations,
                    "ghosts": report.ghost_pods,
                    "stranded": report.stranded_waits,
                    "expired": len(report.expired_adoptions),
                },
            )
        if self.metrics is not None:
            self.metrics.reconciler_leaked.inc(report.leaked_reservations)
            self.metrics.reconciler_ghosts.inc(report.ghost_pods)
            self.metrics.reconciler_stranded.inc(report.stranded_waits)
        if (
            report.leaked_reservations
            or report.ghost_pods
            or report.stranded_waits
            or report.expired_adoptions
        ):
            log.warning(
                "drift reconciler repaired: %d leaked reservation(s), %d "
                "ghost pod record(s), %d stranded wait(s), %d expired "
                "adoption(s)",
                report.leaked_reservations, report.ghost_pods,
                report.stranded_waits, len(report.expired_adoptions),
            )
        return report

    def _cached_pod(self, uid: str) -> "PodSpec | None":
        """The informer has uids, not specs; recover the spec from the
        node-count map or the waitlist so a synthetic delete can carry a
        real object (handlers key on pod.key/labels)."""
        with self.informer._lock:
            for pods in self.informer._pods_by_node.values():
                p = pods.get(uid)
                if p is not None:
                    return p
        for w in self.framework.waiting_pods():
            if w.pod.uid == uid:
                return w.pod
        return self.queue.find(uid)

    def adopted_gangs(self) -> "dict[str, float]":
        """Live adoption deadlines (tests, introspection)."""
        with self._lock:
            return dict(self._adopt_deadlines)

    def run_forever(self, stop: threading.Event, *, period_s: float = 30.0) -> None:
        """The background drift loop (cli.py puts this on a thread once
        leadership is held). Exceptions are logged, never fatal — a
        reconciler crash must not take the serving scheduler with it."""
        while not stop.is_set():
            if stop.wait(period_s):
                return
            try:
                self.reconcile()
            except Exception:  # noqa: BLE001 — repair loop must survive
                log.exception("drift reconcile round failed; will retry")
