"""Speculative placement cache: sub-millisecond binds for hot shapes.

The serve path's warm p99 is dominated by the filter/score spans — even
fully fused, a dispatch against a 100k-node fleet costs most of a
millisecond. But serve traffic is shape-skewed: a handful of (admission
constraints, chip request) shapes account for most arrivals, and between
serve cycles the fleet barely moves. This module exploits that skew:

- Between cycles, the REBALANCER's leadership-gated tick
  (cluster/rebalance.Rebalancer.run_forever) re-evaluates recently-seen
  shapes against resident fleet state and parks one validated candidate
  plan per shape, keyed by (admission key, kernel request) and stamped
  with the informer's snapshot epochs.
- At serve time, a hot-shape arrival binds from the cached plan after a
  cheap validity chain — leader fence, per-plan epoch check against BOTH
  informer delta feeds, and an O(1) admission + staged-claim spot check
  on the single chosen node — skipping the O(fleet) filter/score spans
  entirely.

Safety argument (why a stale plan cannot bind):

- Chip capacity: ``SpecPlan.base_reserved`` records the reserved-chip
  reading the speculative evaluation ACTUALLY saw on the chosen node (its
  dyn row, not a post-hoc re-read). Consumption requires the accountant's
  live value to equal it exactly, so any reservation, release, or claim
  landing after the evaluation — including one racing the evaluation
  itself — fails the equality. This is the same discipline as the burst
  dispatch's per-serve spot check (plugins/yoda/batch._BurstSet).
- Node-object state (cordon, taints, fence) and pod-set changes: the
  admission delta feed (InformerCache.admission_changes_since) names
  touched hosts; a plan whose node appears invalidates, and consumption
  additionally re-runs the single-node admission check against the serve
  cycle's own snapshot.
- Metrics (chip health, HBM): the metrics delta feed
  (InformerCache.changes_since) covers CR value changes; structural
  deltas or ring eviction invalidate unconditionally.
- Gangs: out of scope entirely (see :func:`speculation_key`), so a
  speculative bind can never split a gang.

Threading: speculation runs on the rebalancer thread with a PRIVATE
:class:`~yoda_tpu.ops.resident.FleetStateCache` and numpy kernel — zero
sharing with the serve path's YodaBatch, whose resident state and reused
dyn buffer are not thread-safe. The cache's own lock is level
"speculation", BELOW the informer in the lock DAG (yodalint
lock-discipline): taking informer/feed locks while holding it is legal,
but nothing here may run under the informer lock — invalidation is
pull-based off the delta feeds, never an informer->speculation callback.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable

import numpy as np

from yoda_tpu.api.affinity import pod_has_inter_pod_terms
from yoda_tpu.api.requests import gang_name_of, pod_request
from yoda_tpu.api.types import PodSpec
from yoda_tpu.config import Weights
from yoda_tpu.ops.kernel import KernelRequest, NumpyFleetKernel
from yoda_tpu.ops.resident import FleetStateCache

log = logging.getLogger("yoda_tpu.speculation")


@dataclass
class SpecPlan:
    """One validated candidate placement for a shape.

    ``epoch_m``/``epoch_a`` are the informer's snapshot-stamped metrics
    and admission epochs the plan was computed against (stamped under the
    informer lock at snapshot build, so an event between build and plan
    is re-covered by the next epoch check rather than skipped).
    ``base_reserved`` is the reserved-chip dyn row the evaluation saw on
    the chosen node — the consume-time equality anchor."""

    key: tuple
    node: str
    epoch_m: int
    epoch_a: int
    base_reserved: int
    score: int


def speculation_key(pod: PodSpec) -> "tuple | None":
    """The (admission constraints, kernel request) shape key, or None when
    the pod is out of speculation scope.

    Scope is deliberately narrow — single non-gang pods whose admission
    depends only on node-local state: gangs need joint placement,
    inter-pod affinity / topology spread / hostPorts / PVCs need the
    per-cycle AffinityData, cpu/mem requests interact with concurrent
    cycles' pending resources that a between-cycles evaluation cannot
    see, and preferred node affinity perturbs ranking relative to the
    full path's pref_bonus. Everything excluded here still serves at the
    fused-dispatch baseline."""
    from yoda_tpu.plugins.yoda.batch import _admission_key

    if gang_name_of(pod.labels) is not None:
        return None
    if pod_has_inter_pod_terms(pod) or pod.topology_spread:
        return None
    if pod.pvc_names or pod.host_ports or pod.preferred_node_affinity:
        return None
    if pod.cpu_milli_request or pod.memory_request:
        return None
    adm = _admission_key(pod)
    if adm is None:
        return None
    try:
        reqk = KernelRequest.from_request(pod_request(pod))
    except Exception:
        return None
    if reqk.wants_topology:
        return None
    return (adm, reqk)


class SpeculativeCache:
    """Shape-keyed cache of pre-validated placements (module docstring).

    Producer side (:meth:`speculate_once`, :meth:`sweep`) runs on the
    rebalancer thread; consumer side (:meth:`lookup` →
    :meth:`epoch_valid` → :meth:`revalidate` → :meth:`consume_plan`) runs
    on serve cycles. Plans are single-use: a successful Reserve changes
    the node's reserved chips, staling ``base_reserved`` by construction,
    so consumption pops and the next tick re-plans the shape.
    """

    def __init__(
        self,
        *,
        snapshot_fn: "Callable | None" = None,
        changes_fn: "Callable | None" = None,
        admission_changes_fn: "Callable | None" = None,
        reserved_fn: "Callable | None" = None,
        reserved_map_fn: "Callable | None" = None,
        claimed_fn: "Callable | None" = None,
        claimed_map_fn: "Callable | None" = None,
        last_updated_map_fn: "Callable | None" = None,
        weights: "Weights | None" = None,
        max_metrics_age_s: float = 0.0,
        enabled: bool = True,
        size: int = 256,
        shapes_max: int = 64,
    ) -> None:
        self.enabled = enabled
        self.size = max(1, int(size))
        self.shapes_max = max(1, int(shapes_max))
        self.snapshot_fn = snapshot_fn
        self.changes_fn = changes_fn
        self.admission_changes_fn = admission_changes_fn
        self.reserved_fn = reserved_fn
        self.weights = weights or Weights()
        # yoda_spec_bind_ms hook, wired by standalone to the metrics
        # histogram; None outside a full stack.
        self.bind_observe: "Callable | None" = None
        # Level "speculation" — the BOTTOM of the lock DAG (yodalint
        # lock-discipline): feed/informer calls are legal while holding
        # it; nothing here may be called from under the informer lock.
        self._lock = threading.Lock()
        self._plans: "dict[tuple, SpecPlan]" = {}
        self._shapes: "dict[tuple, PodSpec]" = {}  # key -> representative
        # Private resident state for the rebalancer-thread evaluations:
        # the serve path's YodaBatch (shared dyn buffer, jit caches) is
        # not thread-safe, so the speculator owns its own mirror and runs
        # the numpy kernel — background capacity, not serve-path latency.
        self._numpy_kern = NumpyFleetKernel(self.weights)
        self._fleet = FleetStateCache(
            changes_fn=(
                changes_fn if changes_fn is not None else (lambda epoch: None)
            ),
            kern_fn=lambda arrays: self._numpy_kern,
            max_metrics_age_s=max_metrics_age_s,
            reserved_map_fn=reserved_map_fn,
            reserved_fn=reserved_fn,
            claimed_map_fn=claimed_map_fn,
            claimed_fn=claimed_fn,
            last_updated_map_fn=last_updated_map_fn,
        )
        # Counters — exported as yoda_spec_cache_{hits,misses,
        # invalidations}_total plus producer-side gauges (standalone).
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.reserve_rejects = 0
        self.speculations = 0  # plans produced, lifetime
        self.ticks = 0

    # --- consumer side (serve cycles) ---

    def lookup(self, pod: PodSpec) -> "SpecPlan | None":
        """The cached plan for this pod's shape, or None — recording the
        shape as a speculation candidate on a miss (bounded by
        ``shapes_max``). Read-only: plans leave only via
        :meth:`consume_plan` or invalidation."""
        if not self.enabled:
            return None
        key = speculation_key(pod)
        if key is None:
            return None
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None:
                return plan
            self.misses += 1
            if key not in self._shapes and len(self._shapes) < self.shapes_max:
                self._shapes[key] = pod
        return None

    def epoch_valid(self, plan: SpecPlan) -> bool:
        """Is the plan's chosen node untouched since the plan's epochs?

        Pulls both informer delta feeds — metrics CR values
        (``changes_fn``) and the admission feed covering Node-object
        events and pod-set changes (``admission_changes_fn``). A
        structural delta or a feed that can no longer answer (ring
        eviction, unwired) invalidates; otherwise only a delta naming the
        plan's node does, and a clean pass re-stamps the plan forward so
        the next check covers only new events. Never called under the
        speculation lock: feed calls take the informer lock, which sits
        ABOVE speculation in the lock DAG."""
        if self.changes_fn is None or self.admission_changes_fn is None:
            self._invalidate(plan.key)
            return False
        mdelta = self.changes_fn(plan.epoch_m)
        acur, achanged = self.admission_changes_fn(plan.epoch_a)
        if mdelta is None or mdelta.structural or achanged is None:
            self._invalidate(plan.key)
            return False
        if plan.node in mdelta.changed or plan.node in achanged:
            self._invalidate(plan.key)
            return False
        # Forward re-stamp is monotone-safe: any event after the feed
        # reads above lands at a later epoch and is covered next check.
        plan.epoch_m = mdelta.epoch
        plan.epoch_a = acur
        return True

    def revalidate(self, plan: SpecPlan, pod: PodSpec, snapshot) -> bool:
        """O(1) consume-time spot check against the SERVE cycle's own
        snapshot: the chosen node must still admit the pod (cordon,
        taints, node-health fence) and the accountant's live reserved
        chips must equal exactly what the speculative evaluation saw."""
        from yoda_tpu.plugins.yoda.batch import _node_admission_ok

        if plan.node not in snapshot:
            self._invalidate(plan.key)
            return False
        fenced = getattr(snapshot, "fenced", None)
        if not _node_admission_ok(plan.node, snapshot, fenced, pod):
            self._invalidate(plan.key)
            return False
        # Fail closed without a staged-claim source: no equality check
        # means no oversubscription guarantee.
        if self.reserved_fn is None or int(
            self.reserved_fn(plan.node)
        ) != plan.base_reserved:
            self._invalidate(plan.key)
            return False
        return True

    def consume_plan(self, plan: SpecPlan) -> "str | None":
        """Pop-and-return the plan's node. Atomic and single-use: exactly
        one caller wins a given plan object; a loser gets None and takes
        the full path. yodalint (speculation-safety) requires every call
        site to be dominated by the leader fence AND :meth:`epoch_valid`."""
        with self._lock:
            if self._plans.get(plan.key) is plan:
                del self._plans[plan.key]
                self.hits += 1
                return plan.node
        return None

    def reserve_rejected(self, plan: SpecPlan) -> None:
        """The consumed plan lost the race between the spot check and
        Reserve (a foreign claim landed in that window). The plan is
        already popped; the serve cycle falls through to the full path —
        never parks off a speculative miss."""
        with self._lock:
            self.reserve_rejects += 1
            self.invalidations += 1

    def record_bound(self, ms: float) -> None:
        """Feed the yoda_spec_bind_ms histogram (when wired)."""
        obs = self.bind_observe
        if obs is not None:
            obs(ms)

    # --- producer side (rebalancer tick) ---

    def speculate_once(self, budget: "int | None" = None) -> int:
        """ONE speculation pass: sweep stale plans off the delta feeds,
        then (re-)evaluate up to ``budget`` tracked shapes against the
        current snapshot on the private resident state. Driven by the
        rebalancer's leadership-gated tick, so followers never speculate.
        Returns the number of plans produced."""
        if not self.enabled or self.snapshot_fn is None:
            return 0
        self.ticks += 1
        self.sweep()
        with self._lock:
            shapes = list(self._shapes.items())
        if not shapes:
            return 0
        snapshot = self.snapshot_fn()
        m_epoch = getattr(snapshot, "metrics_version", None)
        a_epoch = getattr(snapshot, "admission_epoch", None)
        if not m_epoch or a_epoch is None:
            return 0  # informer without epoch stamps: nothing cacheable
        try:
            arrays = self._fleet.sync(snapshot)
        except Exception:
            log.exception("speculation fleet sync failed; flushing plans")
            self.flush()
            return 0
        if not arrays.names:
            return 0
        if budget is not None:
            shapes = shapes[:budget]
        produced = 0
        for key, pod in shapes:
            plan = self._plan_for(key, pod, snapshot, arrays, m_epoch, a_epoch)
            with self._lock:
                if plan is None:
                    # No feasible host right now: a cached plan for the
                    # shape is definitionally stale, drop it.
                    if self._plans.pop(key, None) is not None:
                        self.invalidations += 1
                elif len(self._plans) < self.size or key in self._plans:
                    self._plans[key] = plan
                    produced += 1
        self.speculations += produced
        return produced

    def _plan_for(self, key, pod, snapshot, arrays, m_epoch, a_epoch):
        from yoda_tpu.plugins.yoda.batch import _host_admission

        host_ok = _host_admission(arrays, snapshot, pod)
        dyn = self._fleet.dyn_packed(host_ok=host_ok)
        try:
            res = self._fleet.kern.evaluate(dyn, key[1])
        except Exception:
            log.exception("speculative evaluation failed for shape %r", key[1])
            return None
        best = int(res.best_index)
        if best < 0:
            return None
        return SpecPlan(
            key=key,
            node=arrays.names[best],
            epoch_m=m_epoch,
            epoch_a=a_epoch,
            # The dyn row the evaluation saw — NOT a re-read, so a
            # reservation racing the evaluation fails the equality.
            base_reserved=int(np.asarray(dyn[1])[best]),
            score=int(np.asarray(res.scores)[best]),
        )

    def sweep(self) -> None:
        """Pull-based invalidation: run the consumption-path epoch check
        over every cached plan, so hosts touched since a plan's epochs
        evict exactly the plans referencing them (structural churn or
        ring eviction evicts everything, same as at consume time)."""
        with self._lock:
            plans = list(self._plans.values())
        for plan in plans:
            self.epoch_valid(plan)

    # --- lifecycle ---

    def flush(self) -> int:
        """Drop every plan AND tracked shape. Live reconfiguration and
        shard-set resize call this: after a topology change the shard's
        informer feeds are a different timeline, and no plan keyed
        against the old one may survive it."""
        with self._lock:
            n = len(self._plans)
            self.invalidations += n
            self._plans.clear()
            self._shapes.clear()
        return n

    def configure(
        self, *, enabled=None, size=None, shapes_max=None
    ) -> None:
        """Apply reloadable knobs (spec_enabled / spec_cache_size /
        spec_shapes_max). Shrinking evicts oldest-inserted first;
        disabling flushes — plans must not outlive the kill switch."""
        with self._lock:
            if size is not None:
                self.size = max(1, int(size))
                while len(self._plans) > self.size:
                    del self._plans[next(iter(self._plans))]
                    self.invalidations += 1
            if shapes_max is not None:
                self.shapes_max = max(1, int(shapes_max))
                while len(self._shapes) > self.shapes_max:
                    del self._shapes[next(iter(self._shapes))]
        if enabled is not None:
            was = self.enabled
            self.enabled = bool(enabled)
            if was and not self.enabled:
                self.flush()

    def _invalidate(self, key) -> None:
        with self._lock:
            if self._plans.pop(key, None) is not None:
                self.invalidations += 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "plans": len(self._plans),
                "shapes": len(self._shapes),
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "reserve_rejects": self.reserve_rejects,
                "speculations": self.speculations,
                "ticks": self.ticks,
            }
