"""Framework runtime: drives plugins through the extension points and owns
the Permit waitlist.

Semantics follow the modern upstream framework runtime:
- Filter: every FilterPlugin must succeed for a node to be feasible.
- Score: each ScorePlugin's raw scores are normalized by its ``normalize``
  then summed across plugins.
- Reserve: runs in plugin order; on failure, already-reserved plugins are
  unreserved in reverse order.
- Permit: any WAIT parks the pod on the waitlist; approval requires every
  waiting plugin to allow; rejection or timeout unreserves.

The batch fast path (``BatchFilterScorePlugin``) replaces the per-node
filter/score loops with one fused computation — the TPU-native fix for the
reference's O(nodes) per-pod round-trips (reference pkg/yoda/scheduler.go:70,108).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Mapping, Sequence

log = logging.getLogger("yoda_tpu.framework")

from yoda_tpu.api.types import PodSpec
from yoda_tpu.framework.cyclestate import CycleState
from yoda_tpu.tracing import subject_of
from yoda_tpu.framework.interfaces import (
    BatchFilterScorePlugin,
    BindPlugin,
    Code,
    FilterPlugin,
    NodeInfo,
    PermitPlugin,
    Plugin,
    PostFilterPlugin,
    PreFilterPlugin,
    PreScorePlugin,
    QueueSortPlugin,
    ReservePlugin,
    ScorePlugin,
    Snapshot,
    Status,
)


class WaitingPod:
    """A pod parked at Permit (gang members wait here until the gang is
    complete). Thread-safe; resolution fires ``on_resolved`` exactly once."""

    def __init__(
        self,
        pod: PodSpec,
        node_name: str,
        state: CycleState,
        pending_plugins: set[str],
        deadline: float,
        on_resolved: Callable[["WaitingPod", Status], None],
        parked_at: float | None = None,
    ) -> None:
        self.pod = pod
        self.node_name = node_name
        self.state = state
        self.deadline = deadline
        self.parked_at = parked_at  # clock time the pod entered the waitlist
        self._pending = set(pending_plugins)
        self._on_resolved = on_resolved
        self._lock = threading.Lock()
        self._resolved: Status | None = None

    @property
    def resolved(self) -> Status | None:
        with self._lock:
            return self._resolved

    def allow(self, plugin_name: str) -> None:
        fire = False
        with self._lock:
            if self._resolved is not None:
                return
            self._pending.discard(plugin_name)
            if not self._pending:
                self._resolved = Status.ok()
                fire = True
        if fire:
            self._on_resolved(self, Status.ok())

    def reject(self, message: str) -> None:
        with self._lock:
            if self._resolved is not None:
                return
            self._resolved = Status.unschedulable(message)
        self._on_resolved(self, Status.unschedulable(message))


class _DaemonPool:
    """Minimal ``ThreadPoolExecutor`` stand-in with DAEMON worker
    threads and the same Future-returning ``submit`` contract.

    stdlib pools deliberately join their (non-daemon) workers at
    interpreter shutdown; for the bind pipeline that policy inverts the
    failure mode we care about — an executor whose owner dropped it
    without ``shutdown()`` keeps idle non-daemon workers alive forever
    (the tests/conftest.py thread-hygiene gate flags exactly this), and
    a stalled bind round-trip can then block process exit. Bind tasks
    need no exit-time draining: in-flight work is bounded by the API
    client's request timeout, reservations roll back through resync, and
    the stop_event already aborts backoff sleeps."""

    def __init__(self, max_workers: int, thread_name_prefix: str) -> None:
        import queue as _queue

        self._max_workers = max_workers
        self._prefix = thread_name_prefix
        self._q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        self._threads: "list[threading.Thread]" = []
        self._lock = threading.Lock()
        self._down = False

    def submit(self, fn: Callable[[], object]):
        from concurrent.futures import Future

        fut: Future = Future()
        with self._lock:
            if self._down:
                raise RuntimeError("cannot submit after shutdown")
            self._q.put((fut, fn))
            if len(self._threads) < self._max_workers:
                t = threading.Thread(
                    target=self._worker,
                    name=f"{self._prefix}_{len(self._threads)}",
                    daemon=True,
                )
                self._threads.append(t)
                t.start()
        return fut

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)

    def shutdown(self, wait: bool = True, cancel_futures: bool = False) -> None:
        import queue as _queue

        with self._lock:
            self._down = True
            threads = list(self._threads)
        if cancel_futures:
            while True:
                try:
                    item = self._q.get_nowait()
                except _queue.Empty:
                    break
                if item is not None:
                    item[0].cancel()
        for _ in threads:
            self._q.put(None)
        if wait:
            for t in threads:
                t.join()


class BindExecutor:
    """Bounded-concurrency bind fan-out — the bind pipeline (config
    ``bind_workers``).

    A persistent thread pool that carries bind API round-trips, and their
    retry/backoff sleeps, OFF the scheduling thread: a gang's waitlist
    release submits every member's allow-and-bind here and returns, so the
    serve loop starts the next cycle's snapshot refresh and kernel dispatch
    while the previous cycle's binds are still in flight. In-flight binds
    stay charged to the accountant through their reservations, so the
    overlapped dispatch already sees their capacity as consumed.

    The executor is the pipeline's completion bookkeeping too:

    - ``inflight()`` feeds the ``yoda_bind_inflight`` gauge and the drain
      barrier (``Scheduler.run_until_idle`` treats pending binds as active
      work instead of concluding idle under them);
    - every settle fires ``on_settled`` (the scheduler wires its activity
      signal) so drain waits are event-bound, not polled;
    - ``stop_event`` is shared with the binder's interruptible backoff
      sleeps: setting it (shutdown, leadership loss) aborts pending retry
      waits promptly instead of draining up to ``retry_cap_s`` each.

    Workers are created lazily on the first submit, so pipeline-disabled
    stacks and tests never pay the threads. They are DAEMON threads (see
    ``_DaemonPool``): an executor whose owner forgot ``shutdown()`` — a
    dropped test stack, a SIGTERM mid-drain — must never wedge
    interpreter exit or trip the tests/conftest.py thread-hygiene gate;
    orderly shutdown still exists and is what cli.py uses.
    """

    def __init__(
        self,
        workers: int = 8,
        *,
        stop_event: "threading.Event | None" = None,
        name: str = "bind",
    ) -> None:
        self.workers = max(int(workers), 1)
        self.stop_event = stop_event if stop_event is not None else threading.Event()
        # Fired (no args) after every task settles, successes and failures
        # alike, AFTER the in-flight count dropped — a waiter woken by it
        # observes the decrement.
        self.on_settled: Callable[[], None] | None = None
        self._name = name
        self._lock = threading.Lock()
        self._pool = None
        self._inflight = 0
        self.submitted = 0  # lifetime task count (tests, introspection)

    def submit(self, fn: Callable[[], None]):
        """Run ``fn`` on a worker; returns the Future. ``fn``'s exceptions
        are logged, never propagated — bind failures are reported through
        the resolution chain, not the future."""
        with self._lock:
            if self._pool is None:
                self._pool = _DaemonPool(
                    max_workers=self.workers,
                    thread_name_prefix=f"{self._name}-worker",
                )
            self._inflight += 1
            self.submitted += 1

        def run() -> None:
            try:
                fn()
            except Exception:  # noqa: BLE001 — workers must never die silently
                log.exception("bind executor task failed")
            finally:
                with self._lock:
                    self._inflight -= 1
                cb = self.on_settled
                if cb is not None:
                    cb()

        return self._pool.submit(run)

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def shutdown(self) -> None:
        """Stop accepting work and abort pending retry sleeps. ``wait=False``
        so a SIGTERM during a stalled bind round-trip does not block the
        drain on the worker; the in-flight HTTP call is bounded by the API
        client's request timeout either way."""
        self.stop_event.set()
        self.release()

    def release(self) -> None:
        """Shut the worker pool WITHOUT firing ``stop_event`` — the live
        shard resize retires one lane's executor while the process-wide
        stop event (shared by every lane's interruptible sleeps) must
        stay unset. Idle daemon workers exit on their sentinels."""
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)


class Framework:
    def __init__(self, plugins: Sequence[Plugin]) -> None:
        # Lifecycle tracer (yoda_tpu/tracing.py), wired by
        # standalone.build_stack: run_bind/run_unbind record spans on
        # WHICHEVER thread executes them — inline binds on the serve
        # thread, pipelined binds on the executor workers — so the
        # Perfetto view shows bind I/O overlapping the next cycle's track.
        self.tracer = None
        self.queue_sort = next(
            (p for p in plugins if isinstance(p, QueueSortPlugin)), None
        )
        self.pre_filter_plugins = [p for p in plugins if isinstance(p, PreFilterPlugin)]
        self.filter_plugins = [p for p in plugins if isinstance(p, FilterPlugin)]
        self.post_filter_plugins = [p for p in plugins if isinstance(p, PostFilterPlugin)]
        self.pre_score_plugins = [p for p in plugins if isinstance(p, PreScorePlugin)]
        self.score_plugins = [p for p in plugins if isinstance(p, ScorePlugin)]
        self.batch_plugins = [p for p in plugins if isinstance(p, BatchFilterScorePlugin)]
        self.reserve_plugins = [p for p in plugins if isinstance(p, ReservePlugin)]
        self.permit_plugins = [p for p in plugins if isinstance(p, PermitPlugin)]
        self.bind_plugins = [p for p in plugins if isinstance(p, BindPlugin)]
        self._waiting: dict[str, WaitingPod] = {}
        self._waiting_lock = threading.Lock()

    # --- filter / score ---

    def run_pre_filter(self, state: CycleState, pod: PodSpec, snapshot: Snapshot) -> Status:
        for p in self.pre_filter_plugins:
            st = p.pre_filter(state, pod, snapshot)
            if not st.success and st.code != Code.SKIP:
                return st
        return Status.ok()

    def run_filters(
        self,
        state: CycleState,
        pod: PodSpec,
        snapshot: Snapshot,
        *,
        stop_after_feasible: int = 0,
        start_index: int = 0,
    ) -> dict[str, Status]:
        """Run the FilterPlugin chain per node. ``stop_after_feasible > 0``
        truncates the SEARCH once that many feasible nodes are found
        (upstream percentageOfNodesToScore semantics: Filter work is
        capped too, not just score fan-out), scanning from the rotating
        ``start_index`` so the cap does not always favor the same
        name-ordered prefix. Unscanned nodes are simply absent from the
        returned map — preemption walks the snapshot itself, so PostFilter
        is unaffected."""
        statuses: dict[str, Status] = {}
        infos = snapshot.infos()
        n = len(infos)
        feasible = 0
        # Node-health fence (yoda_tpu/nodehealth): SUSPECT/DRAINING/DOWN
        # hosts take no NEW placements — the loop-mode half of the veto
        # the batch path applies in its cached admission vector.
        fenced = getattr(snapshot, "fenced", None)
        for i in range(n):
            node = infos[(start_index + i) % n]
            if fenced and node.name in fenced:
                statuses[node.name] = Status.unschedulable(
                    "node fenced by the health monitor (suspect/draining/"
                    "down)"
                )
                continue
            st = Status.ok()
            for p in self.filter_plugins:
                st = p.filter(state, pod, node)
                if not st.success:
                    break
            statuses[node.name] = st
            if st.success:
                feasible += 1
                if stop_after_feasible and feasible >= stop_after_feasible:
                    break
        return statuses

    @property
    def supports_burst(self) -> bool:
        """True when some batch plugin can pre-evaluate a multi-pod burst
        (YodaBatch.prepare_burst) — the scheduler gates its K-pod queue
        pops on this so burst-less stacks never pay the deeper pop."""
        return any(hasattr(p, "prepare_burst") for p in self.batch_plugins)

    def prepare_burst(self, pods: Sequence[PodSpec], snapshot: Snapshot) -> None:
        """Hand the next K pending pods to burst-capable batch plugins: one
        kernel dispatch evaluates them all, and their individual scheduling
        cycles are then served from the cached rows (VERDICT r3 #1). Purely
        advisory — a plugin may decline, and cycles fall back to individual
        dispatches."""
        for p in self.batch_plugins:
            prepare = getattr(p, "prepare_burst", None)
            if prepare is not None:
                prepare(pods, snapshot)

    def prepare_gang(self, pods: Sequence[PodSpec], snapshot: Snapshot) -> None:
        """Hand a gathered gang (every co-queued member, one gang) to
        gang-burst-capable batch plugins: ONE kernel dispatch evaluates all
        members, and each member's cycle is served from its own row with
        the chips claimed by earlier members deducted
        (YodaBatch.prepare_gang_burst). Advisory, like prepare_burst —
        member cycles fall back to per-cycle dispatches / the gang plan."""
        for p in self.batch_plugins:
            prepare = getattr(p, "prepare_gang_burst", None)
            if prepare is not None:
                prepare(pods, snapshot)

    def prepare_joint(
        self,
        groups: "Sequence[Sequence[PodSpec]]",
        snapshot: Snapshot,
    ) -> "list[str] | None":
        """Hand SEVERAL gathered gangs (one group per gang, priority
        order) to joint-capable batch plugins: ONE kernel dispatch
        evaluates every member of every gang, and each gang's cycles are
        served net of the claims of higher-priority gangs in the same
        dispatch (YodaBatch.prepare_joint_burst). Returns the first
        capable plugin's per-group verdicts — "fused" (drive the members
        this turn), "solo" (schedule per-cycle), "park" (cannot fit
        whole; restore untouched) — or None when no plugin can run a
        joint pass (the scheduler then falls back to per-gang passes)."""
        for p in self.batch_plugins:
            prepare = getattr(p, "prepare_joint_burst", None)
            if prepare is not None:
                return prepare(groups, snapshot)
        return None

    def run_batch_filter_score(
        self, state: CycleState, pod: PodSpec, snapshot: Snapshot
    ) -> tuple[dict[str, Status], dict[str, int]] | None:
        """Fused fast path; None when no batch plugin is registered. Regular
        FilterPlugins (e.g. the gang host-pinning filter) still run, but only
        over the batch-feasible subset."""
        if not self.batch_plugins:
            return None
        if len(self.batch_plugins) == 1:
            # Hot path: the plugin's dicts are used directly (the batch
            # contract hands ownership to the caller — plugins must return
            # fresh dicts), skipping the init + merge passes below.
            statuses, totals = self.batch_plugins[0].filter_and_score_batch(
                state, pod, snapshot
            )
            for n in snapshot.names():
                if n not in statuses:
                    statuses[n] = Status.ok()
                    totals.setdefault(n, 0)
        else:
            statuses = {n: Status.ok() for n in snapshot.names()}
            totals = {n: 0 for n in snapshot.names()}
            for p in self.batch_plugins:
                p_statuses, p_scores = p.filter_and_score_batch(
                    state, pod, snapshot
                )
                for n, st in p_statuses.items():
                    if not st.success and statuses[n].success:
                        statuses[n] = st
                for n, s in p_scores.items():
                    totals[n] += s
        for n, st in statuses.items():
            if not st.success:
                continue
            for p in self.filter_plugins:
                st2 = p.filter(state, pod, snapshot.get(n))
                if not st2.success:
                    statuses[n] = st2
                    break
        feasible_scores = {
            n: totals.get(n, 0) for n, st in statuses.items() if st.success
        }
        return statuses, feasible_scores

    def run_post_filter(
        self,
        state: CycleState,
        pod: PodSpec,
        snapshot: Snapshot,
        statuses: Mapping[str, Status],
    ) -> tuple[str | None, Status]:
        for p in self.post_filter_plugins:
            nominated, st = p.post_filter(state, pod, snapshot, statuses)
            if st.success and nominated:
                return nominated, st
            if st.code == Code.ERROR:
                return None, st
        return None, Status.unschedulable("no postfilter plugin could make room")

    def run_pre_score(
        self, state: CycleState, pod: PodSpec, snapshot: Snapshot, feasible: Sequence[str]
    ) -> Status:
        for p in self.pre_score_plugins:
            st = p.pre_score(state, pod, snapshot, feasible)
            if not st.success and st.code != Code.SKIP:
                return st
        return Status.ok()

    def run_scores(
        self, state: CycleState, pod: PodSpec, snapshot: Snapshot, feasible: Sequence[str]
    ) -> tuple[dict[str, int], Status]:
        totals: dict[str, int] = {n: 0 for n in feasible}
        for p in self.score_plugins:
            raw: dict[str, int] = {}
            for n in feasible:
                s, st = p.score(state, pod, snapshot.get(n))
                if not st.success:
                    return {}, st
                raw[n] = s
            st = p.normalize(state, pod, raw)
            if not st.success:
                return {}, st
            for n, s in raw.items():
                totals[n] += s
        return totals, Status.ok()

    # --- reserve / permit / bind ---

    def run_reserve(self, state: CycleState, pod: PodSpec, node_name: str) -> Status:
        done: list[ReservePlugin] = []
        for p in self.reserve_plugins:
            st = p.reserve(state, pod, node_name)
            if not st.success:
                for q in reversed(done):
                    q.unreserve(state, pod, node_name)
                return st
            done.append(p)
        return Status.ok()

    def run_unreserve(self, state: CycleState, pod: PodSpec, node_name: str) -> None:
        for p in reversed(self.reserve_plugins):
            p.unreserve(state, pod, node_name)

    def run_permit(
        self,
        state: CycleState,
        pod: PodSpec,
        node_name: str,
        on_resolved: Callable[[WaitingPod, Status], None],
        *,
        now: float | None = None,
    ) -> Status:
        """Runs Permit plugins. On WAIT, registers a WaitingPod and returns
        WAIT; ``on_resolved`` fires (possibly on another thread, possibly
        re-entrantly from a later permit call) once it is allowed/rejected."""
        waiting_plugins: set[str] = set()
        max_timeout = 0.0
        for p in self.permit_plugins:
            st, timeout = p.permit(state, pod, node_name)
            if st.code == Code.WAIT:
                waiting_plugins.add(p.name)
                max_timeout = max(max_timeout, timeout)
            elif not st.success:
                return st
        if not waiting_plugins:
            return Status.ok()
        now = time.monotonic() if now is None else now
        wp = WaitingPod(
            pod,
            node_name,
            state,
            waiting_plugins,
            deadline=now + max_timeout,
            on_resolved=lambda w, s: self._finish_waiting(w, s, on_resolved),
            parked_at=now,
        )
        with self._waiting_lock:
            self._waiting[pod.key] = wp
        # A permit plugin may have been waiting for exactly this pod (last
        # gang member): give plugins a chance to flush now it is registered.
        for p in self.permit_plugins:
            post = getattr(p, "on_pod_waiting", None)
            if post is not None:
                post(self, wp)
        return Status.wait()

    def _finish_waiting(
        self, wp: WaitingPod, status: Status, cb: Callable[[WaitingPod, Status], None]
    ) -> None:
        with self._waiting_lock:
            self._waiting.pop(wp.pod.key, None)
        # Permit plugins observe resolutions first (gang bookkeeping and
        # cascade rollback), then the scheduler binds or unreserves.
        for p in self.permit_plugins:
            hook = getattr(p, "on_pod_resolved", None)
            if hook is not None:
                hook(self, wp, status)
        cb(wp, status)

    def waiting_pods(self) -> list[WaitingPod]:
        with self._waiting_lock:
            return list(self._waiting.values())

    def get_waiting_pod(self, pod_key: str) -> WaitingPod | None:
        with self._waiting_lock:
            return self._waiting.get(pod_key)

    def cancel_waiting(self, pod_key: str, message: str) -> bool:
        """Reject ONE waiting pod by key, if present — the delete-event
        fast path and the drift reconciler cancel a deleted pod's Permit
        wait immediately instead of letting it eat the full timeout (its
        gang cascade then releases every sibling's reservation). Returns
        whether a wait was actually cancelled."""
        wp = self.get_waiting_pod(pod_key)
        if wp is None:
            return False
        wp.reject(message)
        return True

    def expire_waiting(self, *, now: float | None = None) -> int:
        """Reject waiting pods past their Permit deadline. Returns count."""
        now = time.monotonic() if now is None else now
        expired = [w for w in self.waiting_pods() if now >= w.deadline]
        for w in expired:
            w.reject(f"permit wait timed out for pod {w.pod.key}")
        return len(expired)

    # An inline bind cheaper than this adds no information beyond its
    # cycle span (whose wall already contains it) — recording it would be
    # pure hot-path cost. Real API binds are milliseconds and always
    # clear the gate; executor-side binds record regardless (their wall
    # lives on a worker track the cycle span cannot show).
    BIND_SPAN_MIN_S = 0.0005

    def run_bind(self, state: CycleState, pod: PodSpec, node_name: str) -> Status:
        tracer = self.tracer
        if tracer is None or not tracer.enabled:
            return self._run_bind_inner(state, pod, node_name)
        t0 = time.monotonic()
        st = self._run_bind_inner(state, pod, node_name)
        t1 = time.monotonic()
        track = threading.current_thread().name
        if (
            t1 - t0 >= self.BIND_SPAN_MIN_S
            or not st.success
            or track.startswith("bind-")
        ):
            tracer.add(
                subject_of(pod), "bind",
                t0=t0, t1=t1, track=track,
                attrs={"pod": pod.key, "node": node_name, "ok": st.success},
            )
        return st

    def _run_bind_inner(
        self, state: CycleState, pod: PodSpec, node_name: str
    ) -> Status:
        for p in self.bind_plugins:
            st = p.bind(state, pod, node_name)
            if st.code != Code.SKIP:
                return st
        return Status.error(f"no bind plugin bound pod {pod.key}")

    def run_unbind(self, state: CycleState, pod: PodSpec, node_name: str) -> Status:
        """Reverse a landed bind (transactional gang rollback): the first
        bind plugin implementing ``unbind`` handles it. An error status —
        including no plugin implementing it — means the pod may be
        stranded bound; the caller logs it and the watch stream remains
        the source of truth."""
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            t0 = time.monotonic()
            st = self._run_unbind_inner(state, pod, node_name)
            tracer.add(
                subject_of(pod), "unbind",
                t0=t0, t1=time.monotonic(),
                attrs={"pod": pod.key, "node": node_name, "ok": st.success},
            )
            return st
        return self._run_unbind_inner(state, pod, node_name)

    def _run_unbind_inner(
        self, state: CycleState, pod: PodSpec, node_name: str
    ) -> Status:
        for p in self.bind_plugins:
            unbind = getattr(p, "unbind", None)
            if unbind is not None:
                st = unbind(state, pod, node_name)
                if st.code != Code.SKIP:
                    return st
        return Status.error(f"no bind plugin can unbind pod {pod.key}")
