"""Per-tenant accounting for DRF fair queuing and quota admission.

The upstream scheduling framework (KEP-624, PAPERS.md) has no tenant
model at all — one flooding namespace starves every other through the
single FIFO+priority queue. Here (ISSUE 10) a tenant is a namespace
(overridable per pod via the ``tpu/tenant`` label, so one namespace can
host several billed tenants or several namespaces can share one), and
the :class:`TenantLedger` maintains each tenant's *dominant resource
share* (Ghodsi et al.'s DRF, PAPERS.md): usage over the two fleet
resources that matter — TPU chips and HBM — each as a fraction of fleet
capacity, the tenant's share being the max of the two. The scheduling
queue (``framework/queue.py``) pops from the lowest-share tenant first,
which is what makes a flooding tenant unable to starve anyone: every
pod it binds raises its share and pushes it behind the tenants it was
flooding past.

The ledger is watch-driven (exactly like ``ChipAccountant``): fleet
capacity comes from TpuNodeMetrics CRs, usage from bound-pod events, so
the whole thing reconstructs from a list+watch replay on scheduler
restart and costs nothing on the scheduling hot path beyond a dict read
per pop.
"""

from __future__ import annotations

import threading

from yoda_tpu.api.requests import LabelParseError, pod_request

TENANT_LABEL = "tpu/tenant"

MIB = 1 << 20


def tenant_of(pod) -> str:
    """The tenant a pod bills to: the ``tpu/tenant`` label when present,
    else the pod's namespace."""
    return pod.labels.get(TENANT_LABEL) or pod.namespace


def _pod_demand(pod) -> "tuple[int, int]":
    """(chips, hbm_mib) a pod occupies for share/quota accounting. Pods
    with no recognizable TPU ask charge their ``google.com/tpu`` resource
    limit (chips only) or nothing — non-TPU pods do not move TPU shares."""
    try:
        req = pod_request(pod)
    except LabelParseError:
        limit = getattr(pod, "tpu_resource_limit", 0)
        return (limit, 0) if limit > 0 else (0, 0)
    if not req.wants_tpu:
        limit = getattr(pod, "tpu_resource_limit", 0)
        return (limit, 0) if limit > 0 else (0, 0)
    chips = req.effective_chips
    return chips, (req.hbm_per_chip // MIB) * chips


class TenantLedger:
    """Watch-driven per-tenant usage + fleet capacity, and the DRF share
    and quota verdicts computed from them. Thread-safe; every reader is
    one lock acquisition over small dicts."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # node -> (healthy chips, total hbm MiB): fleet capacity.
        self._nodes: dict[str, tuple[int, int]] = {}
        self._cap_chips = 0
        self._cap_hbm = 0
        # pod uid -> (tenant, chips, hbm_mib): idempotent charge records.
        self._pods: dict[str, tuple[str, int, int]] = {}
        # tenant -> [chips, hbm_mib] in use (bound pods only).
        self._usage: dict[str, list[int]] = {}

    # --- watch sink (registration order does not matter: independent
    # state; standalone registers it alongside the accountant) ---

    def handle(self, event) -> None:
        if event.kind == "TpuNodeMetrics":
            tpu = event.obj
            with self._lock:
                if event.type == "deleted":
                    chips, hbm = self._nodes.pop(tpu.name, (0, 0))
                    self._cap_chips -= chips
                    self._cap_hbm -= hbm
                else:
                    healthy = tpu.healthy_chips()
                    cap = (
                        len(healthy),
                        sum(c.hbm_total for c in healthy) // MIB,
                    )
                    prev = self._nodes.get(tpu.name, (0, 0))
                    self._nodes[tpu.name] = cap
                    self._cap_chips += cap[0] - prev[0]
                    self._cap_hbm += cap[1] - prev[1]
            return
        if event.kind != "Pod":
            return
        pod = event.obj
        if event.type == "deleted" or not pod.node_name:
            # Deleted, or unbound (including a rollback's unbind — the
            # capacity returns to the pool the moment the modified event
            # lands).
            self.release(pod.uid)
        else:
            self.charge(pod)

    def handle_batch(self, events) -> None:
        for event in events:
            self.handle(event)

    # --- charging ---

    def charge(self, pod) -> None:
        chips, hbm = _pod_demand(pod)
        if chips == 0 and hbm == 0:
            return
        tenant = tenant_of(pod)
        with self._lock:
            if pod.uid in self._pods:
                return  # bind-event replay / reserve->bind: single charge
            self._pods[pod.uid] = (tenant, chips, hbm)
            use = self._usage.setdefault(tenant, [0, 0])
            use[0] += chips
            use[1] += hbm

    def release(self, uid: str) -> None:
        with self._lock:
            rec = self._pods.pop(uid, None)
            if rec is None:
                return
            tenant, chips, hbm = rec
            use = self._usage.get(tenant)
            if use is not None:
                use[0] = max(use[0] - chips, 0)
                use[1] = max(use[1] - hbm, 0)
                if use == [0, 0]:
                    del self._usage[tenant]

    # --- readers ---

    def capacity(self) -> "tuple[int, int]":
        with self._lock:
            return self._cap_chips, self._cap_hbm

    def usage(self, tenant: str) -> "tuple[int, int]":
        with self._lock:
            use = self._usage.get(tenant)
            return (use[0], use[1]) if use else (0, 0)

    def dominant_share(self, tenant: str) -> float:
        """max(chips share, HBM share) in [0, 1] — the DRF ordering key.
        An empty fleet puts every tenant at share 0 (pure FIFO)."""
        with self._lock:
            use = self._usage.get(tenant)
            if not use:
                return 0.0
            chip_share = use[0] / self._cap_chips if self._cap_chips else 0.0
            hbm_share = use[1] / self._cap_hbm if self._cap_hbm else 0.0
            return max(chip_share, hbm_share)

    def shares(self) -> "dict[str, float]":
        """Every tenant with nonzero usage -> dominant share (the
        yoda_tenant_dominant_share gauge)."""
        with self._lock:
            out: dict[str, float] = {}
            for tenant, use in self._usage.items():
                chip_share = (
                    use[0] / self._cap_chips if self._cap_chips else 0.0
                )
                hbm_share = use[1] / self._cap_hbm if self._cap_hbm else 0.0
                out[tenant] = max(chip_share, hbm_share)
            return out

    def quota_verdict(
        self, tenant: str, pod, *, chips_cap: int = 0, hbm_cap_mib: int = 0
    ) -> "str | None":
        """Why-pending verdict when admitting ``pod`` would push its
        tenant past a per-tenant quota, else None. Usage is BOUND usage,
        which only moves when binds land — so a gang gathered in one
        locked queue pass sees one consistent verdict for every member
        (all gather or all park; atomicity at gather granularity), and a
        gang admitted under-quota may finish binding past the cap: the
        overshoot is bounded by one admission's ask. 0 = unlimited."""
        chips, hbm = _pod_demand(pod)
        with self._lock:
            use = self._usage.get(tenant) or (0, 0)
            if chips_cap and use[0] + chips > chips_cap:
                return (
                    f"tenant {tenant} over chip quota: "
                    f"{use[0]} in use + {chips} asked > {chips_cap}"
                )
            if hbm_cap_mib and use[1] + hbm > hbm_cap_mib:
                return (
                    f"tenant {tenant} over HBM quota: "
                    f"{use[1]} MiB in use + {hbm} MiB asked > "
                    f"{hbm_cap_mib} MiB"
                )
        return None
