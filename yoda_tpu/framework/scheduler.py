"""The scheduling loop: pop → cycle → reserve → permit → bind.

The from-scratch equivalent of the upstream scheduleOne driver the reference
inherits via ``app.NewSchedulerCommand`` (reference pkg/register/register.go:10).
One scheduling cycle is serialized (as upstream); Permit waits do NOT block
the loop — waiting pods park on the framework waitlist and are bound from the
resolution callback (gang scheduling, SURVEY.md §7 step 4).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

log = logging.getLogger("yoda_tpu.scheduler")

from yoda_tpu.api.requests import gang_name_of
from yoda_tpu.api.types import PodSpec
from yoda_tpu.framework.cyclestate import (
    SHARD_STATE_KEY,
    CycleState,
    ShardTag,
)
from yoda_tpu.framework.interfaces import (
    Code,
    MAX_NODE_SCORE,
    Snapshot,
    Status,
    summarize_failure,
)
from yoda_tpu.framework.queue import QueuedPodInfo, SchedulingQueue
from yoda_tpu.framework.runtime import Framework, WaitingPod
from yoda_tpu.observability import PhaseTimer, SchedulingMetrics, TraceEntry
from yoda_tpu.tracing import subject_of


@dataclass
class ScheduleResult:
    pod_key: str
    outcome: str  # "bound" | "waiting" | "unschedulable" | "error" | "nominated" | "gone"
    node: str | None = None
    message: str = ""
    latency_s: float = 0.0
    # Cycle-completion instant on the scheduler's clock (monotonic by
    # default) — lets external harnesses decompose end-to-end latency
    # into pre-cycle (watch delivery + queue wait), in-cycle, and
    # post-cycle shares (bench.py _http_gang_scenario).
    completed_at: float = 0.0


@dataclass
class SchedulerStats:
    results: list[ScheduleResult] = field(default_factory=list)
    binds: int = 0
    preempt_nominations: int = 0

    def latencies(self) -> list[float]:
        return [r.latency_s for r in self.results]


# Never score fewer feasible nodes than this when percentage_nodes_to_score
# caps the set (the scaled-down analog of upstream's minFeasibleNodesToFind,
# which is 100 — TPU fleets are 1-2 orders smaller than general clusters).
MIN_FEASIBLE_TO_SCORE = 8


class Scheduler:
    def __init__(
        self,
        framework: Framework,
        snapshot_fn: Callable[[], Snapshot],
        queue: SchedulingQueue,
        *,
        clock: Callable[[], float] = time.monotonic,
        on_bound: Callable[[PodSpec, str], None] | None = None,
        on_unschedulable: Callable[[PodSpec, str], None] | None = None,
        on_nominated: Callable[[PodSpec, "str | None"], None] | None = None,
        cycle_lock: "threading.Lock | None" = None,
        post_filter_lock: "threading.Lock | None" = None,
        metrics: SchedulingMetrics | None = None,
        percentage_nodes_to_score: int = 100,
        pod_alive: Callable[[PodSpec], bool] | None = None,
        burst_size: int = 1,
        fence_fn: "Callable[[], bool] | None" = None,
        bind_executor=None,
    ) -> None:
        self.framework = framework
        self.snapshot_fn = snapshot_fn
        self.queue = queue
        self.clock = clock
        self.stats = SchedulerStats()
        self.on_bound = on_bound
        self.on_unschedulable = on_unschedulable
        self.on_nominated = on_nominated
        # Shared across profile stacks (standalone.build_profile_stacks):
        # serializes whole scheduling cycles so two profiles cannot both
        # pass Filter against the same free chips before either Reserves —
        # upstream profiles get this for free from their single scheduleOne
        # loop. None = private lock (single-profile, no contention).
        self.cycle_lock = cycle_lock or threading.Lock()
        # Also shared across profile stacks, but narrower: serializes
        # PostFilter preemption only. Victim selection reads snapshot
        # occupancy and issues evictions; two profiles preempting
        # concurrently would pick overlapping victim sets for the same
        # chips (over-eviction). Separate from cycle_lock so the eviction
        # API round-trips never stall Filter->Reserve of other profiles
        # (lock order: cycle_lock is RELEASED before this is taken).
        self.post_filter_lock = post_filter_lock or threading.Lock()
        self.metrics = metrics
        self.percentage_nodes_to_score = percentage_nodes_to_score
        self.pod_alive = pod_alive
        # Multi-pod fused dispatch (config batch_requests): pop up to this
        # many queue entries at once and pre-evaluate them in ONE kernel
        # call (Framework.prepare_burst); each entry still runs its own
        # full scheduling cycle, served from the burst cache. Bounded
        # priority inversion: a higher-priority pod arriving mid-burst
        # waits at most burst_size - 1 cycles (upstream pops one at a
        # time; the amortization is worth the K-deep window). The gang
        # gather (_gather_gang) extends the same promise: popping one gang
        # member pulls its co-queued siblings, so a higher-priority
        # singleton waits at most gang_size - 1 member cycles — bounded by
        # the gang's own size, never by queue depth.
        self.burst_size = max(burst_size, 1)
        # Event-bound drain (run_until_idle): permit resolutions and queue
        # activity bump _activity_seq and wake the waiter, so drain latency
        # tracks the event, not a poll interval.
        self._activity = threading.Condition()
        self._activity_seq = 0
        queue.on_activity = self._signal_activity
        # Leader fencing (failure-domain hardening): when wired (cli.py
        # passes LeaderElector.is_leader), a False return FENCES the
        # scheduler — binds are aborted BEFORE the API write (a new leader
        # may already be acting on the same pods) and the serve loop parks
        # the queue until leadership returns. Settable post-construction.
        self.fence_fn = fence_fn
        # Speculative placement cache (framework/speculation.py): wired by
        # the stack builder; None disables the fast path entirely. The
        # consume chain in _schedule_one_locked (lookup -> fence ->
        # epoch_valid -> revalidate -> consume_plan) is the only reader.
        self.speculation = None
        # Bind pipeline (ISSUE 4): when wired, gang releases fan their
        # member binds out on this executor and the serve loop OVERLAPS
        # the next cycle (snapshot refresh + kernel dispatch) with the
        # in-flight binds. The scheduler treats pending binds as active
        # work: run_until_idle never concludes idle under them, and every
        # settle bumps the activity condition so drain waits stay
        # event-bound.
        self.bind_executor = bind_executor
        if bind_executor is not None:
            bind_executor.on_settled = self._signal_activity
        # Warm-start gate (crash-safe failover): when wired (cli.py sets
        # the stack reconciler's resync), serve_forever invokes this ONCE,
        # after the fence first reports leadership but BEFORE the first
        # queue pop — so the resync pass (rebuild reservations from
        # cluster truth, adopt/rollback partial gangs) completes before
        # any post-promotion bind can happen. A raising hook propagates:
        # serving on un-resynced state risks double-placement, so the
        # process fails closed and restarts into standby.
        self.on_serve_start: "Callable[[], None] | None" = None
        # Scheduler shard-out (framework/shards.py): when this loop is one
        # of N parallel shards, `shard` names it (cycles are tagged so the
        # shared accountant STAGES their claims) and `commit_fn`
        # (ChipAccountant.commit_staged) is the optimistic
        # claim->validate->commit point — singletons validate immediately
        # before their bind write; a gang's cohort validates once every
        # member's bind has landed (_flush_shard_commits), rolling the
        # gang back whole through the transactional unbind path on a
        # conflict. Both None (the default) = today's unsharded path,
        # nothing staged, nothing committed.
        self.shard: "str | None" = None
        self.commit_fn: "Callable[[list], tuple[bool, str]] | None" = None
        # Live shard resize (ShardSet.resize): a dissolved shard's loop
        # is RETIRED — permanently fenced (no bind can start) and its
        # serve_forever thread exits at the next turn. Queued work was
        # already rerouted by the resizer; anything that straggles in
        # parks fenced until the final reroute sweep moves it.
        self.retired = threading.Event()
        self._search_rotor = 0
        # (retire() lives below with the loop methods.)
        # pod uid -> node nominated by preemption this session; consulted at
        # bind time so a pod that ends up on a DIFFERENT node gets its
        # stale status.nominatedNodeName cleared (phantom earmarked
        # capacity otherwise). Entries drop on bind or deletion.
        self._nominated: dict[str, str] = {}
        self._lock = threading.Lock()

    def _bind_inflight(self) -> int:
        """Binds currently in flight on the pipeline executor (0 when no
        executor is wired — every bind then runs inline in its cycle)."""
        ex = self.bind_executor
        return ex.inflight() if ex is not None else 0

    def _fenced(self) -> bool:
        """True when a leader gate is wired and this process does NOT hold
        leadership right now: no bind may hit the API. A raising fence
        check counts as fenced — fail closed. A RETIRED loop (its shard
        dissolved by a live resize) is fenced forever."""
        if self.retired.is_set():
            return True
        fn = self.fence_fn
        if fn is None:
            return False
        try:
            return not fn()
        except Exception:  # noqa: BLE001 — fail closed
            log.exception("fence check failed; treating scheduler as fenced")
            return True

    def _search_limit(self, n_nodes: int) -> int:
        """Upstream percentageOfNodesToScore, the SEARCH half: how many
        feasible nodes the filter scan needs before it may stop. 0 = no
        cap (the default 100%%, tiny fleets, or batch mode — the fused
        kernel filters the fleet in one dispatch where a cap would cost
        placement quality and save nothing)."""
        pct = self.percentage_nodes_to_score
        if pct >= 100 or n_nodes <= MIN_FEASIBLE_TO_SCORE:
            return 0
        return max(-(-(n_nodes * pct) // 100), MIN_FEASIBLE_TO_SCORE)

    def _search_start(self, n_nodes: int) -> int:
        """Rotating scan origin (upstream nextStartNodeIndex). The rotor is
        advanced AFTER the scan by the number of nodes actually visited
        (:meth:`_advance_search`): a long infeasible run is skipped by the
        next cycle instead of being re-filtered window-width at a time."""
        if n_nodes <= 0 or self.percentage_nodes_to_score >= 100:
            return 0
        with self._lock:
            return self._search_rotor % n_nodes

    def _advance_search(self, visited: int) -> None:
        with self._lock:
            self._search_rotor += max(visited, 1)

    # --- one pod ---

    def schedule_one(self, qpi: QueuedPodInfo) -> ScheduleResult:
        # The lock must cover snapshot -> Filter -> Reserve (two profiles
        # must not both pass Filter on the same free chips before either
        # Reserves); once Reserve has charged the shared accountant, other
        # profiles' Filters see the claim, so the body releases the lock
        # BEFORE Permit/Bind/PostFilter — a slow bind or PDB-aware
        # eviction round-trip must not stall every other profile's queue.
        self.cycle_lock.acquire()
        released = [False]

        def release_cycle_lock() -> None:
            if not released[0]:
                released[0] = True
                self.cycle_lock.release()

        try:
            return self._schedule_one_locked(qpi, release_cycle_lock)
        finally:
            release_cycle_lock()

    def _schedule_one_locked(
        self,
        qpi: QueuedPodInfo,
        release_cycle_lock: Callable[[], None] = lambda: None,
    ) -> ScheduleResult:
        pod = qpi.pod
        t0 = self.clock()
        # A pod deleted while queued must be dropped, not retried forever
        # through the bind-error path (upstream removes deleted pods from
        # its queues; here the check is at cycle start, which also covers
        # deletion races around requeues).
        if self.pod_alive is not None and not self.pod_alive(pod):
            # The hook reports "should this queue entry still be scheduled"
            # (informer.pod_schedulable): deleted, already bound via a
            # fresher copy, or currently held by scheduling gates.
            log.debug(
                "pod %s no longer schedulable (deleted/bound/gated); "
                "dropping queue entry", pod.key,
            )
            with self._lock:
                self._nominated.pop(pod.uid, None)
            now = self.clock()
            r = ScheduleResult(
                pod.key, "gone", latency_s=now - t0, completed_at=now
            )
            with self._lock:
                self.stats.results.append(r)
            if self.metrics is not None:
                self.metrics.attempts.inc(result="gone")
            return r
        state = CycleState()
        if self.shard is not None:
            # Tag the cycle so the shared accountant stages (rather than
            # finalizes) this cycle's Reserve claims for the optimistic
            # commit validation.
            state.write(SHARD_STATE_KEY, ShardTag(self.shard))
        snapshot = self.snapshot_fn()
        timer = PhaseTimer(self.clock)
        feasible_count = 0
        # Pre-bound for done()'s closure: the filter section rebinds it
        # with the real per-node verdict map; prefilter-path exits see {}.
        statuses: dict[str, Status] = {}
        # Lifecycle tracing (yoda_tpu/tracing.py): one "cycle" span per
        # scheduling attempt on the pod/gang's trace, with the outcome,
        # chosen node, and per-phase wall splits as attributes. None when
        # tracing is off — the only cost then is this attribute read.
        tracer = self.metrics.tracer if self.metrics is not None else None
        if tracer is not None and not tracer.enabled:
            tracer = None
        subject = subject_of(pod) if tracer is not None else None

        def done(
            outcome: str,
            node: str | None = None,
            message: str = "",
            *,
            unresolvable: bool = False,
        ) -> ScheduleResult:
            # Nothing below needs the cross-profile cycle lock (stats,
            # queue ops, Event/status callbacks — including the synchronous
            # on_nominated PATCH, an API round-trip that must not stall
            # other profiles' queues). Filter->Reserve is already past or
            # never happened on this path. No-op when already released.
            release_cycle_lock()
            now = self.clock()
            r = ScheduleResult(
                pod.key, outcome, node, message, now - t0, completed_at=now
            )
            # One line per outcome at INFO (the reference's operational klog
            # trail, reference pkg/yoda/scheduler.go:143); waiting members
            # are routine gang mechanics -> DEBUG.
            if outcome == "bound":
                log.info(
                    "bound %s -> %s (%d/%d nodes feasible, %.1f ms)",
                    pod.key, node, feasible_count, len(snapshot),
                    r.latency_s * 1e3,
                )
                self._clear_stale_nomination(pod, node)
            elif outcome == "nominated":
                log.info("nominated %s -> %s: %s", pod.key, node, message)
            elif outcome == "unschedulable":
                log.info("unschedulable %s: %s", pod.key, message)
            elif outcome == "error":
                log.warning("error scheduling %s: %s", pod.key, message)
            else:
                log.debug("pod %s waiting at permit on %s", pod.key, node)
            with self._lock:
                self.stats.results.append(r)
            if self.metrics is not None:
                self.metrics.attempts.inc(result=outcome)
                self.metrics.latency.observe(r.latency_s, phase="total")
                timer.observe_into(self.metrics.latency)
                self.metrics.trace(
                    TraceEntry(
                        pod_key=pod.key,
                        outcome=outcome,
                        node=node,
                        nodes_total=len(snapshot),
                        nodes_feasible=feasible_count,
                        message=message,
                        phases_ms=dict(timer.phases_ms),
                    )
                )
            if tracer is not None:
                # timer.phases_ms is handed over as-is (the timer dies
                # with this cycle) — building per-phase attr keys here
                # costs more than the whole record append.
                cycle_attrs = {
                    "pod": pod.key,
                    "outcome": outcome,
                    "node": node or "",
                    "message": message[:200],
                    "phases_ms": timer.phases_ms,
                }
                if self.shard is not None:
                    # Shard spans (ISSUE 14): which serve loop ran this
                    # cycle — the trace-side half of explain's shard tag.
                    cycle_attrs["shard"] = self.shard
                cycle_id = tracer.add(
                    subject, "cycle", t0=t0, t1=now, attrs=cycle_attrs,
                )
                if outcome == "waiting":
                    tracer.add(
                        subject, "permit-park", parent=cycle_id,
                        attrs={"pod": pod.key, "node": node or ""},
                    )
                elif outcome == "bound" and gang_name_of(pod.labels):
                    # Gang members bound directly (the fused pass's last
                    # member) mark the edge explicitly; singleton cycles
                    # already say outcome=bound on the cycle span — a
                    # second record per bind would be pure hot-path cost.
                    tracer.add(
                        subject, "bound", parent=cycle_id,
                        attrs={"pod": pod.key, "node": node or ""},
                    )
            if self.metrics is not None:
                # Why-pending index: every rejection verdict aggregates
                # per pod AND per gang; a bind retires the entry.
                gang = gang_name_of(pod.labels)
                if outcome in ("unschedulable", "error", "nominated"):
                    self.metrics.pending.record(
                        pod.key,
                        kind=outcome,
                        message=message,
                        gang=gang,
                        node_reasons={
                            n: s.message
                            for n, s in statuses.items()
                            if not s.success
                        }
                        or None,
                        shard=self.shard,
                    )
                elif outcome == "bound":
                    self.metrics.pending.resolve(pod.key, gang=gang)
            if outcome == "unschedulable":
                if unresolvable:
                    self.queue.park_unresolvable(qpi, message)
                else:
                    self.queue.add_unschedulable(qpi, message)
                if self.on_unschedulable:
                    self.on_unschedulable(pod, message)
            elif outcome == "error":
                # Errors are RETRYABLE, not terminal: a kernel-dispatch or
                # plugin exception must not silently drop the pod from the
                # queue (the pre-hardening behavior). The backoff ladder
                # bounds the retry rate if the error is chronic.
                self.queue.add_unschedulable(qpi, message)
                if self.on_unschedulable:
                    self.on_unschedulable(pod, message)
            elif outcome == "nominated":
                # Preemption made room; victims must terminate before the pod
                # fits, so requeue and let the next cycle place it. The
                # nomination is also surfaced to the cluster
                # (status.nominatedNodeName — kubectl's NOMINATED NODE
                # column, upstream parity) via the backend's status patch.
                self.queue.add_unschedulable(qpi, message)
                with self._lock:
                    self.stats.preempt_nominations += 1
                if node is not None:
                    with self._lock:
                        changed = self._nominated.get(pod.uid) != node
                        self._nominated[pod.uid] = node
                    # Re-nomination to the same node happens every retry
                    # cycle while victims drain gracefully: skip the
                    # identical (synchronous) status PATCH.
                    if changed and self.on_nominated is not None:
                        self.on_nominated(pod, node)
            return r

        if pod.scheduling_gates:
            # Defensive (the informer keeps gated pods out of the queue): a
            # gated copy that reaches a cycle anyway parks via the standard
            # unresolvable path — full metrics/trace/Events bookkeeping —
            # until the gate-clear watch event enqueues the current copy.
            return done(
                "unschedulable",
                message="pod has scheduling gates; not ready to schedule",
                unresolvable=True,
            )

        with timer.span("prefilter"):
            st = self.framework.run_pre_filter(state, pod, snapshot)
        if not st.success:
            if st.code == Code.UNSCHEDULABLE:
                # PreFilter rejections (gang admission: not enough capacity
                # for the whole gang) reach PostFilter too, as upstream —
                # preemption is how a training gang displaces inference pods
                # (BASELINE config 5). Unresolvable (bad labels) cannot be
                # helped by eviction. No Reserve happens on this path, so
                # the cycle lock is released BEFORE the eviction round-trips
                # (pods/eviction + PDB 429 handling must not stall other
                # profiles' queues — ADVICE r3).
                release_cycle_lock()
                with timer.span("postfilter"), self.post_filter_lock:
                    nominated, pf_st = self.framework.run_post_filter(
                        state, pod, snapshot, {}
                    )
                if nominated:
                    return done("nominated", node=nominated, message=pf_st.message)
            return done(
                "unschedulable",
                message=st.message,
                unresolvable=st.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE,
            )

        # Speculative placement cache (framework/speculation.py): a hot,
        # constraint-free shape can bind from a plan the rebalancer's idle
        # capacity pre-validated between cycles, skipping the O(fleet)
        # filter/score spans entirely. Consumption is gated on the leader
        # fence, the plan's epoch validity against BOTH informer delta
        # feeds, and an O(1) admission + staged-claim spot check on the
        # single chosen node; a failed Reserve falls through to the full
        # path below — a speculative miss never parks the pod.
        best: str | None = None
        spec = self.speculation
        if spec is not None and spec.enabled:
            t_spec = self.clock()
            with timer.span("spec"):
                node = None
                plan = spec.lookup(pod)
                if (
                    plan is not None
                    and not self._fenced()
                    and spec.epoch_valid(plan)
                    and spec.revalidate(plan, pod, snapshot)
                ):
                    node = spec.consume_plan(plan)
            if node is not None:
                with timer.span("reserve"):
                    st = self.framework.run_reserve(state, pod, node)
                if st.success:
                    best = node
                    feasible_count = 1
                    spec.record_bound((self.clock() - t_spec) * 1e3)
                else:
                    # A foreign claim raced the window between the spot
                    # check and Reserve; the plan was already consumed.
                    spec.reserve_rejected(plan)

        if best is None:
            # Fused batch filter+score (TPU-native hot path), else per-node
            # loops.
            with timer.span("filter"):
                try:
                    batch = self.framework.run_batch_filter_score(
                        state, pod, snapshot
                    )
                except Exception as e:  # noqa: BLE001 — keep the loop serving
                    # The batch plugin's own fallback chain
                    # (YodaBatch._dispatch) already demoted through every
                    # kernel backend; reaching here means even the host
                    # evaluator failed. The pod retries via the error path;
                    # the loop survives.
                    log.exception(
                        "batch filter/score failed for %s; retrying via "
                        "backoff",
                        pod.key,
                    )
                    return done(
                        "error", message=f"batch filter/score failed: {e}"
                    )
                if batch is not None:
                    statuses, batch_scores = batch
                    feasible = sorted(batch_scores)
                else:
                    limit = self._search_limit(len(snapshot))
                    statuses = self.framework.run_filters(
                        state, pod, snapshot,
                        stop_after_feasible=limit,
                        start_index=self._search_start(len(snapshot)),
                    )
                    if limit:
                        # run_filters records a status per node VISITED, so
                        # the map's size is the processed count (upstream
                        # advances nextStartNodeIndex the same way).
                        self._advance_search(len(statuses))
                    batch_scores = {}
                    feasible = sorted(
                        n for n, s in statuses.items() if s.success
                    )
            feasible_count = len(feasible)
            # The reference's V(3) per-node decision detail (scheduler.go:67).
            # Under search truncation, statuses covers only the scanned window
            # — say so, or 12/1000 reads as 988 infeasible nodes.
            if log.isEnabledFor(logging.DEBUG):
                log.debug(
                    "pod %s: %d/%d scanned nodes feasible (fleet %d)",
                    pod.key, feasible_count, len(statuses), len(snapshot),
                )
                for n in sorted(statuses):
                    s = statuses[n]
                    if not s.success:
                        log.debug(
                            "pod %s: node %s rejected: %s",
                            pod.key, n, s.message,
                        )

            if not feasible:
                # As above: no Reserve on the infeasible path — release
                # before the preemption API round-trips.
                release_cycle_lock()
                with timer.span("postfilter"), self.post_filter_lock:
                    nominated, pf_st = self.framework.run_post_filter(
                        state, pod, snapshot, statuses
                    )
                if nominated:
                    return done(
                        "nominated", node=nominated, message=pf_st.message
                    )
                return done(
                    "unschedulable", message=summarize_failure(statuses)
                )

            with timer.span("score"):
                st = self.framework.run_pre_score(
                    state, pod, snapshot, feasible
                )
                totals = {}
                if st.success:
                    totals, st = self.framework.run_scores(
                        state, pod, snapshot, feasible
                    )
            # Outside the span: returning from inside it would drop the
            # score phase from this cycle's trace entry and latency
            # histogram.
            if not st.success:
                return done("error", message=st.message)
            if batch_scores:
                if self.framework.score_plugins:
                    # Combining with per-node plugins: bring the batch total
                    # onto the same [0,100] scale.
                    normalized = _normalize(batch_scores)
                    for n in feasible:
                        totals[n] = totals.get(n, 0) + normalized[n]
                else:
                    # Batch is the only scorer (the normal fused mode): its
                    # scores are already normalized+tiered; re-normalizing
                    # would only quantize away within-tier ordering.
                    totals = dict(batch_scores)

            best = max(feasible, key=lambda n: (totals.get(n, 0), n))
            # Final scores (the reference's V(3) score log, scheduler.go:143).
            if log.isEnabledFor(logging.DEBUG):
                ranked = sorted(
                    ((totals.get(n, 0), n) for n in feasible), reverse=True
                )
                log.debug(
                    "pod %s: scores %s -> %s",
                    pod.key,
                    [(n, s) for s, n in ranked[:8]],
                    best,
                )

            with timer.span("reserve"):
                st = self.framework.run_reserve(state, pod, best)
            if not st.success:
                return done("unschedulable", node=best, message=st.message)

        # Reservation charged: other profiles' cycles now see the claim.
        release_cycle_lock()

        with timer.span("permit"):
            st = self.framework.run_permit(
                state, pod, best, self._on_permit_resolved, now=self.clock()
            )
        if st.code == Code.WAIT:
            return done("waiting", node=best)
        if not st.success:
            self.framework.run_unreserve(state, pod, best)
            return done("unschedulable", node=best, message=st.message)

        return self._bind(state, qpi, pod, best, done)

    def _bind(self, state, qpi, pod, node_name, done) -> ScheduleResult:
        if self._fenced():
            # Leader fencing: abort BEFORE the API write. The reservation
            # rolls back and the pod requeues; the new leader (or this
            # process after re-acquiring) schedules it cleanly.
            if self.metrics is not None:
                self.metrics.fenced_binds.inc()
            self.framework.run_unreserve(state, pod, node_name)
            return done(
                "unschedulable",
                node=node_name,
                message="scheduler fenced (not leader); bind aborted before "
                "the API write",
            )
        if self.commit_fn is not None:
            # Optimistic shard commit, singleton form: validate this
            # cycle's staged claim at the shared accountant BEFORE the
            # bind write — a conflict (another shard's earlier-staged
            # claim owns the chips) costs one unreserve + requeue, never
            # an API write to roll back. The fence check above dominates
            # this commit (yodalint fence-before-write).
            ok, why = self.commit_fn([pod.uid])
            if not ok:
                self.framework.run_unreserve(state, pod, node_name)
                return done(
                    "unschedulable",
                    node=node_name,
                    message=f"shard commit conflict: {why}",
                )
        st = self.framework.run_bind(state, pod, node_name)
        if not st.success:
            self.framework.run_unreserve(state, pod, node_name)
            return done("unschedulable", node=node_name, message=st.message)
        with self._lock:
            self.stats.binds += 1
        if self.metrics is not None:
            self.metrics.binds.inc()
            # SLO engine: close the enqueue->bound admission-wait edge.
            self.metrics.slo.observe_bound(pod, now=self.clock())
        if self.on_bound:
            self.on_bound(pod, node_name)
        # Cluster changed: retry parked pods. Skipped when nothing is
        # parked — on a drained queue the sweep (a full lock + heap walk)
        # would run once per bind to move nothing (ISSUE 10 quick fix,
        # same guard as the event path in standalone.build_stack).
        if self.queue.has_parked():
            self.queue.move_all_to_active()
        return done("bound", node=node_name)

    def _clear_stale_nomination(self, pod: PodSpec, node: str) -> None:
        """On ANY successful bind (direct or permit-released): drop the
        nomination record, and clear status.nominatedNodeName when the pod
        ended up on a DIFFERENT node — a stale nomination reads as phantom
        earmarked capacity."""
        with self._lock:
            nominated = self._nominated.pop(pod.uid, None)
        if (
            nominated is not None
            and nominated != node
            and self.on_nominated is not None
        ):
            self.on_nominated(pod, None)

    def retire(self) -> None:
        """Permanently fence this loop and make its serve thread exit
        (a live shard resize dissolved its lane). Idempotent."""
        self.retired.set()
        self._signal_activity()

    def _signal_activity(self) -> None:
        with self._activity:
            self._activity_seq += 1
            self._activity.notify_all()

    def _on_permit_resolved(self, wp: WaitingPod, status: Status) -> None:
        """Fires when a waiting pod is allowed (bind it) or rejected
        (roll back its reservation and requeue) — on the pipelined
        release, from a bind-executor worker. Flushes any gang rollbacks
        whose release barrier this settle completed, then signals the
        drain condition — AFTER the bind, requeue, and rollbacks landed,
        so a woken ``run_until_idle`` never observes the half-resolved
        state."""
        try:
            self._do_permit_resolved(wp, status)
        finally:
            try:
                self._flush_deferred_rollbacks()
                self._flush_shard_commits()
            finally:
                self._signal_activity()

    def _flush_deferred_rollbacks(self) -> None:
        """Completion-barrier flush: unwind landed binds of gangs whose
        release cohort has FULLY settled after a bind failure (every
        in-flight sibling bound, failed, or was cascade-rejected). Runs
        after every settle, on whichever thread settled last — an unbind
        never races a sibling's bind still mid-air."""
        for p in self.framework.permit_plugins:
            hook = getattr(p, "collect_rollbacks", None)
            if hook is None:
                continue
            for spec, node, why in hook(self.framework):
                self._rollback_bound(spec, node, None, why)

    def _flush_shard_commits(self) -> None:
        """Optimistic shard commit, gang form: validate the staged claims
        of every release cohort whose binds have FULLY landed (the gang
        plugin arms ``collect_commits`` on the last settle). Runs on
        whichever thread settled last — exactly the deferred-rollback
        discipline. A validation conflict (or a fence flip: a new leader
        owns the truth now) makes the shard the LOSER: every landed
        member's bind rolls back through the transactional unbind path
        and the gang requeues whole, counted in
        ``yoda_shard_commit_rollbacks_total``."""
        if self.commit_fn is None:
            return
        for p in self.framework.permit_plugins:
            hook = getattr(p, "collect_commits", None)
            if hook is None:
                continue
            for gang_name, cohort in hook(self.framework):
                fenced = self._fenced()
                if fenced:
                    ok, why = False, (
                        "scheduler fenced (lost leadership) before the "
                        "shard commit; rolling the gang back"
                    )
                else:
                    ok, why = self.commit_fn(
                        [spec.uid for spec, _host in cohort]
                    )
                if ok:
                    continue
                why = f"gang {gang_name}: shard commit conflict: {why}"
                log.warning(
                    "%s — rolling back %d landed member(s)",
                    why, len(cohort),
                )
                if self.metrics is not None:
                    self.metrics.shard_rollbacks.inc(len(cohort))
                    if self.metrics.tracer.enabled:
                        self.metrics.tracer.add(
                            f"gang:{gang_name}", "shard-commit-conflict",
                            attrs={
                                "members": len(cohort),
                                "shard": self.shard or "",
                                "message": why[:200],
                            },
                        )
                    self.metrics.pending.record(
                        gang_name,
                        kind="unschedulable",
                        message=why,
                        shard=self.shard,
                    )
                # EVERY membership drops BEFORE any member requeues: a
                # rolled-back member re-admitted while siblings still
                # read as bound would find a satisfied-looking barrier
                # and release alone — the split gang this path must
                # never produce.
                drop = getattr(p, "drop_membership", None)
                if drop is not None:
                    for spec, _host in cohort:
                        drop(spec)
                for spec, host in cohort:
                    self._rollback_bound(spec, host, None, why)

    def _do_permit_resolved(self, wp: WaitingPod, status: Status) -> None:
        pod = wp.pod
        if self.metrics is not None and wp.parked_at is not None:
            self.metrics.gang_wait.observe(max(self.clock() - wp.parked_at, 0.0))
        if status.success:
            if self._fenced():
                # Leader fencing between permit release and bind: the one
                # window nothing used to check. Abort before the API write;
                # the gang rolls back transactionally below, exactly as a
                # bind failure would.
                if self.metrics is not None:
                    self.metrics.fenced_binds.inc()
                st = Status.unschedulable(
                    "scheduler fenced (lost leadership); bind aborted "
                    "before the API write"
                )
            else:
                st = self.framework.run_bind(wp.state, pod, wp.node_name)
            if st.success:
                if not self._confirm_bound(wp):
                    # The gang began a bind-failure rollback while this
                    # member's bind was in flight (parallel release): the
                    # landed bind is unwound, not celebrated.
                    self._rollback_bound(
                        pod,
                        wp.node_name,
                        wp.state,
                        "gang rolled back while this member's bind was in "
                        "flight",
                    )
                    return
                log.info("bound %s -> %s (permit released)", pod.key, wp.node_name)
                with self._lock:
                    self.stats.binds += 1
                if self.metrics is not None:
                    self.metrics.binds.inc()
                    # SLO engine: permit-released members close their
                    # admission-wait edge here, on whichever thread
                    # settled the bind.
                    self.metrics.slo.observe_bound(pod, now=self.clock())
                    gang = gang_name_of(pod.labels)
                    self.metrics.pending.resolve(pod.key, gang=gang)
                    if self.metrics.tracer.enabled:
                        # Emitted on whichever thread settled the bind —
                        # on the pipelined release that is a bind-executor
                        # worker, so the span's track links the bind back
                        # to the releasing cycle's overlapped turn.
                        self.metrics.tracer.add(
                            subject_of(pod), "bound",
                            attrs={"pod": pod.key, "node": wp.node_name},
                        )
                if self.on_bound:
                    self.on_bound(pod, wp.node_name)
                self._clear_stale_nomination(pod, wp.node_name)
                if self.queue.has_parked():  # see _bind: skip empty sweeps
                    self.queue.move_all_to_active()
                return
            self._handle_bind_failure(wp, st)
            status = st
        log.info(
            "permit rejected %s on %s: %s", pod.key, wp.node_name, status.message
        )
        self.framework.run_unreserve(wp.state, pod, wp.node_name)
        if self.metrics is not None:
            self.metrics.pending.record(
                pod.key,
                kind="permit-rejected",
                message=status.message,
                gang=gang_name_of(pod.labels),
                shard=self.shard,
            )
            if self.metrics.tracer.enabled:
                self.metrics.tracer.add(
                    subject_of(pod), "permit-rejected",
                    attrs={
                        "pod": pod.key,
                        "node": wp.node_name,
                        "message": status.message[:200],
                    },
                )
        self.queue.add_unschedulable(QueuedPodInfo(pod=pod), status.message)
        if self.on_unschedulable:
            self.on_unschedulable(pod, status.message)

    def _confirm_bound(self, wp: WaitingPod) -> bool:
        """Let Permit plugins observe a landed permit-release bind
        (transactional gang bookkeeping). Any False verdict means the bind
        must be rolled back — the gang failed while this bind was in
        flight."""
        keep = True
        for p in self.framework.permit_plugins:
            hook = getattr(p, "on_pod_bound", None)
            if hook is not None and not hook(self.framework, wp):
                keep = False
        return keep

    def _handle_bind_failure(self, wp: WaitingPod, st: Status) -> None:
        """A permit-released bind failed after the binder's transient
        retries (or was fenced): give Permit plugins the chance to make
        the failure TRANSACTIONAL — the gang plugin rejects still-waiting
        members and parks the siblings whose binds already landed for a
        deferred unwind — the actual unbind/unreserve/requeue happens in
        ``_flush_deferred_rollbacks`` once the release cohort has fully
        settled (the completion barrier: an unbind must never race a
        sibling's bind still mid-air on the pipeline). The failing member
        itself goes through the caller's standard rejection path."""
        initiated = False
        for p in self.framework.permit_plugins:
            hook = getattr(p, "on_bind_failed", None)
            if hook is None:
                continue
            if hook(self.framework, wp, st):
                initiated = True
        if initiated and self.metrics is not None:
            self.metrics.recovery_rollbacks.inc()

    def _rollback_bound(
        self, pod: PodSpec, node_name: str, state, why: str
    ) -> None:
        """Undo a LANDED bind (transactional gang rollback): unbind via the
        bind plugins, release the reservation, requeue the pod untouched.
        An unbind the backend cannot perform is logged — the watch stream
        stays the source of truth and the pod re-admits via the gang's
        self-heal on its next cycle."""
        state = state if state is not None else CycleState()
        st = self.framework.run_unbind(state, pod, node_name)
        if not st.success:
            # The pod REMAINS bound on the cluster: keep its reservation
            # (a bound pod holds its chips) and restore its membership so
            # the gang completes AROUND it when the rolled-back siblings
            # requeue — forgetting a still-bound member would wedge the
            # barrier on a ghost until the permit timeout, forever.
            log.error(
                "gang rollback could not unbind %s from %s (%s); pod "
                "remains bound — restoring its gang membership",
                pod.key, node_name, st.message,
            )
            for p in self.framework.permit_plugins:
                hook = getattr(p, "on_unbind_failed", None)
                if hook is not None:
                    hook(self.framework, pod, node_name)
            return
        self.framework.run_unreserve(state, pod, node_name)
        log.warning("rolled back bind of %s on %s: %s", pod.key, node_name, why)
        self.queue.add_unschedulable(QueuedPodInfo(pod=pod), why)
        if self.on_unschedulable:
            self.on_unschedulable(pod, why)

    # --- the loop ---

    def _pop_batch(self, first: QueuedPodInfo) -> list[QueuedPodInfo]:
        """Expand one popped entry into the batch this loop turn schedules:
        a gang member gathers every co-queued gang (cross-gang joint
        pass), any other pod gathers a multi-pod burst."""
        if gang_name_of(first.pod.labels):
            return self._gather_gangs(first)
        return self._pop_burst(first)

    def _gather_gangs(self, first: QueuedPodInfo) -> list[QueuedPodInfo]:
        """Cross-gang joint scheduling pass (the gang-fused pass of ISSUE 1
        extended across gangs, ISSUE 2): pull EVERY co-queued gang member
        — ``first``'s own siblings and members of other gangs — out of the
        queue (still-ticking backoff siblings of the gathered gangs
        included, so a fuse happens one retry earlier), group them by gang
        in priority order, and evaluate all groups in ONE kernel dispatch
        (``Framework.prepare_joint`` -> YodaBatch.prepare_joint_burst).
        Every fully-placed gang then drives reserve -> permit -> bind
        back-to-back in this same loop turn — the Permit barrier resolves
        inside each gang's last member's cycle, and a later gang's members
        are served net of the earlier gangs' claims, so contending gangs
        bind disjoint blocks in one pass instead of serializing dispatches
        through admission-window ordering and cascade/backoff. A gang the
        joint plan cannot fit WHOLE is restored to the queue untouched
        (all-or-nothing: no reservations, no attempt charged); its own
        later pop runs the normal admission path. Priority order is
        preserved across gangs — a lower-priority gang never takes
        capacity a gathered higher-priority gang could use — and the
        inversion window for a higher-priority singleton stays bounded by
        the gathered gangs' total size (the burst-window promise)."""
        first_name = gang_name_of(first.pod.labels)
        groups: "dict[str, list[QueuedPodInfo]]" = {first_name: [first]}
        for q in self.queue.pop_matching(
            lambda p: gang_name_of(p.labels) is not None
        ):
            groups.setdefault(gang_name_of(q.pod.labels), []).append(q)
        # Satellite gather: siblings of the gathered gangs still ticking
        # down backoff fuse now instead of one retry later.
        names = set(groups)
        for q in self.queue.pop_matching(
            lambda p: gang_name_of(p.labels) in names, include_backoff=True
        ):
            groups[gang_name_of(q.pod.labels)].append(q)
        snapshot = self.snapshot_fn()
        if len(groups) == 1:
            batch = groups[first_name]
            if len(batch) > 1:
                log.debug(
                    "gang %s: gathered %d co-queued member(s) for a fused "
                    "pass", first_name, len(batch),
                )
                try:
                    self.framework.prepare_gang(
                        [q.pod for q in batch], snapshot
                    )
                except Exception:
                    # Advisory only: members still schedule back-to-back
                    # below, falling to per-cycle dispatches / the gang plan.
                    log.exception(
                        "gang pre-evaluation failed; scheduling members "
                        "individually"
                    )
            return batch
        ordered = list(groups.items())
        log.debug(
            "joint pass: gathered %d gang(s) (%s) for one dispatch",
            len(ordered), ", ".join(n for n, _ in ordered),
        )
        tracer = self.metrics.tracer if self.metrics is not None else None
        if tracer is not None and not tracer.enabled:
            tracer = None
        tg0 = self.clock()
        verdicts = None
        try:
            verdicts = self.framework.prepare_joint(
                [[q.pod for q in g] for _, g in ordered], snapshot
            )
        except Exception:
            # Advisory only: every gang still schedules back-to-back below
            # through the per-gang machinery (plans / fresh dispatches).
            log.exception(
                "joint gang pre-evaluation failed; scheduling gangs "
                "per-gang"
            )
        if tracer is not None:
            # The gather edge: one span per gathered gang, so each gang's
            # trace shows the joint pass it rode (same wall window).
            names = ",".join(n for n, _ in ordered)
            for name, g in ordered:
                tracer.add(
                    f"gang:{name}", "gather",
                    t0=tg0, t1=self.clock(),
                    attrs={"gangs": names, "members": len(g)},
                )
        if verdicts is None:
            return [q for _, g in ordered for q in g]
        batch: list[QueuedPodInfo] = []
        for i, ((name, g), verdict) in enumerate(zip(ordered, verdicts)):
            if verdict == "park" and i > 0:
                # All-or-nothing without churn: the joint plan proved the
                # gang cannot place whole net of the gangs ahead of it —
                # back to the queue untouched. Never the FIRST group: its
                # pop must always progress (to a bind or an admission
                # park), or a re-pop would loop on the same verdict.
                log.debug(
                    "gang %s: does not fit the joint plan; restored "
                    "untouched (%d member(s))", name, len(g),
                )
                why = (
                    f"gang {name}: joint fit gate — cannot place whole "
                    "net of higher-priority co-queued gangs; restored "
                    "untouched"
                )
                if tracer is not None:
                    tracer.add(
                        f"gang:{name}", "joint-park",
                        attrs={"members": len(g), "behind": i},
                    )
                if self.metrics is not None:
                    for q in g:
                        self.metrics.pending.record(
                            q.pod.key,
                            kind="joint-park",
                            message=why,
                            gang=name,
                        )
                for q in g:
                    self.queue.restore(q)
            else:
                batch.extend(g)
        return batch

    def _pop_burst(self, first: QueuedPodInfo) -> list[QueuedPodInfo]:
        """Pop up to burst_size - 1 further entries and pre-evaluate the
        whole batch in one kernel dispatch. Always returns at least
        ``[first]``; scheduling still happens one full cycle per entry."""
        batch = [first]
        if self.burst_size <= 1 or not self.framework.supports_burst:
            return batch
        while len(batch) < self.burst_size:
            nxt = self.queue.pop(timeout=0.0)
            if nxt is None:
                break
            if gang_name_of(nxt.pod.labels):
                # A gang member must enter through the gang gather, not
                # ride a singleton burst one cycle at a time: un-pop it and
                # stop here — its own pop next loop turn runs the fused
                # gang pass.
                self.queue.restore(nxt)
                break
            batch.append(nxt)
        if len(batch) > 1:
            try:
                self.framework.prepare_burst(
                    [q.pod for q in batch], self.snapshot_fn()
                )
            except Exception:
                # Advisory only: a failed prepare must never lose the
                # popped entries — they schedule individually below.
                log.exception("burst pre-evaluation failed; scheduling individually")
        return batch

    # Ceiling on one event-bound drain wait: signals wake the waiter
    # immediately, so this bounds only the unsignaled cases (fake clocks
    # skewing permit deadlines, resolutions on paths that cannot signal) —
    # 25x coarser than the old fixed 2 ms poll, and never the latency of
    # the common path.
    DRAIN_WAIT_CAP_S = 0.05

    def run_until_idle(self, *, max_wall_s: float = 30.0) -> None:
        """Drain the queue, resolving Permit waits and expirations, until no
        active work remains or ``max_wall_s`` passes. Test/demo driver; the
        production loop is ``serve_forever``.

        Event-bound: while Permit waiters exist the loop sleeps on the
        activity condition — woken by permit resolutions (allow/reject from
        any thread) and queue activity (adds, event reactivations) — with a
        timeout no later than the earliest permit deadline, so expiry still
        fires on time. The old fixed 2 ms settle poll made every gang's
        drain latency a multiple of the poll interval; now it tracks the
        resolving event itself."""
        deadline = time.monotonic() + max_wall_s
        binds_at_drain = -1  # binds count when the queue last went inactive
        while time.monotonic() < deadline:
            with self._activity:
                seq = self._activity_seq  # pre-check capture: a resolution
                # landing between the checks below and the wait bumps the
                # seq and turns the wait into a no-op (no lost wakeup).
            if self._fenced():
                # Leader fencing: park the queue — nothing is popped or
                # bound while fenced. The drain's fixed-point checks below
                # conclude quickly (no binds advance).
                qpi = None
            else:
                qpi = self.queue.pop(timeout=0.0)
            if qpi is not None:
                if self.metrics is not None and self._bind_inflight() > 0:
                    # Pipeline overlap: this cycle's snapshot + dispatch
                    # runs while the previous release's binds are in
                    # flight — the serialization the pipeline removes.
                    self.metrics.overlap_cycles.inc()
                for q in self._pop_batch(qpi):
                    self.schedule_one(q)
                continue
            self.framework.expire_waiting(now=self.clock())
            waiters = self.framework.waiting_pods()
            inflight = self._bind_inflight()
            if waiters or inflight:
                # Pending pipelined binds are active work: their pods left
                # the waitlist when allow() fired, but the bind API write
                # (and any rollback it triggers) has not landed. Each
                # settle signals the activity condition.
                now = self.clock()
                next_deadline = (
                    min(w.deadline for w in waiters)
                    if waiters
                    else now + self.DRAIN_WAIT_CAP_S
                )
                timeout = max(
                    min(
                        next_deadline - now,
                        deadline - time.monotonic(),
                        self.DRAIN_WAIT_CAP_S,
                    ),
                    0.0,
                )
                with self._activity:
                    if self._activity_seq == seq:
                        self._activity.wait(timeout)
                continue
            if self.queue.pending_retry_count() == 0:
                return
            # Only backoff pods remain. Retrying them is useful only if the
            # cluster changed (a bind) since their last attempt; otherwise
            # this is a fixed point — leave them to the event-driven path.
            # Forced: the settlement driver must not conclude "idle" while
            # a CHRONIC pod (beyond the event-retry cutoff) could fit the
            # freed capacity — bounded, since it only fires when binds
            # advanced since the last drain.
            if self.stats.binds == binds_at_drain:
                return
            binds_at_drain = self.stats.binds
            self.queue.move_all_to_active(force=True)

    def serve_forever(self, stop: threading.Event, *, poll_s: float = 0.5) -> None:
        """The production loop: block on the queue, schedule the popped
        batch, then sweep permit expirations ONCE per iteration (the sweep
        ran twice per iteration before — once after the pop and once per
        scheduled entry — pure overhead, since expiry resolution only needs
        to be poll_s-grained and each sweep walks the whole waitlist).

        With the bind pipeline wired, a gang release returns before its
        binds land: the next iteration's pop -> snapshot -> kernel dispatch
        OVERLAPS the in-flight bind I/O (yoda_overlap_cycles_total counts
        these turns). Correctness needs no extra synchronization — the
        in-flight members' reservations stay charged to the accountant, so
        the overlapped evaluation already sees their capacity as consumed."""
        while not stop.is_set():
            if self.retired.is_set():
                # Dissolved by a live shard resize: the thread exits; the
                # resizer already rerouted this lane's queue.
                return
            if self._fenced():
                # Leader fencing: park the queue until leadership returns.
                # Permit expirations still sweep so parked gangs cannot
                # hold reservations past their deadlines while fenced.
                self.framework.expire_waiting(now=self.clock())
                stop.wait(poll_s)
                continue
            if self.on_serve_start is not None:
                # Warm-start resync: runs exactly once, after the fence
                # first admits leadership and before the first pop — no
                # bind can precede it (the /readyz contract).
                hook, self.on_serve_start = self.on_serve_start, None
                hook()
            qpi = self.queue.pop(timeout=poll_s)
            if qpi is not None:
                if self.metrics is not None and self._bind_inflight() > 0:
                    self.metrics.overlap_cycles.inc()
                for q in self._pop_batch(qpi):
                    self.schedule_one(q)
            self.framework.expire_waiting(now=self.clock())


def _normalize(scores: dict[str, int]) -> dict[str, int]:
    """Min-max rescale to [0, MAX_NODE_SCORE] — parity with the reference's
    NormalizeScore including the all-equal guard (reference
    pkg/yoda/scheduler.go:136-144)."""
    if not scores:
        return {}
    lowest, highest = min(scores.values()), max(scores.values())
    if highest == lowest:
        lowest -= 1
    return {
        n: (s - lowest) * MAX_NODE_SCORE // (highest - lowest) for n, s in scores.items()
    }
