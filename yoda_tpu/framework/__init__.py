"""Scheduling framework: extension points, cycle state, queue, and runtime.

The reference relies on the upstream kube-scheduler scheduling framework
(k8s 1.17 ``framework/v1alpha1``) which it gets wholesale through
``app.NewSchedulerCommand`` (reference pkg/register/register.go:9-13) — the
queues, cache, cycle driver, and binding all live upstream. This package is
the from-scratch equivalent of that machinery, modeled on the MODERN (v1)
framework semantics, because every hook the reference uses has moved since
v1alpha1: the reference's "PostFilter" (a pre-scoring data-collection hook,
reference pkg/yoda/scheduler.go:85) is today's **PreScore**, and today's
PostFilter means preemption (SURVEY.md §3.2 note).

Extension-point order for one pod's scheduling cycle:

    QueueSort (queue ordering)
    -> PreFilter -> Filter (per node) -> [PostFilter on failure: preemption]
    -> PreScore -> Score (per node) -> NormalizeScore
    -> Reserve [-> Unreserve on any later failure]
    -> Permit (may Wait: gang scheduling)
    -> Bind
"""

from yoda_tpu.framework.interfaces import (
    Code,
    Status,
    NodeInfo,
    Snapshot,
    QueueSortPlugin,
    PreFilterPlugin,
    FilterPlugin,
    PostFilterPlugin,
    PreScorePlugin,
    ScorePlugin,
    BatchFilterScorePlugin,
    ReservePlugin,
    PermitPlugin,
    BindPlugin,
    MAX_NODE_SCORE,
)
from yoda_tpu.framework.cyclestate import CycleState, StateData
from yoda_tpu.framework.queue import SchedulingQueue, QueuedPodInfo
from yoda_tpu.framework.runtime import BindExecutor, Framework, WaitingPod
from yoda_tpu.framework.scheduler import ScheduleResult, Scheduler, SchedulerStats

__all__ = [
    "Code",
    "Status",
    "NodeInfo",
    "Snapshot",
    "QueueSortPlugin",
    "PreFilterPlugin",
    "FilterPlugin",
    "PostFilterPlugin",
    "PreScorePlugin",
    "ScorePlugin",
    "BatchFilterScorePlugin",
    "ReservePlugin",
    "PermitPlugin",
    "BindPlugin",
    "MAX_NODE_SCORE",
    "CycleState",
    "StateData",
    "SchedulingQueue",
    "QueuedPodInfo",
    "BindExecutor",
    "Framework",
    "WaitingPod",
    "Scheduler",
    "ScheduleResult",
    "SchedulerStats",
]
