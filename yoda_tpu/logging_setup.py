"""Verbosity-leveled operational logging — the klog ``--v`` analog.

The reference follows every scheduling decision from its stdout: per-pod
filter entry, collection, and final scores log at ``klog.V(3)`` (reference
pkg/yoda/scheduler.go:58,67,143) and the Deployment runs ``--v=3``
(reference deploy/yoda-scheduler.yaml:62). This module maps that model onto
stdlib ``logging`` for the whole ``yoda_tpu`` logger tree:

    --v=0   WARNING  (failures and anomalies only)
    --v=1   INFO     (one line per scheduling outcome, gang/lease
                      transitions, preemption victims)
    --v>=3  DEBUG    (per-node filter rejections and score detail — the
                      reference's V(3) decision logs)

Loggers stay cheap when disabled: decision-detail call sites guard with
``isEnabledFor`` before building per-node strings.
"""

from __future__ import annotations

import logging
import sys

ROOT = "yoda_tpu"


def level_for(verbosity: int) -> int:
    if verbosity >= 3:
        return logging.DEBUG
    if verbosity >= 1:
        return logging.INFO
    return logging.WARNING


def configure_logging(verbosity: int = 0, stream=None) -> None:
    """Configure the ``yoda_tpu`` logger tree for a CLI process. Idempotent:
    re-running adjusts the level without stacking handlers (tests and
    embedded callers may call main() repeatedly)."""
    root = logging.getLogger(ROOT)
    root.setLevel(level_for(verbosity))
    if not any(isinstance(h, _YodaHandler) for h in root.handlers):
        handler = _YodaHandler(stream or sys.stderr)
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s.%(msecs)03d %(levelname).1s %(name)s] %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        root.addHandler(handler)


class _YodaHandler(logging.StreamHandler):
    """Marker subclass so configure_logging can recognize its own handler."""
