"""Sliding-window SLI engine + declarative SLO evaluation.

PR 9 gave the system per-lifecycle traces and why-pending; PR 10/11 gave
it fairness and repair. What was still missing is the AGGREGATE answer to
"are tenants getting the service we promised?" — Pollux (PAPERS.md) makes
fleet-wide goodput the metric co-adaptive allocation optimizes, and
Gandiva's introspection loop reads continuously measured per-job signals.
This module is that observability substrate:

- **SLIs** are computed from events the scheduler already emits, at the
  cost of one lock + a deque append per event (the serve path never
  evaluates anything):

  * *admission wait* — the enqueue→bound edge per pod, per tenant
    (``observe_enqueue`` fired by the informer's pending hook,
    ``observe_bound`` by both bind completion paths), windowed quantiles;
  * *starvation windows* — a tenant with queued work and ZERO admissions
    across a full ``starvation_window_s`` has been starved for that
    window (the DRF queue's ``tenant_wait_stats`` feeds the pending side);
  * *preemption / repair rates* — timestamps from the preemption plugin,
    the rebalancer's priority preemptions, and nodehealth gang repairs;
  * *chip-utilization goodput* — the accountant-backed bin-packing
    efficiency gauge, sampled at evaluation time.

- **SLO targets** are declarative (:class:`SloTargets`, config
  ``slo_targets``, shipped in the deploy ConfigMap) and evaluated with
  the classic multi-window burn-rate discipline: the admission SLI's
  error budget (fraction of admissions slower than the target p99,
  against an ``admission_wait_slo`` goal) is burned over a FAST and a
  SLOW window; an alert fires only when BOTH windows burn past
  ``burn_threshold`` — fast-only spikes are noise, slow-only burn is
  already-old news.

One engine is shared across profile stacks and federation members
(carried on :class:`~yoda_tpu.observability.SchedulingMetrics` exactly
like the tracer and the why-pending index), so per-tenant SLIs aggregate
across every serve loop that can bind the tenant's pods. Served at
``GET /debug/slo``, by ``yoda-tpu-scheduler slo``, and as the
``yoda_slo_*`` Prometheus series.

Everything is stdlib-only; evaluation is on-demand (scrape / HTTP / CLI /
bench) and cached for ``cache_ttl_s`` on the engine clock so one scrape's
eight series see one consistent evaluation.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, fields
from typing import Callable

from yoda_tpu.framework.tenancy import tenant_of as _tenant_of

# Bound on distinct pod keys awaiting their bound edge: an LRU so a
# million-pod churn stream of never-bound foreign/parked pods cannot grow
# the join map without bound (same discipline as the tracer's subjects).
MAX_ENQUEUED = 65536

# Per-tenant admission-sample ring bound (exact quantiles up to this many
# samples inside the slow window).
MAX_SAMPLES = 4096

# Bound on event-timestamp rings (preemptions / repairs / goodput).
MAX_EVENTS = 8192


def _quantile(sorted_vals: "list[float]", q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(len(sorted_vals) * q), len(sorted_vals) - 1)]


@dataclass(frozen=True)
class SloTargets:
    """Declarative per-tenant service-level objectives (config
    ``slo_targets``). 0 disables the corresponding target entirely —
    the SLI is still computed and exported, just never alerted on."""

    # Admission wait: p99 of enqueue->bound per tenant must stay under
    # this many seconds; the burn-rate SLI counts an admission slower
    # than this as error-budget spend against the admission_wait_slo goal.
    admission_wait_p99_s: float = 60.0
    # Fraction of admissions that must land under the target (the error
    # budget is 1 - this; burn rate = bad fraction / budget).
    admission_wait_slo: float = 0.99
    # Tolerated starved windows per tenant (a window is
    # slo_starvation_window_s of queued work with zero admissions).
    # The bench matrix asserts 0.
    starved_windows: int = 0
    # Fleet preemption / repair rates (per minute over the fast window)
    # above these alert; 0 = no target.
    preemption_rate_per_min: float = 0.0
    repair_rate_per_min: float = 0.0
    # Minimum chip-utilization goodput (bin-packing efficiency in [0,1])
    # the fleet must hold while loaded; 0 = no target.
    goodput_min: float = 0.0

    @classmethod
    def from_dict(cls, d: dict) -> "SloTargets":
        known = {f.name for f in fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown slo_targets keys: {sorted(unknown)}")
        bad = {
            k: v
            for k, v in d.items()
            if isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0
        }
        if bad:
            raise ValueError(
                f"slo_targets values must be non-negative numbers: {bad}"
            )
        cfg = cls(**d)
        if not 0 < cfg.admission_wait_slo < 1:
            raise ValueError(
                "slo_targets.admission_wait_slo must be in (0, 1), got "
                f"{cfg.admission_wait_slo!r}"
            )
        if cfg.goodput_min > 1:
            raise ValueError(
                "slo_targets.goodput_min must be in [0, 1], got "
                f"{cfg.goodput_min!r}"
            )
        if int(cfg.starved_windows) != cfg.starved_windows:
            raise ValueError(
                "slo_targets.starved_windows must be an integer, got "
                f"{cfg.starved_windows!r}"
            )
        return cfg

    def to_dict(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}


class SloEngine:
    """Event-fed SLI accumulators + on-demand SLO evaluation.

    Record paths (``observe_*``) are serve-path-cheap: one attribute read
    when disabled, one lock + a dict/deque op when enabled — the < 2%
    pods/s overhead contract the bench pair proves. ``evaluate`` walks
    the windows, updates starvation accounting, and returns the full
    per-tenant + fleet summary; it runs only on scrape/HTTP/CLI/bench
    demand, never on a serve loop."""

    def __init__(
        self,
        *,
        targets: "SloTargets | None" = None,
        enabled: bool = True,
        starvation_window_s: float = 60.0,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        burn_threshold: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        cache_ttl_s: float = 1.0,
    ) -> None:
        self.targets = targets if targets is not None else SloTargets()
        self.enabled = bool(enabled)
        self.starvation_window_s = max(float(starvation_window_s), 1e-9)
        self.fast_window_s = max(float(fast_window_s), 1e-9)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.burn_threshold = float(burn_threshold)
        self.clock = clock
        self.cache_ttl_s = max(float(cache_ttl_s), 0.0)
        # Chip-utilization goodput source (standalone wires the
        # accountant-backed bin-packing-efficiency gauge); sampled at
        # evaluation time only.
        self.goodput_fn: "Callable[[], float] | None" = None
        self.evaluations = 0
        self._lock = threading.Lock()
        # pod key -> (tenant, enqueue time): the enqueue->bound join.
        self._enqueued: "OrderedDict[str, tuple[str, float]]" = OrderedDict()
        # tenant -> ring of (bound time, wait seconds).
        self._admissions: "dict[str, deque[tuple[float, float]]]" = {}
        self._admission_total: "dict[str, int]" = {}
        self._last_admission: "dict[str, float]" = {}
        self._preemptions: "deque[float]" = deque(maxlen=MAX_EVENTS)
        self._repairs: "deque[float]" = deque(maxlen=MAX_EVENTS)
        # tenant -> cumulative starved windows / the window-accounting mark.
        self._starved: "dict[str, int]" = {}
        self._starve_mark: "dict[str, float]" = {}
        # SchedulingQueue providers of tenant_wait_stats() — one per stack
        # sharing this engine (profiles, federation members).
        self._queues: list = []
        self._cache: "dict | None" = None
        self._cache_at = float("-inf")

    # --- wiring (standalone.build_stack) ---

    def add_queue(self, queue) -> None:
        """Register a stack's scheduling queue as a pending-work source
        (``tenant_wait_stats``). Idempotent per queue object."""
        with self._lock:
            if queue not in self._queues:
                self._queues.append(queue)

    def remove_queue(self, queue) -> None:
        """Retire a queue (a shard lane dissolved by a live resize): its
        pending work no longer feeds the starvation SLIs."""
        with self._lock:
            if queue in self._queues:
                self._queues.remove(queue)

    # --- the record paths (serve-path cheap) ---

    def observe_enqueue(self, pod, *, now: "float | None" = None) -> None:
        """A pod became pending (the informer's enqueue edge). First
        sight wins: requeues and watch re-deliveries do not reset the
        admission clock — the SLI is time-to-FIRST-bind."""
        if not self.enabled:
            return
        now = self.clock() if now is None else now
        tenant = _tenant_of(pod)
        key = pod.key
        with self._lock:
            if key in self._enqueued:
                return
            self._enqueued[key] = (tenant, now)
            while len(self._enqueued) > MAX_ENQUEUED:
                self._enqueued.popitem(last=False)

    def observe_bound(self, pod, *, now: "float | None" = None) -> None:
        """The pod bound: close its enqueue->bound edge. Pods with no
        recorded enqueue (adopted at resync, LRU-evicted) are skipped —
        a fabricated zero wait would flatter the quantiles."""
        if not self.enabled:
            return
        now = self.clock() if now is None else now
        with self._lock:
            ent = self._enqueued.pop(pod.key, None)
            if ent is None:
                return
            tenant, t0 = ent
            ring = self._admissions.get(tenant)
            if ring is None:
                ring = self._admissions[tenant] = deque(maxlen=MAX_SAMPLES)
            ring.append((now, max(now - t0, 0.0)))
            self._admission_total[tenant] = (
                self._admission_total.get(tenant, 0) + 1
            )
            self._last_admission[tenant] = now

    def observe_retired(self, pod) -> None:
        """The pod left the system without binding (deleted while
        pending): drop its enqueue record so the join map reflects live
        pods only. No SLI sample — a cancelled ask is not an admission."""
        if not self.enabled:
            return
        with self._lock:
            self._enqueued.pop(pod.key, None)

    def observe_preemption(
        self, n: int = 1, *, now: "float | None" = None
    ) -> None:
        """``n`` pods were preempted (PostFilter eviction or rebalancer
        priority preemption)."""
        if not self.enabled or n <= 0:
            return
        now = self.clock() if now is None else now
        with self._lock:
            for _ in range(min(int(n), MAX_EVENTS)):
                self._preemptions.append(now)

    def observe_repair(self, *, now: "float | None" = None) -> None:
        """One gang-whole repair landed (nodehealth patch/shrink/requeue
        or a rebalancer drain migration)."""
        if not self.enabled:
            return
        now = self.clock() if now is None else now
        with self._lock:
            self._repairs.append(now)

    # --- evaluation ---

    def _rate_per_min(
        self, ring: "deque[float]", now: float
    ) -> float:
        cutoff = now - self.fast_window_s
        n = sum(1 for t in ring if t > cutoff)
        return n / (self.fast_window_s / 60.0)

    def _burn(
        self, samples: "list[tuple[float, float]]", now: float, window: float
    ) -> "tuple[float, int]":
        """(burn rate, samples in window) for the admission SLI over one
        window: bad fraction / error budget."""
        target = self.targets.admission_wait_p99_s
        budget = 1.0 - self.targets.admission_wait_slo
        cutoff = now - window
        n = bad = 0
        for t, wait in samples:
            if t <= cutoff:
                continue
            n += 1
            if target > 0 and wait > target:
                bad += 1
        if n == 0 or target <= 0 or budget <= 0:
            return 0.0, n
        return (bad / n) / budget, n

    def evaluate(self, now: "float | None" = None) -> dict:
        """Compute every SLI over the sliding windows, advance the
        starvation-window accounting, and judge the targets. Returns the
        summary dict ``/debug/slo`` serves. Deterministic for a given
        event history and ``now`` (the seeded-replay contract)."""
        now = self.clock() if now is None else now
        # Goodput is sampled OUTSIDE the engine lock: the hook reads the
        # informer snapshot + accountant, each with locks of their own.
        goodput = None
        if self.enabled and self.goodput_fn is not None:
            try:
                goodput = float(self.goodput_fn())
            except Exception:  # noqa: BLE001 — a sick gauge must not kill /debug/slo
                goodput = None
        with self._lock:
            self.evaluations += 1
            if not self.enabled:
                out = {
                    "now": round(now, 6),
                    "enabled": False,
                    "targets": self.targets.to_dict(),
                    "tenants": {},
                    "fleet": {},
                    "alerts": [],
                }
                self._cache, self._cache_at = out, now
                return out
            horizon = now - self.slow_window_s
            for tenant, ring in list(self._admissions.items()):
                while ring and ring[0][0] <= horizon:
                    ring.popleft()
                if not ring:
                    del self._admissions[tenant]
            while self._preemptions and self._preemptions[0] <= horizon:
                self._preemptions.popleft()
            while self._repairs and self._repairs[0] <= horizon:
                self._repairs.popleft()

            # Pending work, merged across every registered queue.
            pending: "dict[str, tuple[int, float | None]]" = {}
            for q in self._queues:
                try:
                    stats = q.tenant_wait_stats()
                except Exception:  # noqa: BLE001 — one sick queue must not kill SLIs
                    continue
                for tenant, (depth, oldest) in stats.items():
                    pn, po = pending.get(tenant, (0, None))
                    if oldest is not None and (po is None or oldest < po):
                        po = oldest
                    pending[tenant] = (pn + depth, po)

            # Starvation-window accounting: a tenant with queued work and
            # no admission across a whole window is starved for it. The
            # per-tenant mark makes repeated evaluations idempotent.
            W = self.starvation_window_s
            for tenant, (depth, oldest) in pending.items():
                if depth <= 0 or oldest is None:
                    continue
                start = max(self._last_admission.get(tenant, oldest), oldest)
                mark = max(self._starve_mark.get(tenant, start), start)
                windows = int((now - mark) // W)
                if windows > 0:
                    self._starved[tenant] = (
                        self._starved.get(tenant, 0) + windows
                    )
                    mark += windows * W
                self._starve_mark[tenant] = mark
            for tenant in list(self._starve_mark):
                got = pending.get(tenant)
                if got is None or got[0] <= 0:
                    # Queue drained: the starvation clock restarts at the
                    # next enqueue, not from stale history.
                    del self._starve_mark[tenant]

            tenants = sorted(
                set(self._admissions)
                | set(pending)
                | set(self._starved)
                | set(self._admission_total)
            )
            per_tenant: "dict[str, dict]" = {}
            alerts: "list[dict]" = []
            all_samples: "list[tuple[float, float]]" = []
            t_target = self.targets.admission_wait_p99_s
            for tenant in tenants:
                samples = list(self._admissions.get(tenant, ()))
                all_samples.extend(samples)
                waits = sorted(w for _, w in samples)
                depth, oldest = pending.get(tenant, (0, None))
                burn_fast, n_fast = self._burn(
                    samples, now, self.fast_window_s
                )
                burn_slow, n_slow = self._burn(
                    samples, now, self.slow_window_s
                )
                starved = self._starved.get(tenant, 0)
                burning = (
                    t_target > 0
                    and n_fast > 0
                    and burn_fast >= self.burn_threshold
                    and burn_slow >= self.burn_threshold
                )
                row = {
                    "admission_wait_p99_s": round(_quantile(waits, 0.99), 6),
                    "admission_wait_p50_s": round(_quantile(waits, 0.50), 6),
                    "admissions_window": len(samples),
                    "admissions_total": self._admission_total.get(tenant, 0),
                    "pending": depth,
                    "oldest_wait_s": (
                        round(max(now - oldest, 0.0), 6)
                        if (depth > 0 and oldest is not None)
                        else 0.0
                    ),
                    "starved_windows": starved,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "alert": "burning" if burning else "ok",
                }
                per_tenant[tenant] = row
                if burning:
                    alerts.append(
                        {
                            "sli": "admission_wait",
                            "tenant": tenant,
                            "burn_fast": row["burn_fast"],
                            "burn_slow": row["burn_slow"],
                        }
                    )
                if starved > self.targets.starved_windows:
                    alerts.append(
                        {
                            "sli": "starvation",
                            "tenant": tenant,
                            "starved_windows": starved,
                        }
                    )

            fleet_waits = sorted(w for _, w in all_samples)
            preempt_rate = self._rate_per_min(self._preemptions, now)
            repair_rate = self._rate_per_min(self._repairs, now)
            fleet_burn_fast, _ = self._burn(
                all_samples, now, self.fast_window_s
            )
            fleet_burn_slow, _ = self._burn(
                all_samples, now, self.slow_window_s
            )
            fleet = {
                "admission_wait_p99_s": round(
                    _quantile(fleet_waits, 0.99), 6
                ),
                "admissions_window": len(all_samples),
                "starved_windows": sum(self._starved.values()),
                "preemption_rate_per_min": round(preempt_rate, 4),
                "repair_rate_per_min": round(repair_rate, 4),
                "goodput": round(goodput, 6) if goodput is not None else None,
                "burn_fast": round(fleet_burn_fast, 4),
                "burn_slow": round(fleet_burn_slow, 4),
            }
            t = self.targets
            if (
                t.preemption_rate_per_min > 0
                and preempt_rate > t.preemption_rate_per_min
            ):
                alerts.append(
                    {
                        "sli": "preemption_rate",
                        "tenant": "",
                        "rate_per_min": fleet["preemption_rate_per_min"],
                    }
                )
            if t.repair_rate_per_min > 0 and repair_rate > t.repair_rate_per_min:
                alerts.append(
                    {
                        "sli": "repair_rate",
                        "tenant": "",
                        "rate_per_min": fleet["repair_rate_per_min"],
                    }
                )
            if (
                t.goodput_min > 0
                and goodput is not None
                and goodput < t.goodput_min
                and (all_samples or any(d for d, _ in pending.values()))
            ):
                # Only judged while the fleet sees traffic: an idle fleet's
                # 0.0 efficiency is not an SLO violation.
                alerts.append(
                    {
                        "sli": "goodput",
                        "tenant": "",
                        "goodput": fleet["goodput"],
                    }
                )
            out = {
                "now": round(now, 6),
                "enabled": True,
                "targets": t.to_dict(),
                "windows": {
                    "starvation_s": self.starvation_window_s,
                    "burn_fast_s": self.fast_window_s,
                    "burn_slow_s": self.slow_window_s,
                    "burn_threshold": self.burn_threshold,
                },
                "tenants": per_tenant,
                "fleet": fleet,
                "alerts": alerts,
            }
            self._cache, self._cache_at = out, now
            return out

    def summary(self) -> dict:
        """A FRESH evaluation (the /debug/slo and CLI surface)."""
        return self.evaluate()

    def _cached(self) -> dict:
        """At-most-once-per-``cache_ttl_s`` evaluation: one scrape's
        eight ``yoda_slo_*`` series read one consistent summary instead
        of re-walking the windows per series."""
        now = self.clock()
        with self._lock:
            cache, at = self._cache, self._cache_at
        if cache is not None and now - at < self.cache_ttl_s:
            return cache
        return self.evaluate(now)

    def burn_snapshot(self) -> "tuple[float, float]":
        """(fast, slow) fleet burn rates from the cached evaluation —
        the overload monitor's burn-pressure signal (cheap: at most one
        window walk per cache_ttl_s across every consumer)."""
        fleet = self._cached().get("fleet", {})
        return (
            float(fleet.get("burn_fast", 0.0) or 0.0),
            float(fleet.get("burn_slow", 0.0) or 0.0),
        )

    # --- Prometheus views (lazy collect_fns, observability.py) ---

    def prom_admission_p99(self) -> dict:
        return {
            (("tenant", t),): row["admission_wait_p99_s"]
            for t, row in self._cached()["tenants"].items()
        }

    def prom_starved_windows(self) -> dict:
        return {
            (("tenant", t),): float(row["starved_windows"])
            for t, row in self._cached()["tenants"].items()
        }

    def prom_burn(self) -> dict:
        fleet = self._cached()["fleet"]
        return {
            (("window", "fast"),): fleet.get("burn_fast", 0.0),
            (("window", "slow"),): fleet.get("burn_slow", 0.0),
        }

    def prom_preemption_rate(self) -> float:
        return self._cached()["fleet"].get("preemption_rate_per_min", 0.0)

    def prom_repair_rate(self) -> float:
        return self._cached()["fleet"].get("repair_rate_per_min", 0.0)

    def prom_goodput(self) -> float:
        got = self._cached()["fleet"].get("goodput")
        return got if got is not None else 0.0

    def prom_alerts_firing(self) -> float:
        return float(len(self._cached()["alerts"]))
