"""Fleet SLO engine (ISSUE 12): sliding-window SLIs computed from the
events the scheduler already emits, evaluated against declarative targets
with multi-window burn-rate alerting. See yoda_tpu/slo/engine.py."""

from yoda_tpu.slo.engine import SloEngine, SloTargets

__all__ = ["SloEngine", "SloTargets"]
