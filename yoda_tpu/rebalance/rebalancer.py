"""The goodput-driven rebalancer: background ICI defragmentation, priority
preemption, and elastic gang resize.

Everything before this subsystem placed once: after a gang bound, the
fleet only got worse — churn fragmented the ICI blocks
(rebalance/score.py quantifies the decay) and a parked high-priority gang
could wait forever behind low-priority singletons even when unbinding a
handful of pods would admit it whole. Pollux (OSDI '21) shows continuous
re-allocation toward aggregate goodput beats static placement; Gandiva
(OSDI '18) hides migration cost behind job boundaries. This module is
that control loop for this scheduler, one :class:`Rebalancer` per stack
(``standalone.build_stack``), run on ONE background thread
(:meth:`run_forever`, leadership-gated like the drift reconciler) — it
never blocks a scheduling cycle.

Each pass (:meth:`run_once`), in order:

1. **Priority preemption.** For every gang parked WHOLE in the queue that
   already failed a local cycle (``SchedulingQueue.pending_gangs`` — the
   federation spillover's candidate test), highest priority first: if the
   gang cannot fit the current occupancy model, select the cheapest set of
   strictly-lower-priority victim units — singletons, whole bound gangs
   (never a slice of one), or the elastic-shrink surplus of a bound
   elastic gang — that admits it, minimizing evicted priority-weighted
   work (``(max(priority,0)+1) x chips`` per pod). Victims are preempted
   through the **unbind path** (``Scheduler._rollback_bound``: unbind,
   unreserve, requeue), not deleted: a preempted gang re-queues whole and
   re-places when capacity returns.
2. **Elastic resize.** Gangs declaring ``tpu/min-members``/
   ``tpu/max-members`` grow up into free capacity (parked surplus members
   admitted by raising the effective size) — the shrink direction runs as
   the cheapest preemption unit above, and never below ``min-members``
   (``GangPlugin.set_effective_size`` clamps).
3. **Repack.** Bound topology gangs whose move to a planner-chosen tight
   block improves the fragmentation score by at least ``min_gain`` are
   migrated with the transactional move primitive: take the gang's queue
   entries (``take_gang`` — the serve loop provably cannot touch the gang
   mid-move, the federation migration discipline), drop memberships,
   unbind every member through the standard rollback path (fanned out on
   the bind executor so the unbind I/O overlaps the serve loop), install
   the target plan (``GangPlugin.install_plan``), and re-add the entries.
   The requeued members re-admit onto the installed block through the
   NORMAL reserve -> permit -> bind cycle — no capacity is ever claimed
   outside standard admission, which is what makes "no oversubscription
   during a move" structural.

Crash safety: a process death mid-move leaves at most a partially-bound
gang — exactly the state the PR 5 warm-start resync classifies
adopt-or-rolled-back-whole, so a half-moved gang can never stay split. A
per-pass simulated occupancy ledger (:class:`FleetOccupancy` clone) keeps
the pass's own promises consistent — two moves (or a move and a
preemption) cannot be promised the same free block — and because every
real claim still goes through admission against the live accountant, the
pass cannot race the joint dispatch either.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from yoda_tpu.api.requests import LabelParseError, gang_name_of, pod_request
from yoda_tpu.api.types import PodSpec, pod_admits_on
from yoda_tpu.framework.queue import QueuedPodInfo
from yoda_tpu.plugins.yoda.sort import pod_priority
from yoda_tpu.plugins.yoda.topology import plan_multislice_placement
from yoda_tpu.rebalance.score import FleetOccupancy

log = logging.getLogger("yoda_tpu.rebalance")


def priority_weight(pod: PodSpec) -> int:
    """Evicted-work weight of one victim pod: priorities can be negative,
    so the weight floor is chips alone — zero- and negative-priority work
    still counts as work."""
    try:
        chips = pod_request(pod).effective_chips
    except LabelParseError:
        chips = 1
    return (max(pod_priority(pod), 0) + 1) * chips


@dataclass
class _VictimUnit:
    """One atomic preemption choice: a singleton, a WHOLE bound gang, or
    the elastic-shrink surplus of a bound elastic gang. Gangs are never
    preempted partially (they must requeue whole); the shrink unit is the
    sanctioned partial form — the gang keeps running at ``keep``."""

    members: "list[tuple[PodSpec, str]]"   # (pod, bound host)
    max_priority: int
    weight: int
    gang: str | None = None
    keep: int | None = None                # shrink unit: new effective size

    @property
    def kind(self) -> str:
        if self.gang is None:
            return "pod"
        return "shrink" if self.keep is not None else "gang"


@dataclass
class RebalanceReport:
    """What one pass measured and did (tests, logs)."""

    fragmentation_before: float = 0.0
    fragmentation_after: float = 0.0
    # Gangs/singletons migrated off DRAINING nodes (the node health
    # monitor's graceful-drain integration).
    drained: list[str] = field(default_factory=list)
    moves: list[str] = field(default_factory=list)
    aborted_moves: list[str] = field(default_factory=list)
    preempted: list[str] = field(default_factory=list)      # victim pod keys
    admitted_gangs: list[str] = field(default_factory=list)
    resizes: dict[str, tuple[int, int]] = field(default_factory=dict)
    preempted_weight: int = 0


class Rebalancer:
    """One per stack; all I/O and planning on the caller's (background)
    thread — the serve loop only ever feels the standard queue/unbind
    effects."""

    def __init__(
        self,
        *,
        cluster,
        informer,
        accountant,
        gang,
        framework,
        queue,
        scheduler,
        metrics=None,
        bind_executor=None,
        clock: Callable[[], float] = time.monotonic,
        min_gain: float = 0.05,
        max_moves: int = 1,
        preemption: bool = True,
        elastic: bool = True,
        max_victims: int = 8,
        gate_fn: "Callable[[], bool] | None" = None,
        draining_fn: "Callable[[], frozenset] | None" = None,
    ) -> None:
        self.cluster = cluster
        self.informer = informer
        self.accountant = accountant
        self.gang = gang
        self.framework = framework
        self.queue = queue
        self.scheduler = scheduler
        self.metrics = metrics
        self.bind_executor = bind_executor
        self.clock = clock
        self.min_gain = min_gain
        self.max_moves = max_moves
        self.enable_preemption = preemption
        self.enable_elastic = elastic
        self.max_victims = max_victims
        # run_forever's per-tick admission gate (cli wires leadership +
        # resynced); run_once ignores it — direct drivers decide themselves.
        self.gate_fn = gate_fn
        # Speculative placement cache (framework/speculation.py), wired by
        # the stack builder: this thread's idle capacity between passes
        # drives its producer tick — settable post-construction like
        # gate_fn.
        self.speculator = None
        # Node health integration (yoda_tpu/nodehealth): nodes under a
        # graceful drain — the pass migrates bound gangs off them
        # PROACTIVELY (rolling-upgrade support), before the monitor's
        # deadline forces a DOWN-style evacuation.
        self.draining_fn = draining_fn
        self.scheduler_name = informer.scheduler_name
        self._lock = threading.Lock()
        self.passes = 0

    # --- the pass ---

    def run_once(self) -> RebalanceReport:
        report = RebalanceReport()
        snapshot = self.informer.snapshot()
        occ = FleetOccupancy.from_snapshot(
            snapshot, self.accountant.chips_by_node()
        )
        report.fragmentation_before = occ.score()
        if self.metrics is not None:
            self.metrics.fragmentation.set(report.fragmentation_before)
        self._drain_pass(snapshot, occ, report)
        if self.enable_preemption:
            self._preempt_pass(snapshot, occ, report)
        if self.enable_elastic:
            self._resize_up_pass(snapshot, occ, report)
        self._repack_pass(snapshot, occ, report)
        # Re-score from live state so the gauge reflects what the pass
        # actually changed (unbinds landed synchronously above).
        report.fragmentation_after = FleetOccupancy.from_snapshot(
            self.informer.snapshot(), self.accountant.chips_by_node()
        ).score()
        if self.metrics is not None:
            self.metrics.fragmentation.set(report.fragmentation_after)
        with self._lock:
            self.passes += 1
        if (
            report.moves
            or report.aborted_moves
            or report.preempted
            or report.resizes
        ):
            log.info(
                "rebalance pass: %d move(s) (%d aborted), %d pod(s) "
                "preempted for %s, %d resize(s), fragmentation %.3f -> %.3f",
                len(report.moves), len(report.aborted_moves),
                len(report.preempted), report.admitted_gangs or "-",
                len(report.resizes), report.fragmentation_before,
                report.fragmentation_after,
            )
        return report

    def run_forever(
        self,
        stop: threading.Event,
        *,
        period_s: float = 30.0,
        spec_period_s: float = 1.0,
    ) -> None:
        """The background loop (cli.py puts this on a thread once
        leadership is held). Gate checked per tick; exceptions logged,
        never fatal — a rebalancer crash must not take the scheduler.

        When a speculator is wired, this thread's idle capacity between
        rebalance passes drives the speculative placement cache on the
        much faster ``spec_period_s`` sub-tick — plans stale at
        fleet-churn speed, so a 30 s refresh would never hit. Both ticks
        share the leadership gate: followers neither rebalance nor
        speculate. Without a speculator the loop is byte-for-byte the old
        one-pass-per-period behavior."""
        ticks = 0
        while not stop.is_set():
            spec = self.speculator
            ratio = (
                max(1, round(period_s / spec_period_s))
                if spec is not None
                else 1
            )
            if stop.wait(spec_period_s if spec is not None else period_s):
                return
            try:
                if self.gate_fn is not None and not self.gate_fn():
                    continue
                if spec is not None:
                    spec.speculate_once()
                ticks += 1
                if ticks >= ratio:
                    ticks = 0
                    self.run_once()
            except Exception:  # noqa: BLE001 — background loop must survive
                log.exception("rebalance pass failed; will retry")

    # --- shared plumbing ---

    def _unbind_all(
        self, items: "list[tuple[PodSpec, str]]", why: str
    ) -> None:
        """Unbind every (pod, host) through the standard rollback path.
        Fanned out on the bind executor when wired, so the unbind API I/O
        overlaps the serve loop's next cycles; this (background) thread
        waits for completion either way — the serve loop never does."""
        if self.bind_executor is not None and len(items) > 1:
            futures = [
                self.bind_executor.submit(
                    lambda pod=pod, host=host: self.scheduler._rollback_bound(
                        pod, host, None, why
                    )
                )
                for pod, host in items
            ]
            for f in futures:
                f.result()
        else:
            for pod, host in items:
                self.scheduler._rollback_bound(pod, host, None, why)

    def _bound_by_gang(
        self, snapshot
    ) -> "tuple[dict[str, list[tuple[PodSpec, str]]], list[tuple[PodSpec, str]]]":
        """This profile's BOUND pods from the snapshot, grouped into
        (gangs, singletons). Only TPU-holding pods — chip-free pods free
        nothing when preempted and pin no blocks."""
        gangs: dict[str, list[tuple[PodSpec, str]]] = {}
        singles: list[tuple[PodSpec, str]] = []
        for ni in snapshot.infos():
            for p in ni.pods:
                if p.scheduler_name != self.scheduler_name:
                    continue
                try:
                    req = pod_request(p)
                except LabelParseError:
                    continue
                if not req.wants_tpu:
                    continue
                name = gang_name_of(p.labels)
                if name:
                    gangs.setdefault(name, []).append((p, ni.name))
                else:
                    singles.append((p, ni.name))
        return gangs, singles

    @staticmethod
    def _spec_of(pods: "list[PodSpec]"):
        for p in pods:
            try:
                spec = pod_request(p).gang
            except LabelParseError:
                continue
            if spec is not None:
                return spec
        return None

    def _fits(
        self,
        snapshot,
        occ: FleetOccupancy,
        pods: "list[PodSpec]",
        *,
        charge: bool,
    ) -> bool:
        """Whole-gang fit check against the occupancy model (the per-pass
        consumption ledger): the real multislice planner for topology
        gangs, a greedy claimable walk for plain ones — the PR 2 / PR 6
        fit-gate shape on the simulated substrate. ``charge=True`` commits
        the chosen hosts' chips to ``occ`` so later decisions this pass
        see them consumed. A predicate, not a placement: real admission
        re-validates everything when the members actually schedule."""
        if not pods:
            return False
        spec = self._spec_of(pods)
        try:
            req0 = pod_request(pods[0])
        except LabelParseError:
            return False
        chips = max(req0.effective_chips, 1)
        # Node-health fence: SUSPECT/DRAINING/DOWN hosts must not be
        # promised capacity by any rebalance decision.
        fenced = getattr(snapshot, "fenced", frozenset())
        if spec is not None and spec.topology is not None:
            plan = plan_multislice_placement(
                snapshot,
                want_dims=spec.topology,
                slices=spec.slices,
                host_ok=lambda ni: (
                    ni.name not in fenced
                    and occ.free_chips(ni.name) >= chips
                    and pod_admits_on(ni.node, pods[0])[0]
                ),
            )
            if plan is None:
                return False
            if charge:
                for host in sorted(plan)[: len(pods)]:
                    occ.occupy(host, chips)
            return True
        taken: list[tuple[str, int]] = []
        for pod in pods:
            try:
                chips = max(pod_request(pod).effective_chips, 1)
            except LabelParseError:
                chips = 1
            best, best_free = None, -1
            for ni in snapshot.infos():
                if ni.name in fenced:
                    continue
                f = occ.free_chips(ni.name)
                if f >= chips and f > best_free and pod_admits_on(ni.node, pod)[0]:
                    best, best_free = ni.name, f
            if best is None:
                for host, c in taken:
                    occ.release(host, c)
                return False
            occ.occupy(best, chips)
            taken.append((best, chips))
        if not charge:
            for host, c in taken:
                occ.release(host, c)
        return True

    # --- (0) graceful drain (node health monitor integration) ---

    def _drain_pass(self, snapshot, occ, report: RebalanceReport) -> None:
        """Migrate bound work off DRAINING nodes proactively (rolling
        cluster upgrades, docs/OPERATIONS.md node-failure runbook): the
        node health monitor fences a draining node from new placements
        and hands its name out via ``draining_fn``; this pass moves every
        bound gang with a member there through the standard transactional
        primitives BEFORE the drain deadline forces a DOWN-style
        evacuation. Topology gangs use the repack move primitive onto a
        live block (no min_gain requirement — the drain overrides the
        churn economics); plain gangs unbind-and-requeue whole and
        re-place off the fence; singletons requeue when capacity exists."""
        if self.draining_fn is None:
            return
        draining = self.draining_fn()
        if not draining:
            return
        gangs, singles = self._bound_by_gang(snapshot)
        for name in sorted(gangs):
            members = gangs[name]
            if not any(h in draining for _, h in members):
                continue
            status = self.gang.gang_status(name)
            if status is not None and status[1] > 0:
                continue  # members waiting at Permit: mid-flight
            spec = self._spec_of([p for p, _ in members])
            why = (
                f"rebalance: draining node(s) "
                f"{sorted({h for _, h in members if h in draining})}; "
                f"migrating gang {name} off before the deadline"
            )
            if spec is not None and spec.topology is not None:
                try:
                    chips = max(
                        pod_request(members[0][0]).effective_chips, 1
                    )
                except LabelParseError:
                    continue
                fenced = getattr(snapshot, "fenced", frozenset())
                sim = occ.clone()
                for _pod, host in members:
                    sim.release(host, chips)
                plan = plan_multislice_placement(
                    snapshot,
                    want_dims=spec.topology,
                    slices=spec.slices,
                    host_ok=lambda ni: (
                        ni.name not in draining
                        and ni.name not in fenced
                        and sim.free_chips(ni.name) >= chips
                        and pod_admits_on(ni.node, members[0][0])[0]
                    ),
                )
                if plan is None or set(plan) == {h for _, h in members}:
                    continue  # nowhere live to go yet; deadline escalates
                if self._execute_move(name, spec, members, plan, report):
                    for _pod, host in members:
                        occ.release(host, chips)
                    for host in plan:
                        occ.occupy(host, chips)
                    report.drained.append(name)
                    if self.metrics is not None:
                        self.metrics.gang_repairs.inc(mode="drain")
                        self.metrics.slo.observe_repair(now=self.clock())
                continue
            # Plain/elastic gang: requeue whole — admission re-places it
            # off the fenced node. Only when live capacity fits it now
            # (a gang with nowhere to go keeps running until the
            # deadline, beats thrashing it into the queue).
            pods = [p for p, _ in members]
            if not self._fits(snapshot, occ, pods, charge=True):
                continue
            qpis = self.queue.take_gang(name)
            try:
                if self.scheduler._fenced():
                    return
                for pod, _host in members:
                    self.gang.drop_membership(pod)
                self._unbind_all(list(members), why)
            finally:
                for q in qpis:
                    self.queue.readd(q)
                self.queue.move_all_to_active()
            report.drained.append(name)
            if self.metrics is not None:
                self.metrics.gang_repairs.inc(mode="drain")
                self.metrics.slo.observe_repair(now=self.clock())
            log.info(
                "rebalance: drained gang %s off %s (requeued whole)",
                name, sorted({h for _, h in members if h in draining}),
            )
        for pod, host in singles:
            if host not in draining:
                continue
            if not self._fits(snapshot, occ, [pod], charge=True):
                continue
            if self.scheduler._fenced():
                return
            self.scheduler._rollback_bound(
                pod, host, None,
                f"rebalance: draining node {host}; pod requeued",
            )
            report.drained.append(pod.key)

    # --- (1) priority preemption ---

    def _preempt_pass(self, snapshot, occ, report: RebalanceReport) -> None:
        pending = self.queue.pending_gangs()
        if not pending:
            return
        held: "list[tuple[int, str, list[QueuedPodInfo]]]" = []
        try:
            for name in sorted(pending):
                count, min_attempts = pending[name]
                if min_attempts < 1:
                    continue  # has not failed a cycle yet: not stuck
                status = self.gang.gang_status(name)
                if status is not None and (status[1] > 0 or status[2] > 0):
                    continue  # members waiting at Permit or bound: mid-flight
                qpis = self.queue.take_gang(name)
                pods = [q.pod for q in qpis]
                spec = self._spec_of(pods)
                target = spec.size if spec is not None else 0
                if spec is not None and spec.elastic:
                    eff = self.gang.effective_size(name)
                    target = eff if eff is not None else spec.size
                if spec is None or len(pods) < min(
                    target, spec.floor if spec.elastic else target
                ):
                    # Not the whole gang in hand: admitting a subset would
                    # split it — the thing preemption must never cause.
                    for q in qpis:
                        self.queue.readd(q)
                    continue
                prio = max(pod_priority(p) for p in pods)
                held.append((prio, name, qpis))
            # Highest priority first: a lower-priority parked gang never
            # takes capacity (or victims) a higher one could use.
            held.sort(key=lambda t: -t[0])
            for prio, name, qpis in held:
                pods = [q.pod for q in qpis]
                spec = self._spec_of(pods)
                target = spec.size
                if spec.elastic:
                    eff = self.gang.effective_size(name)
                    target = max(
                        spec.floor, min(eff if eff is not None else spec.size,
                                        len(pods)),
                    )
                members = pods[:target]
                if self._fits(snapshot, occ, members, charge=True):
                    # Fits already (or after earlier victims this pass):
                    # the serve loop places it once the entries return.
                    report.admitted_gangs.append(name)
                    continue
                chosen = self._select_victims(snapshot, occ, members, prio)
                if chosen is None:
                    if spec.elastic:
                        # No victim set admits the gang at its current
                        # size: shrink the PARKED gang toward its floor
                        # until it fits free capacity — running at
                        # min-members beats parking forever (Pollux's
                        # goodput argument). Never below the floor.
                        for k in range(target - 1, spec.floor - 1, -1):
                            if self._fits(
                                snapshot, occ, pods[:k], charge=True
                            ):
                                new_eff = self.gang.set_effective_size(
                                    name, k
                                )
                                if new_eff is not None:
                                    report.resizes[name] = (target, new_eff)
                                    report.admitted_gangs.append(name)
                                    if self.metrics is not None:
                                        self.metrics.rebalance_resizes.inc()
                                    log.info(
                                        "rebalance: shrank parked elastic "
                                        "gang %s %d -> %d to fit free "
                                        "capacity", name, target, new_eff,
                                    )
                                break
                    continue
                self._execute_victims(name, chosen, occ, report)
                # Charge the admitted gang against the freed capacity so
                # the remaining passes cannot re-promise it.
                self._fits(snapshot, occ, members, charge=True)
                report.admitted_gangs.append(name)
        finally:
            for _, _, qpis in held:
                for q in qpis:
                    self.queue.readd(q)
            if held:
                self.queue.move_all_to_active()

    def _select_victims(
        self, snapshot, occ, gang_pods, prio: int
    ) -> "list[_VictimUnit] | None":
        """Cheapest victim set admitting ``gang_pods`` whole: units sorted
        by (highest member priority, priority-weighted work), added
        greedily into a simulated occupancy until the gang fits. None =
        no feasible set within ``max_victims`` pods."""
        gangs, singles = self._bound_by_gang(snapshot)
        units: list[_VictimUnit] = []
        for pod, host in singles:
            p = pod_priority(pod)
            if p >= prio:
                continue
            units.append(_VictimUnit([(pod, host)], p, priority_weight(pod)))
        for name, members in gangs.items():
            prios = [pod_priority(p) for p, _ in members]
            if max(prios) >= prio:
                continue
            spec = self._spec_of([p for p, _ in members])
            weight = sum(priority_weight(p) for p, _ in members)
            if (
                spec is not None
                and spec.elastic
                and len(members) > spec.floor
            ):
                # Elastic shrink: the cheapest partial form — the gang
                # keeps running at its floor, only the surplus is evicted.
                surplus = sorted(
                    members, key=lambda m: m[0].creation_seq, reverse=True
                )[: len(members) - spec.floor]
                units.append(
                    _VictimUnit(
                        surplus,
                        max(prios),
                        sum(priority_weight(p) for p, _ in surplus),
                        gang=name,
                        keep=spec.floor,
                    )
                )
            units.append(
                _VictimUnit(list(members), max(prios), weight, gang=name)
            )
        units.sort(key=lambda u: (u.max_priority, u.weight))
        # Two greedy rounds: shrink units are cheaper but cap a gang's
        # contribution at its surplus — when only a WHOLE eviction of that
        # gang admits the target, the shrink pick would block it (one unit
        # per gang), so a failed first round retries without shrinks.
        pools = [units]
        if any(u.keep is not None for u in units):
            pools.append([u for u in units if u.keep is None])
        for pool in pools:
            chosen = self._greedy_pick(snapshot, occ, gang_pods, pool)
            if chosen is not None:
                return chosen
        return None

    def _greedy_pick(
        self, snapshot, occ, gang_pods, units: "list[_VictimUnit]"
    ) -> "list[_VictimUnit] | None":
        sim = occ.clone()
        chosen: list[_VictimUnit] = []
        chosen_gangs: set[str] = set()
        n_pods = 0
        for unit in units:
            if unit.gang is not None and unit.gang in chosen_gangs:
                continue  # one unit per gang — no double-free
            if n_pods + len(unit.members) > self.max_victims:
                continue
            for pod, host in unit.members:
                try:
                    sim.release(host, max(pod_request(pod).effective_chips, 1))
                except LabelParseError:
                    sim.release(host, 1)
            chosen.append(unit)
            if unit.gang is not None:
                chosen_gangs.add(unit.gang)
            n_pods += len(unit.members)
            if self._fits(snapshot, sim, gang_pods, charge=False):
                return chosen
        return None

    def _execute_victims(
        self, for_gang: str, chosen: "list[_VictimUnit]", occ, report
    ) -> None:
        if self.scheduler._fenced():
            return
        weight = 0
        for unit in chosen:
            why = (
                f"rebalance: preempted to admit parked gang {for_gang} "
                f"(victim {unit.kind})"
            )
            if unit.kind == "shrink":
                new_eff = self.gang.set_effective_size(unit.gang, unit.keep)
                if new_eff is not None:
                    report.resizes[unit.gang] = (
                        len(unit.members) + unit.keep, new_eff
                    )
                    if self.metrics is not None:
                        self.metrics.rebalance_resizes.inc()
            for pod, _host in unit.members:
                if unit.gang is not None:
                    self.gang.drop_membership(pod)
            self._unbind_all(unit.members, why)
            for pod, host in unit.members:
                try:
                    chips = max(pod_request(pod).effective_chips, 1)
                except LabelParseError:
                    chips = 1
                occ.release(host, chips)
                report.preempted.append(pod.key)
                weight += priority_weight(pod)
        report.preempted_weight += weight
        tr = self._tracer()
        if tr is not None:
            from yoda_tpu.tracing import subject_of

            tr.add(
                f"gang:{for_gang}", "preempt-admit",
                track="rebalancer",
                attrs={
                    "victims": sum(len(u.members) for u in chosen),
                    "weight": weight,
                },
            )
            for unit in chosen:
                for pod, host in unit.members:
                    tr.add(
                        subject_of(pod), "preempted",
                        track="rebalancer",
                        attrs={
                            "for_gang": for_gang,
                            "host": host,
                            "unit": unit.kind,
                        },
                    )
        if self.metrics is not None:
            n_preempted = sum(len(u.members) for u in chosen)
            self.metrics.rebalance_preemptions.inc(n_preempted)
            self.metrics.preempted_weight.inc(weight)
            # SLO engine: priority preemptions feed the fleet
            # preemption-rate SLI alongside PostFilter evictions.
            self.metrics.slo.observe_preemption(
                n_preempted, now=self.clock()
            )
        log.info(
            "rebalance: preempted %d pod(s) in %d unit(s) (weight %d) to "
            "admit gang %s",
            sum(len(u.members) for u in chosen), len(chosen), weight, for_gang,
        )

    # --- (2) elastic resize up ---

    def _resize_up_pass(self, snapshot, occ, report: RebalanceReport) -> None:
        pending = self.queue.pending_gangs()
        resized = False
        for name in sorted(pending):
            status = self.gang.gang_status(name)
            if status is None:
                continue
            _size, waiting, bound = status
            if waiting > 0 or bound == 0:
                continue  # mid-flight, or not running — not a grow target
            eff = self.gang.effective_size(name)
            if eff is None or bound < eff:
                continue  # gang not complete at its current size
            qpis = self.queue.take_gang(name)
            try:
                pods = [q.pod for q in qpis]
                spec = self._spec_of(pods)
                if spec is None or not spec.elastic:
                    continue
                room = spec.ceiling - eff
                if room <= 0 or not pods:
                    continue
                grow: list[PodSpec] = []
                for pod in pods[:room]:
                    if self._fits(snapshot, occ, [pod], charge=True):
                        grow.append(pod)
                    else:
                        break
                if not grow:
                    continue
                new_eff = self.gang.set_effective_size(name, eff + len(grow))
                if new_eff is not None and new_eff != eff:
                    resized = True
                    report.resizes[name] = (eff, new_eff)
                    if self.metrics is not None:
                        self.metrics.rebalance_resizes.inc()
                    log.info(
                        "rebalance: grew elastic gang %s %d -> %d into free "
                        "capacity", name, eff, new_eff,
                    )
            finally:
                for q in qpis:
                    self.queue.readd(q)
        if resized:
            # Parked surplus members re-admit against the raised size.
            self.queue.move_all_to_active()

    # --- (3) repack (background defragmentation) ---

    def _repack_pass(self, snapshot, occ, report: RebalanceReport) -> None:
        if self.max_moves <= 0:
            return
        gangs, _singles = self._bound_by_gang(snapshot)
        for name in sorted(gangs):
            if len(report.moves) >= self.max_moves:
                return
            members = gangs[name]
            spec = self._spec_of([p for p, _ in members])
            if spec is None or spec.topology is None:
                continue  # repack targets ICI blocks
            if len(members) < spec.size:
                continue  # partial gang: the reconciler's problem, not ours
            status = self.gang.gang_status(name)
            if status is not None and status[1] > 0:
                continue  # members waiting at Permit: mid-flight
            try:
                chips = max(pod_request(members[0][0]).effective_chips, 1)
            except LabelParseError:
                continue
            cur_hosts = {host for _, host in members}
            sim = occ.clone()
            for _pod, host in members:
                sim.release(host, chips)
            fenced = getattr(snapshot, "fenced", frozenset())
            plan = plan_multislice_placement(
                snapshot,
                want_dims=spec.topology,
                slices=spec.slices,
                host_ok=lambda ni: (
                    ni.name not in fenced
                    and sim.free_chips(ni.name) >= chips
                    and pod_admits_on(ni.node, members[0][0])[0]
                ),
            )
            if plan is None or set(plan) == cur_hosts:
                continue
            for host in plan:
                sim.occupy(host, chips)
            gain = occ.score() - sim.score()
            if gain < self.min_gain:
                continue
            if self._execute_move(name, spec, members, plan, report):
                # Commit the simulated state as this pass's ledger.
                for _pod, host in members:
                    occ.release(host, chips)
                for host in plan:
                    occ.occupy(host, chips)

    def _tracer(self):
        tr = getattr(self.metrics, "tracer", None)
        return tr if tr is not None and tr.enabled else None

    def _execute_move(
        self, name: str, spec, members, plan, report: RebalanceReport
    ) -> bool:
        """The transactional move primitive: take -> unbind (overlapped)
        -> install plan -> readd. Any member left bound (unbind refused,
        fence flipped) aborts the plan install — the unbound members
        requeue and the gang replans around the stragglers through the
        normal admission path, never split, never oversubscribed.

        Traced as one ``rebalance-move`` span on the gang's lifecycle
        trace with a child event per step, so a Perfetto view of the gang
        shows the move sitting between its two bound epochs and WHICH
        step aborted when one does."""
        tr = self._tracer()
        subj = f"gang:{name}"
        move_id = tr.new_span_id() if tr is not None else None
        t0 = time.monotonic()

        def step(step_name: str, **attrs) -> None:
            if tr is not None:
                tr.add(
                    subj, step_name, parent=move_id, track="rebalancer",
                    attrs=attrs,
                )

        aborted = ""
        qpis = self.queue.take_gang(name)
        step("move-take", members=len(qpis))
        try:
            if self.scheduler._fenced():
                aborted = "fenced"
                report.aborted_moves.append(name)
                if self.metrics is not None:
                    self.metrics.rebalance_aborted.inc()
                return False
            why = f"rebalance: repacking gang {name} onto a tighter ICI block"
            for pod, _host in members:
                self.gang.drop_membership(pod)
            self._unbind_all(list(members), why)
            step("move-unbind", members=len(members))
            stranded = []
            for pod, _host in members:
                try:
                    live = self.cluster.get_pod(pod.key)
                except Exception:  # noqa: BLE001 — unreadable: assume stranded
                    live = pod
                if live is not None and live.node_name:
                    stranded.append(pod.key)
            if stranded:
                log.warning(
                    "rebalance: move of gang %s aborted — %d member(s) "
                    "could not be unbound (%s); gang will replan normally",
                    name, len(stranded), stranded[:3],
                )
                aborted = f"stranded:{len(stranded)}"
                report.aborted_moves.append(name)
                if self.metrics is not None:
                    self.metrics.rebalance_aborted.inc()
                return False
            self.gang.install_plan(name, spec, plan)
            step("move-install-plan", hosts=",".join(sorted(plan)))
            report.moves.append(name)
            if self.metrics is not None:
                self.metrics.rebalance_moves.inc()
            log.info(
                "rebalance: moved gang %s onto block %s (was %s)",
                name, sorted(plan), sorted({h for _, h in members}),
            )
            return True
        finally:
            for q in qpis:
                self.queue.readd(q)
            self.queue.move_all_to_active()
            step("move-readd", members=len(qpis))
            if tr is not None:
                tr.add(
                    subj, "rebalance-move",
                    t0=t0, t1=time.monotonic(),
                    span_id=move_id, track="rebalancer",
                    attrs={
                        "from": ",".join(sorted({h for _, h in members})),
                        "to": ",".join(sorted(plan)),
                        "aborted": aborted,
                    },
                )
