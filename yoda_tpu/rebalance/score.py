"""Fleet fragmentation scoring — the rebalancer's objective function.

Placement is place-once today: churn punches holes into ICI slices and
strands free chips on partially-occupied hosts, so over a long-running
fleet the probability that a whole contiguous block exists for the next
topology gang decays monotonically (Gandiva's fragmentation observation,
PAPERS.md). This module quantifies that decay as one number in [0, 1] so
the background rebalancer (rebalance/rebalancer.py) can (a) publish it
(``yoda_fragmentation_score``), (b) evaluate candidate repacks by score
delta on a simulated occupancy, and (c) prove in the bench's long-churn
replay that rebalancing bounds it.

The score blends two terms, each 0 when free capacity is perfectly
consolidated:

- **block fragmentation** (ICI slices): within each multi-host slice, the
  wholly-free hosts form islands under ICI adjacency (coords differing by
  1 on one axis). Free hosts outside the largest island are fragmented —
  a topology gang cannot use them as one block.
  ``block_frag = Σ_s (free_s - largest_island_s) / Σ_s free_s``.
- **chip stranding** (every host): free chips on partially-occupied hosts
  cannot serve whole-host pods.
  ``chip_frag = stranded_free_chips / total_free_chips``.

``fragmentation = (block_frag + chip_frag) / 2``; an empty term (no free
slice hosts / no free chips) contributes 0.

:class:`FleetOccupancy` is the simulation substrate: a host -> (free,
total) chip model built from a snapshot net of accountant reservations,
cheap to clone, with release/occupy edits — candidate moves are scored on
a clone before any pod is touched.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from yoda_tpu.api.requests import TpuRequest
from yoda_tpu.framework.interfaces import Snapshot
from yoda_tpu.plugins.yoda.filter_plugin import available_chips

# No constraints: every healthy chip qualifies — occupancy is a capacity
# model, not an admission check (admission stays with the callers).
_PLAIN = TpuRequest()

Coord = tuple[int, int, int]


@dataclass
class HostOccupancy:
    """One host's capacity state: healthy chips total and claimable now
    (net of metrics-visible use AND accountant reservations — the same
    handoff model the filter uses via :func:`available_chips`)."""

    name: str
    slice_id: str
    coords: Coord
    total: int
    free: int


class FleetOccupancy:
    """Mutable host-level capacity model for what-if rebalance planning."""

    def __init__(self, hosts: "dict[str, HostOccupancy]") -> None:
        self.hosts = hosts

    @classmethod
    def from_snapshot(
        cls, snapshot: Snapshot, reserved_map: "dict[str, int] | None" = None
    ) -> "FleetOccupancy":
        reserved_map = reserved_map or {}
        hosts: dict[str, HostOccupancy] = {}
        for ni in snapshot.infos():
            tpu = ni.tpu
            if tpu is None:
                continue
            total = len(tpu.healthy_chips())
            free = max(
                available_chips(tpu, _PLAIN, reserved_map.get(ni.name, 0)), 0
            )
            hosts[ni.name] = HostOccupancy(
                name=ni.name,
                slice_id=tpu.slice_id or "",
                coords=tpu.topology_coords,
                total=total,
                free=min(free, total),
            )
        return cls(hosts)

    def clone(self) -> "FleetOccupancy":
        return FleetOccupancy(
            {
                n: HostOccupancy(h.name, h.slice_id, h.coords, h.total, h.free)
                for n, h in self.hosts.items()
            }
        )

    def free_chips(self, name: str) -> int:
        h = self.hosts.get(name)
        return h.free if h is not None else 0

    def release(self, name: str, chips: int) -> None:
        """Simulate (or record) an eviction/unbind freeing ``chips``."""
        h = self.hosts.get(name)
        if h is not None:
            h.free = min(h.free + chips, h.total)

    def occupy(self, name: str, chips: int) -> None:
        """Simulate (or record) a placement taking ``chips``."""
        h = self.hosts.get(name)
        if h is not None:
            h.free = max(h.free - chips, 0)

    def score(self) -> float:
        """The fleet fragmentation score in [0, 1]; 0 = free capacity is
        perfectly consolidated, higher = more broken up. See the module
        docstring for the two blended terms."""
        return (self._block_frag() + self._chip_frag()) / 2.0

    # --- terms ---

    def _block_frag(self) -> float:
        by_slice: dict[str, set[Coord]] = {}
        for h in self.hosts.values():
            if h.slice_id and h.free >= h.total and h.total > 0:
                by_slice.setdefault(h.slice_id, set()).add(h.coords)
        total_free = sum(len(c) for c in by_slice.values())
        if total_free == 0:
            return 0.0
        outside = 0
        for coords in by_slice.values():
            outside += len(coords) - _largest_island(coords)
        return outside / total_free

    def _chip_frag(self) -> float:
        free = stranded = 0
        for h in self.hosts.values():
            free += h.free
            if 0 < h.free < h.total:
                stranded += h.free
        return stranded / free if free else 0.0


def _largest_island(coords: "set[Coord]") -> int:
    """Largest connected component of ``coords`` under 6-neighbor ICI
    adjacency (axis-aligned unit steps). Host grids are tens of hosts, so
    plain BFS is plenty."""
    remaining = set(coords)
    best = 0
    while remaining:
        start = remaining.pop()
        q = deque([start])
        size = 1
        while q:
            x, y, z = q.popleft()
            for nxt in (
                (x + 1, y, z), (x - 1, y, z),
                (x, y + 1, z), (x, y - 1, z),
                (x, y, z + 1), (x, y, z - 1),
            ):
                if nxt in remaining:
                    remaining.remove(nxt)
                    q.append(nxt)
                    size += 1
        best = max(best, size)
    return best


def fragmentation_score(
    snapshot: Snapshot, reserved_map: "dict[str, int] | None" = None
) -> float:
    """One-shot convenience: the fleet fragmentation score for a snapshot
    net of ``reserved_map`` (accountant reservations)."""
    return FleetOccupancy.from_snapshot(snapshot, reserved_map).score()
