"""Goodput-driven rebalancing: background ICI defragmentation, priority
preemption, and elastic gang resize (see rebalance/rebalancer.py)."""

from yoda_tpu.rebalance.rebalancer import (
    RebalanceReport,
    Rebalancer,
    priority_weight,
)
from yoda_tpu.rebalance.score import (
    FleetOccupancy,
    HostOccupancy,
    fragmentation_score,
)

__all__ = [
    "FleetOccupancy",
    "HostOccupancy",
    "RebalanceReport",
    "Rebalancer",
    "fragmentation_score",
    "priority_weight",
]
