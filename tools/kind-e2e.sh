#!/usr/bin/env bash
# Real-cluster smoke test (VERDICT r3 missing #3): stand up a kind cluster,
# deploy the scheduler + agent from ./deploy, and assert a tpu/* pod binds —
# the analog of the reference's manual live-cluster check
# (reference readme.md:22-25,70-73), automated. Needs docker + kind +
# kubectl on PATH; the bench/CI environments here have no Docker, so CI
# marks this job optional and it runs wherever Docker exists.
#
# Usage: tools/kind-e2e.sh [--keep]
set -euo pipefail

cd "$(dirname "$0")/.."
KEEP=${1:-}
CLUSTER=yoda-tpu-e2e
IMAGE=yoda-tpu/scheduler:latest

for bin in docker kind kubectl; do
  command -v "$bin" >/dev/null || { echo "missing: $bin" >&2; exit 2; }
done

cleanup() {
  [ "$KEEP" = "--keep" ] || kind delete cluster --name "$CLUSTER" || true
}
trap cleanup EXIT

echo "== build image"
docker build -t "$IMAGE" .

echo "== create kind cluster"
kind get clusters | grep -qx "$CLUSTER" || kind create cluster --name "$CLUSTER" --wait 120s
kind load docker-image "$IMAGE" --name "$CLUSTER"

echo "== apply CRD + RBAC + scheduler + agent"
kubectl apply -f deploy/crd.yaml
kubectl apply -f deploy/yoda-tpu-scheduler.yaml
# kind nodes have no TPUs: the agent publishes spec-table CRs via
# --allow-fake so the scheduling path is exercised end to end. Verify the
# injection actually took (a renamed arg line must fail HERE, not 120 s
# later as "no TpuNodeMetrics appeared").
patched=$(sed 's/- --interval-s=10/- --interval-s=10\n            - --allow-fake/' \
  deploy/yoda-tpu-agent.yaml)
echo "$patched" | grep -q -- '--allow-fake' \
  || { echo "failed to inject --allow-fake into the agent manifest" >&2; exit 1; }
echo "$patched" | kubectl apply -f -

# kind never pulls: the loaded node-local image must be used (":latest"
# defaults imagePullPolicy to Always, which pulls from docker.io and
# fails for this local-only image).
kubectl -n kube-system patch deploy/yoda-tpu-scheduler --type=json -p \
  '[{"op":"add","path":"/spec/template/spec/containers/0/imagePullPolicy","value":"IfNotPresent"}]'
kubectl -n kube-system patch ds/yoda-tpu-agent --type=json -p \
  '[{"op":"add","path":"/spec/template/spec/containers/0/imagePullPolicy","value":"IfNotPresent"}]'

echo "== wait for scheduler + agent"
kubectl -n kube-system rollout status deploy/yoda-tpu-scheduler --timeout=180s
kubectl -n kube-system rollout status ds/yoda-tpu-agent --timeout=180s

echo "== wait for TpuNodeMetrics CRs"
deadline=$((SECONDS + 120))
until [ "$(kubectl get tpunodemetrics -o name 2>/dev/null | wc -l)" -ge 1 ]; do
  [ $SECONDS -lt $deadline ] || { echo "no TpuNodeMetrics appeared" >&2; exit 1; }
  sleep 2
done

echo "== schedule the example pod"
kubectl apply -f example/test-pod.yaml
deadline=$((SECONDS + 120))
until node=$(kubectl get pod tpu-test-pod -o jsonpath='{.spec.nodeName}') \
    && [ -n "$node" ]; do
  [ $SECONDS -lt $deadline ] || {
    echo "pod never bound" >&2
    kubectl describe pod tpu-test-pod >&2
    kubectl -n kube-system logs deploy/yoda-tpu-scheduler --tail=50 >&2
    exit 1
  }
  sleep 2
done
echo "== OK: tpu-test-pod bound to $node"

echo "== schedule a plain 2-member gang"
# NOT example/test-gang.yaml: that is a 2x2x1 TOPOLOGY gang needing four
# ICI-grid hosts, and --allow-fake publishes standalone hosts (no slice)
# — on kind it could never place. A plain gang exercises admission, the
# Permit barrier, and atomic release on the fake hosts that DO exist.
for i in 0 1; do
  kubectl apply -f - <<EOF
apiVersion: v1
kind: Pod
metadata:
  name: e2e-gang-$i
  labels:
    tpu/gang: e2e
    tpu/gang-size: "2"
    tpu/chips: "1"
spec:
  schedulerName: yoda-tpu
  containers:
    - name: main
      image: registry.k8s.io/pause:3.9
EOF
done
deadline=$((SECONDS + 180))
until [ "$(kubectl get pods -l tpu/gang=e2e -o jsonpath='{range .items[*]}{.spec.nodeName}{"\n"}{end}' | grep -c .)" -ge 2 ]; do
  [ $SECONDS -lt $deadline ] || {
    echo "gang never fully bound" >&2
    kubectl get pods -l tpu/gang=e2e -o wide >&2
    kubectl -n kube-system logs deploy/yoda-tpu-scheduler --tail=50 >&2
    exit 1
  }
  sleep 2
done
echo "== OK: gang bound"
echo "kind-e2e PASSED"
