#!/usr/bin/env python
"""Metric-drift check — MIGRATED to the yodalint framework (ISSUE 13).

This shim keeps the historical entry point (`python tools/check_metrics.py`)
alive for muscle memory and old CI recipes; the actual analysis is
yodalint's metrics-drift pass (tools/yodalint/passes/metrics_drift.py),
which `make lint` runs via `python -m tools.yodalint` alongside the six
other project-invariant passes.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.yodalint import Project, apply_suppressions, report  # noqa: E402
from tools.yodalint.passes import PASS_NAMES, metrics_drift  # noqa: E402


def main() -> int:
    project = Project(Path(__file__).resolve().parent.parent)
    findings = apply_suppressions(
        project, metrics_drift.run(project), PASS_NAMES
    )
    rc = report(findings)
    if rc == 0:
        print(
            "check_metrics: clean (ran as yodalint's metrics-drift pass; "
            "`python -m tools.yodalint` runs the full suite)"
        )
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
