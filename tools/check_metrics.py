#!/usr/bin/env python
"""Metric-drift check (ISSUE 9 satellite): every ``yoda_*`` series
registered anywhere in yoda_tpu/ must be (a) asserted in
tests/test_observability.py and (b) documented in docs/OPERATIONS.md.

New metrics silently skipping the test suite or the operator docs is how
observability rots: the series exists, nobody knows what it means, and a
rename breaks dashboards without failing CI. This script closes the loop
and runs under ``make lint``.

Registration sites are found syntactically — the first string argument of
``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` calls (the Registry
surface in yoda_tpu/observability.py) — so a metric cannot hide behind an
accumulator pattern or a lazily-attached family.

Exit 0 when clean; exit 1 listing every undrifted name otherwise.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PACKAGE = REPO / "yoda_tpu"
TEST_FILE = REPO / "tests" / "test_observability.py"
DOCS_FILE = REPO / "docs" / "OPERATIONS.md"

# `r.counter(\n    "yoda_x", ...` — \s* spans the line break; the metric
# name is always the first positional (string literal) argument.
REGISTRATION = re.compile(
    r'\b(?:counter|gauge|histogram)\(\s*["\'](yoda_[a-z0-9_]+)["\']'
)


def registered_names() -> "dict[str, list[str]]":
    """metric name -> files registering it."""
    names: dict[str, list[str]] = {}
    for path in sorted(PACKAGE.rglob("*.py")):
        text = path.read_text()
        for m in REGISTRATION.finditer(text):
            names.setdefault(m.group(1), []).append(
                str(path.relative_to(REPO))
            )
    return names


def main() -> int:
    names = registered_names()
    if not names:
        print("check_metrics: found no registered yoda_* series — the "
              "registration regex no longer matches the code", file=sys.stderr)
        return 1
    test_text = TEST_FILE.read_text()
    docs_text = DOCS_FILE.read_text()
    missing_test = sorted(n for n in names if n not in test_text)
    missing_docs = sorted(n for n in names if n not in docs_text)
    if not missing_test and not missing_docs:
        print(
            f"check_metrics: {len(names)} yoda_* series registered, all "
            "asserted in tests/test_observability.py and documented in "
            "docs/OPERATIONS.md"
        )
        return 0
    for n in missing_test:
        print(
            f"check_metrics: {n} (registered in {names[n][0]}) is not "
            f"asserted in {TEST_FILE.relative_to(REPO)}", file=sys.stderr,
        )
    for n in missing_docs:
        print(
            f"check_metrics: {n} (registered in {names[n][0]}) is not "
            f"documented in {DOCS_FILE.relative_to(REPO)}", file=sys.stderr,
        )
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
