"""Pass 2 — fence-before-write: every mutating cluster-API call site is
dominated by a leader-fence check.

The PR 3/4 invariant: a scheduler process that is not (or is no longer)
the leader must never reach the API with a write — a stale leader's bind
racing the new leader's is exactly the split-brain KEP-624's async-bind
lineage warns about. Reads may go stale harmlessly; writes must be
fenced.

Mutating surface: ``bind_pod`` / ``unbind_pod`` / ``create_pod`` /
``delete_pod`` / ``evict_pod`` (and the preemption plugin's injected
``self.evict``) called on a cluster object. For each such call site the
enclosing function must show fence evidence *before* the call line — a
read of ``_fenced`` / ``fenced_fn`` / ``fence_fn`` / ``gate_fn`` /
``is_leader`` — or every statically-known caller must show evidence
before its call into the function (one level of interprocedural
domination: enough for this codebase's helper shape, and an
under-approximation never hides a write path that has no fence
anywhere).

The cluster backends themselves (cluster/fake.py, cluster/kube.py) are
out of scope — they *implement* the API; the discipline binds their
callers. Test scaffolding (testing/, demo.py) drives clusters by design.
"""

from __future__ import annotations

import ast

from tools.yodalint.callgraph import CallGraph, FunctionInfo
from tools.yodalint.core import Finding, Project, walk_cached

NAME = "fence-before-write"

MUTATING = {
    "bind_pod",
    "unbind_pod",
    "create_pod",
    "delete_pod",
    "evict_pod",
    "evict",
    "evict_fn",  # the preemption plugin's injected evictor
    # Scheduler shard-out (ISSUE 14): the optimistic shard commit is a
    # WRITE-equivalent decision point — a committed claim licenses the
    # bind that follows (or blesses binds that already landed), so a
    # fenced ex-leader committing would launder its stale placements
    # past the new leader exactly as an unfenced bind would. Every
    # commit call must be dominated by a fence read, same as the API
    # writes. (commit_residue is exempt: it finalizes what cluster
    # truth ALREADY shows bound — the reconciler's recovery path.)
    "commit_staged",
    "commit_fn",  # the scheduler's injected commit point
}

FENCE_MARKERS = {"_fenced", "fenced_fn", "fence_fn", "gate_fn", "is_leader"}

SKIP_SUFFIXES = ("cluster/fake.py", "cluster/kube.py", "demo.py")


def _receiver_is_cluster(func: ast.Attribute) -> bool:
    """True when the call receiver syntactically reads as a cluster
    object (``cluster``, ``self.cluster``, ``member.cluster``, ...) or is
    the preemption plugin's injected evictor (``self.evict``)."""
    src_parts: "list[str]" = []
    node: ast.expr = func.value
    while isinstance(node, ast.Attribute):
        src_parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        src_parts.append(node.id)
    if func.attr in ("evict", "evict_fn"):
        return src_parts == ["self"]
    if func.attr in ("commit_staged", "commit_fn"):
        # The shard commit point: the accountant's method, or the
        # scheduler's injected hook (self.commit_fn).
        return src_parts == ["self"] or any(
            "accountant" in part for part in src_parts
        )
    return any("cluster" in part for part in src_parts)


def _fence_lines(fn: FunctionInfo) -> "list[int]":
    """Lines in ``fn`` that read a fence marker."""
    lines = []
    for node in walk_cached(fn.node):
        if isinstance(node, ast.Attribute) and node.attr in FENCE_MARKERS:
            lines.append(node.lineno)
        elif isinstance(node, ast.Name) and node.id in FENCE_MARKERS:
            lines.append(node.lineno)
    return lines


def _call_edges(
    graph: CallGraph,
) -> "dict[str, list[tuple[FunctionInfo, int]]]":
    """callee qualname -> [(caller, call line)] over resolved edges."""
    rev: "dict[str, list[tuple[FunctionInfo, int]]]" = {}
    for fn in graph.functions.values():
        for call in graph.calls_in(fn):
            for callee in graph.resolve_call(call, fn):
                rev.setdefault(callee.qualname, []).append(
                    (fn, call.lineno)
                )
    return rev


def run(project: Project, graph: "CallGraph | None" = None) -> "list[Finding]":
    graph = graph or CallGraph(project)
    rev = _call_edges(graph)
    findings: "list[Finding]" = []
    for fn in graph.functions.values():
        rel = fn.module.relpath
        if rel.endswith(SKIP_SUFFIXES) or "/testing/" in rel:
            continue
        fence_before = _fence_lines(fn)
        for call in graph.calls_in(fn):
            func = call.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in MUTATING
                and _receiver_is_cluster(func)
            ):
                continue
            if any(line <= call.lineno for line in fence_before):
                continue
            callers = rev.get(fn.qualname, [])
            if callers and all(
                any(
                    fl <= call_line
                    for fl in _fence_lines(caller)
                )
                for caller, call_line in callers
            ):
                continue
            findings.append(
                Finding(
                    NAME,
                    rel,
                    call.lineno,
                    f"mutating cluster write .{func.attr}() with no "
                    "leader-fence check dominating it (no _fenced/"
                    "fenced_fn/fence_fn/gate_fn read before this line in "
                    f"{fn.qualname.split('::')[-1]} or its known "
                    "callers) — a fenced ex-leader could race the new "
                    "leader's writes",
                )
            )
    return findings
