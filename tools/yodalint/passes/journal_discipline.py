"""Pass 10 — journal-discipline: the durable claim journal has exactly
one writer, and accountant claim state has exactly one owner.

The crash-consistency argument of the durable claim journal (ISSUE 18,
yoda_tpu/journal/) is write-ahead ordering: every accountant state
mutation appends its record BEFORE the in-memory mutation applies, all
under the accountant's lock. That argument survives only while both
monopolies hold:

**A. Append monopoly.** No module outside ``yoda_tpu/journal/`` and the
accountant implementation (``plugins/yoda/accounting.py``) may call the
``CommitLog`` write surface (``record_stage`` / ``record_commit`` /
``record_release`` / ``record_rollback``). A second appender writes
records that do not correspond to accountant mutations — replay then
rebuilds state the process never held, and the standby inherits phantom
claims.

One scoped exception to A (ISSUE 19): the commit RPC server —
``class CommitRPCServer`` in ``yoda_tpu/framework/procserve.py`` — is
the parent-side front of the accountant for ``shard_mode=process``
workers, and its handlers are the only non-accountant path allowed to
reach the CommitLog write surface. The exemption is CLASS-scoped, not
module-scoped: the RPC *client*, the worker entries, and anything else
in procserve.py that touched the journal directly would be a second
writer running OUTSIDE the accountant's lock, exactly the split-log
hazard rule A exists for.

**C. Term-bump monopoly (ISSUE 20).** The epoch-term record
(``record_term_bump``) is the multi-host fencing root: it must be the
FIRST frame a promoted journal fsyncs, written exactly once per
promotion, from the promotion path only (``yoda_tpu/journal/`` — the
journal's own ``promote()`` and the tailer's ``promote_into``). A bump
written from anywhere else — the accountant, the RPC server, a CLI
branch — could raise the term WITHOUT the standby handover that
justifies it, deposing a healthy leader's term on disk and fencing its
own workers. Rule C is therefore STRICTER than rule A: neither the
accountant nor the CommitRPCServer exemption extends to it.

**B. Claim-state monopoly.** No module outside ``accounting.py`` may
touch the accountant's claim-state attributes (``_claims`` / ``_in_use``
/ ``_staged`` / ``_stage_seq``) on a non-``self`` receiver. An external
mutation bypasses the journal entirely: the on-disk log and memory
diverge, and the next warm-start replay resurrects state the mutation
removed (or drops state it added). Same-module ``self`` access is the
mechanism, not a violation — and a module's own private attr that
happens to share a spelling (the journal's own ``_stage_seq``) stays
legal for the same reason.
"""

from __future__ import annotations

import ast

from tools.yodalint.callgraph import CallGraph
from tools.yodalint.core import Finding, Project, walk_cached

NAME = "journal-discipline"

#: The CommitLog write surface (journal/journal.py CommitLog).
RECORD_METHODS = {
    "record_stage",
    "record_commit",
    "record_release",
    "record_rollback",
}

#: The promotion-only term surface (ISSUE 20): writable from the
#: journal package alone — no accountant or RPC-server exemption.
TERM_METHODS = {"record_term_bump"}

TERM_EXEMPT = ("yoda_tpu/journal/",)

#: The accountant's claim state (plugins/yoda/accounting.py). The
#: journal's replay is the ONLY other legal reconstruction path, and it
#: goes through accountant.restore(), not these attrs.
CLAIM_STATE_ATTRS = {"_claims", "_in_use", "_staged", "_stage_seq"}

#: Modules allowed to call the write surface: the journal package
#: (defines it) and the accountant (the one legal appender).
APPEND_EXEMPT = ("yoda_tpu/journal/", "plugins/yoda/accounting.py")

STATE_OWNER_SUFFIX = "plugins/yoda/accounting.py"

#: Class-scoped append exemption (ISSUE 19): inside THIS module, only
#: code lexically within THIS class may reach the write surface — the
#: commit RPC server fronts the accountant for worker processes; the
#: client and the worker entries in the same file stay forbidden.
RPC_SERVER_MODULE_SUFFIX = "framework/procserve.py"
RPC_SERVER_CLASS = "CommitRPCServer"


def _exempt_from_append(rel: str) -> bool:
    return any(part in rel for part in APPEND_EXEMPT)


def _rpc_server_spans(tree) -> "list[tuple[int, int]]":
    """Line spans of ``class CommitRPCServer`` definitions (top level or
    nested) — the only lexical scope in procserve.py with append
    rights."""
    return [
        (node.lineno, node.end_lineno or node.lineno)
        for node in ast.walk(tree)
        if isinstance(node, ast.ClassDef) and node.name == RPC_SERVER_CLASS
    ]


def run(project: Project, graph: "CallGraph | None" = None) -> "list[Finding]":
    findings: "list[Finding]" = []
    for module in project.modules:
        rel = module.relpath
        rpc_spans = (
            _rpc_server_spans(module.tree)
            if rel.endswith(RPC_SERVER_MODULE_SUFFIX)
            else []
        )
        for node in walk_cached(module.tree):
            # Rule A: journal appends outside the journal/accountant —
            # with the one class-scoped exception: CommitRPCServer
            # handlers in framework/procserve.py.
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in RECORD_METHODS
                and not _exempt_from_append(rel)
                and not any(
                    lo <= node.lineno <= hi for lo, hi in rpc_spans
                )
            ):
                findings.append(
                    Finding(
                        NAME,
                        rel,
                        node.lineno,
                        f"journal append .{node.func.attr}() outside the "
                        "accountant — the CommitLog has exactly one "
                        "writer (plugins/yoda/accounting.py); a second "
                        "appender writes records no accountant mutation "
                        "backs, and replay resurrects phantom claims",
                    )
                )
            # Rule C: term bumps outside the promotion path — stricter
            # than A: no accountant or CommitRPCServer exemption.
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in TERM_METHODS
                and not any(part in rel for part in TERM_EXEMPT)
            ):
                findings.append(
                    Finding(
                        NAME,
                        rel,
                        node.lineno,
                        f".{node.func.attr}() outside yoda_tpu/journal/ "
                        "— the epoch-term record is writable only from "
                        "the promotion path; a bump without a standby "
                        "handover deposes a healthy leader's term on "
                        "disk and fences its own workers",
                    )
                )
            # Rule B: accountant claim state touched from outside.
            if (
                isinstance(node, ast.Attribute)
                and node.attr in CLAIM_STATE_ATTRS
                and not rel.endswith(STATE_OWNER_SUFFIX)
                and not (
                    isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                )
            ):
                findings.append(
                    Finding(
                        NAME,
                        rel,
                        node.lineno,
                        f"accountant claim state .{node.attr} touched "
                        "outside plugins/yoda/accounting.py — mutations "
                        "that bypass the accountant bypass the journal's "
                        "write-ahead append, so the on-disk log and "
                        "memory diverge and the next warm-start replay "
                        "rebuilds the wrong claims",
                    )
                )
    return findings
