"""Pass 5 — hook-registration-order: ``standalone.build_stack`` wires
watch handlers in the documented accountant -> gang -> informer order.

Reservation releases must land before the informer's view of the same
event (the accountant/gang only ever run AHEAD of the informer — the
safe direction: reservations become visible early, never late), and the
event recorder prunes after the informer has applied. The order is
enforced at three sites in ``build_stack``:

1. the ``per_event_sinks`` list construction (accountant before gang
   before the tenant ledger),
2. the batched ``apply_batch`` closure (sinks loop -> informer
   ``handle_batch`` -> recorder),
3. the per-event ``add_watcher`` registrations (sinks -> informer ->
   recorder).

Because 2. and 3. both iterate ``per_event_sinks`` and then name the
informer/recorder explicitly, the check reduces to: within
``build_stack``, the *first textual references* to ``accountant.handle``,
``gang.handle``, ``ledger.handle`` must appear in that order, and every
reference to ``informer.handle``/``handle_batch`` must precede every
``recorder.handle`` in its wiring block while following the sink
construction. A refactor that swaps any pair flags here before a chaos
test ever catches the resulting accounting skew.
"""

from __future__ import annotations

import ast

from tools.yodalint.core import Finding, Project

NAME = "hook-registration-order"

#: (object, attr) handler references, in required first-appearance order.
ORDER = [
    ("accountant", "handle"),
    ("gang", "handle"),
    ("ledger", "handle"),
    ("informer", "handle"),  # handle or handle_batch
    ("recorder", "handle"),
]


def run(project: Project, graph=None) -> "list[Finding]":
    mod = project.module("standalone.py")
    if mod is None:
        return []
    build = None
    for node in mod.tree.body:
        if isinstance(node, ast.FunctionDef) and node.name == "build_stack":
            build = node
            break
    if build is None:
        return [
            Finding(
                NAME,
                mod.relpath,
                1,
                "standalone.py has no build_stack — the handler-order "
                "contract has no anchor; re-point this pass",
            )
        ]
    refs: "list[tuple[int, str]]" = []
    for node in ast.walk(build):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.attr in ("handle", "handle_batch")
            and node.value.id in {o for o, _ in ORDER}
        ):
            refs.append((node.lineno, node.value.id))
    refs.sort()
    first_seen: "dict[str, int]" = {}
    for line, obj in refs:
        first_seen.setdefault(obj, line)
    findings: "list[Finding]" = []
    required = [o for o, _ in ORDER]
    present = [o for o in required if o in first_seen]
    for a, b in zip(present, present[1:]):
        if first_seen[a] > first_seen[b]:
            findings.append(
                Finding(
                    NAME,
                    mod.relpath,
                    first_seen[b],
                    f"handler wiring order violated in build_stack: "
                    f"{b}.handle is wired (line {first_seen[b]}) before "
                    f"{a}.handle (line {first_seen[a]}) — documented "
                    "order is accountant -> gang -> ledger -> informer "
                    "-> recorder (reservation releases must precede the "
                    "informer's view of the same event)",
                )
            )
    if "accountant" not in first_seen or "informer" not in first_seen:
        findings.append(
            Finding(
                NAME,
                mod.relpath,
                build.lineno,
                "build_stack no longer wires accountant.handle and "
                "informer.handle where this pass can see them — the "
                "handler-order contract has no anchor; re-point the pass",
            )
        )
    return findings
