"""Pass 3 — snapshot-immutability: no attribute assignment on Snapshot /
FleetArrays instances outside whitelisted construction sites.

A Snapshot is the immutable-per-cycle cluster view (PR 7's
device-resident fleet state and PR 8/11's admission caches key on
``snapshot.version`` identity); FleetArrays rows are mutated only
through the kernels' ``update_rows`` / ``fill_row`` delta paths. An ad
hoc ``snap.x = ...`` anywhere else silently invalidates every consumer
that cached against the snapshot's identity.

Detection: attribute assignments (``x.attr = ...``, augmented included)
whose target is snapshot-typed —

- bound in the same function from ``Snapshot(...)`` /
  ``FleetArrays(...)`` / ``FleetArrays.from_snapshot(...)`` /
  ``*.with_dynamic(...)`` / a ``*.snapshot()`` call,
- or annotated ``Snapshot`` / ``FleetArrays`` (parameters included),
- or named ``snap`` / ``snapshot`` / ``arrays`` (the tree's naming
  convention for these objects).

Whitelisted: methods of the two classes themselves, functions named
``fill_row`` / ``update_rows`` (the sanctioned mutation paths), and —
construction sites — assignments in the *same function* that constructed
the instance (the informer finishes a snapshot it just built before
publishing it).
"""

from __future__ import annotations

import ast

from tools.yodalint.callgraph import CallGraph
from tools.yodalint.core import Finding, Project, walk_cached

NAME = "snapshot-immutability"

TYPED_NAMES = {"snap", "snapshot", "arrays"}
PROTECTED_CLASSES = {"Snapshot", "FleetArrays"}
MUTATOR_FUNCS = {"fill_row", "update_rows"}

#: Value expressions that bind a snapshot-typed name.
CONSTRUCTOR_CALLS = {"Snapshot", "FleetArrays"}
CONSTRUCTOR_METHODS = {"from_snapshot", "with_dynamic", "snapshot"}


def _constructed_names(fn_node: ast.AST) -> "set[str]":
    """Names bound from a Snapshot/FleetArrays constructor in this
    function (construction site: finishing touches are allowed)."""
    out: "set[str]" = set()
    for node in walk_cached(fn_node):
        if not (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
        ):
            continue
        func = node.value.func
        hit = (
            isinstance(func, ast.Name) and func.id in CONSTRUCTOR_CALLS
        ) or (
            isinstance(func, ast.Attribute)
            and func.attr in (CONSTRUCTOR_CALLS | CONSTRUCTOR_METHODS)
        )
        if not hit:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _annotated_names(fn_node) -> "set[str]":
    """Parameters / locals annotated Snapshot or FleetArrays."""
    out: "set[str]" = set()
    args = getattr(fn_node, "args", None)
    if args is not None:
        for a in (
            list(args.posonlyargs)
            + list(args.args)
            + list(args.kwonlyargs)
        ):
            ann = a.annotation
            text = (
                ann.value
                if isinstance(ann, ast.Constant)
                else (ast.unparse(ann) if ann is not None else "")
            )
            if any(c in str(text) for c in PROTECTED_CLASSES):
                out.add(a.arg)
    for node in walk_cached(fn_node):
        if isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            text = ast.unparse(node.annotation)
            if any(c in text for c in PROTECTED_CLASSES):
                out.add(node.target.id)
    return out


def run(project: Project, graph: "CallGraph | None" = None) -> "list[Finding]":
    graph = graph or CallGraph(project)
    findings: "list[Finding]" = []
    for fn in graph.functions.values():
        rel = fn.module.relpath
        if "/testing/" in rel:
            continue
        if fn.node.name in MUTATOR_FUNCS:
            continue
        if fn.cls is not None and fn.cls.name in PROTECTED_CLASSES:
            continue
        constructed = _constructed_names(fn.node)
        typed = (
            (_annotated_names(fn.node) | TYPED_NAMES | constructed)
            - constructed
        )
        for node in walk_cached(fn.node):
            targets: "list[ast.expr]" = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in typed
                ):
                    continue
                findings.append(
                    Finding(
                        NAME,
                        rel,
                        node.lineno,
                        f"attribute assignment {t.value.id}.{t.attr} on "
                        "a Snapshot/FleetArrays instance outside its "
                        "construction site — snapshots are immutable per "
                        "cycle (admission caches and resident fleet "
                        "state key on snapshot identity)",
                    )
                )
    return findings
