"""Pass 1 — lock-discipline: no blocking work under a component lock, and
lock acquisitions respect the declared ordering DAG.

The PR 9 serve-path stall was a Histogram quantile computed under a hot
lock; the bug *class* is any blocking call — sleeps, cluster API I/O,
subprocess, socket/HTTP, foreign condvar waits, queue gets — reachable
while one of the scheduler's fine-grained state locks is held. Those
locks sit on the watch path, the serve path, or the metrics scrape path,
so one blocked holder stalls every thread behind it.

Two checks:

**Blocking-under-lock.** For every ``with <lock>:`` region whose lock is
a component state lock (``self._lock`` and friends; see LOCK_ATTRS),
every call inside the region — and everything statically reachable from
those calls through the call graph — is screened against the blocking
primitives. ``Condition.wait`` on the *held* lock's own condition is
exempt (wait releases it); waits on anything else block a foreign
holder.

The two *cycle* locks (``cycle_lock`` / ``post_filter_lock``) are
deliberately NOT screened: they exist to serialize whole scheduling
cycles across profile loops — kernel dispatch and bind I/O under them is
the design, not a bug (docs/ARCHITECTURE.md).

**Lock-ordering DAG.** The component locks are ordered

    speculation -> informer -> queue -> accountant -> gang -> metrics

(watch delivery flows informer->queue; queue admission verdicts flow
->metrics; nothing may reach *backwards*). Holding a later lock while
acquiring an earlier one — directly or through the call graph — is a
potential deadlock and is flagged. The speculation level (ISSUE 17)
sits at the BOTTOM: the speculative cache pulls from the informer's
delta feeds, so holding its lock while taking informer locks is legal,
and the informer must never call back into the cache (the companion
``speculation-safety`` pass pins that direction). Locks outside the six
levels (rebalancer, federation, nodehealth, backends) are screened for
blocking calls but carry no order.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.yodalint.callgraph import CallGraph, FunctionInfo
from tools.yodalint.core import Finding, Project, walk_cached

NAME = "lock-discipline"

#: Attribute names that denote a state lock when acquired via ``with``.
LOCK_ATTRS = {
    "_lock",
    "_cond",
    "_apply_lock",
    "_waiting_lock",
    "_trace_lock",
    "_activity",
}

#: Coarse cycle-serialization locks: exempt by design (see docstring).
EXEMPT_LOCK_NAMES = {"cycle_lock", "post_filter_lock", "select_lock"}

#: The declared ordering DAG (lower acquires before higher; acquiring a
#: LOWER level while holding a higher one is the violation).
LOCK_LEVELS = {
    "speculation": 0,
    "informer": 1,
    "queue": 2,
    "accountant": 3,
    "gang": 4,
    "metrics": 5,
}

#: Which classes' locks carry which level. Module-level grouping for the
#: metrics family (one scrape surface, many registry-side classes).
CLASS_LEVELS = {
    "InformerCache": "informer",
    "SchedulingQueue": "queue",
    "ChipAccountant": "accountant",
    "GangPlugin": "gang",
    # Scheduler shard-out (ISSUE 14): the router's fleet-registry lock is
    # taken from INSIDE informer lock regions (pod routing runs during
    # handle_batch), so it ranks WITH the informer level — reaching from
    # it into queue/accountant/gang is forbidden in that direction, and
    # the shared-accountant commit path (accountant level) must never
    # reach back into the router/informer. This is what keeps
    # ChipAccountant.commit_staged's capacity source a watch-maintained
    # local dict instead of an informer read.
    "ShardRouter": "informer",
    # Sub-millisecond serve (ISSUE 17): the speculative placement cache
    # is PULL-only — its producer/consumer paths read the informer feeds
    # and the accountant while holding nothing above speculation level,
    # so its lock ranks below everything. A reach from any higher level
    # back into SpeculativeCache._lock (e.g. an informer-side
    # invalidation callback) is exactly the deadlock the ordering
    # forbids.
    "SpeculativeCache": "speculation",
}
MODULE_LEVELS = {
    "yoda_tpu/observability.py": "metrics",
    "yoda_tpu/tracing.py": "metrics",
    "yoda_tpu/slo/engine.py": "metrics",
}

#: Cluster-API methods: network round-trips on a real backend.
CLUSTER_IO = {
    "bind_pod",
    "unbind_pod",
    "create_pod",
    "delete_pod",
    "evict_pod",
    "list_pods",
    "list_nodes",
    "list_tpu_metrics",
    "list_events",
    "write_event",
    "set_nominated_node",
    "put_tpu_metrics",
    "probe",
}

SUBPROCESS_FNS = {"run", "Popen", "check_output", "check_call", "call"}
HTTP_FNS = {"urlopen", "getresponse", "create_connection"}


@dataclass(frozen=True)
class LockKey:
    """Identity of an acquired lock: the owning class + attribute."""

    owner: str  # class name (or module relpath for module-level locks)
    attr: str
    level: "str | None"  # one of LOCK_LEVELS or None


@dataclass
class FnSummary:
    blocking: "list[tuple[int, str]]" = field(default_factory=list)
    acquires: "list[tuple[LockKey, int]]" = field(default_factory=list)
    callees: "list[tuple[FunctionInfo, int]]" = field(default_factory=list)


def _expr_src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "<expr>"


def _lock_key_for(ctx: ast.expr, fn: FunctionInfo, cond_assoc) -> "LockKey | None":
    """LockKey for a with-context expression, or None when it is not a
    recognized state lock (or is an exempt cycle lock)."""
    if isinstance(ctx, ast.Attribute) and isinstance(ctx.value, ast.Name):
        if ctx.attr in EXEMPT_LOCK_NAMES:
            return None
        if ctx.value.id == "self" and ctx.attr in LOCK_ATTRS:
            owner = fn.cls.name if fn.cls else fn.module.relpath
            level = CLASS_LEVELS.get(owner) or MODULE_LEVELS.get(
                fn.module.relpath
            )
            return LockKey(owner, ctx.attr, level)
    if isinstance(ctx, ast.Name):
        if ctx.id in EXEMPT_LOCK_NAMES:
            return None
        if ctx.id.endswith("lock") or ctx.id.endswith("cond"):
            return LockKey(fn.module.relpath, ctx.id, None)
    return None


def _condition_assoc(graph: CallGraph) -> "dict[tuple[str, str], str]":
    """(class, cond_attr) -> lock_attr for ``self.c = threading.Condition
    (self.l)`` wirings: waiting on ``c`` releases ``l``, so it is safe
    while holding ``l``."""
    assoc: "dict[tuple[str, str], str]" = {}
    for classes in graph.classes_by_name.values():
        for ci in classes:
            for fi in ci.methods.values():
                for node in walk_cached(fi.node):
                    if not (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "Condition"
                        and node.value.args
                        and isinstance(node.value.args[0], ast.Attribute)
                        and isinstance(node.value.args[0].value, ast.Name)
                        and node.value.args[0].value.id == "self"
                    ):
                        continue
                    assoc[(ci.name, node.targets[0].attr)] = (
                        node.value.args[0].attr
                    )
    return assoc


def _blocking_reason(
    call: ast.Call,
    fn: FunctionInfo,
    held: "set[str]",
    cond_assoc,
) -> "str | None":
    """Why this call blocks, or None. ``held`` is the set of attr names of
    locks held in the current region (for condvar-self-wait exemption)."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "sleep":
            return "sleep() while holding the lock"
        if func.id == "interruptible_sleep":
            return "interruptible_sleep() while holding the lock"
        if func.id == "Popen":
            return "subprocess while holding the lock"
        return None
    if isinstance(func, ast.Call):
        # interruptible_sleep(ev)(delay) — a call of a call
        if (
            isinstance(func.func, ast.Name)
            and func.func.id == "interruptible_sleep"
        ):
            return "interruptible_sleep() while holding the lock"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    recv, attr = func.value, func.attr
    recv_name = recv.id if isinstance(recv, ast.Name) else None
    if attr == "sleep" and recv_name == "time":
        return "time.sleep while holding the lock"
    if recv_name == "subprocess" and attr in SUBPROCESS_FNS:
        return f"subprocess.{attr} while holding the lock"
    if attr in HTTP_FNS:
        return f"socket/HTTP call .{attr}() while holding the lock"
    if attr in CLUSTER_IO:
        return (
            f"cluster API call .{attr}() (network round-trip on a real "
            "backend) while holding the lock"
        )
    if attr == "wait":
        # Waiting on the held lock's own condition releases it: safe.
        if isinstance(recv, ast.Attribute) and isinstance(
            recv.value, ast.Name
        ) and recv.value.id == "self":
            if recv.attr in held:
                return None
            if fn.cls is not None and cond_assoc.get(
                (fn.cls.name, recv.attr)
            ) in held:
                return None
        return f"blocking wait on {_expr_src(recv)} while holding the lock"
    if attr == "acquire":
        return None  # handled as an acquisition by the ordering check
    if attr == "get" and any(
        kw.arg in ("block", "timeout") for kw in call.keywords
    ):
        return "blocking queue get while holding the lock"
    if attr == "join" and not isinstance(recv, ast.Constant):
        # str.join is ubiquitous; flag joins on self-attributes that an
        # __init__ typed as threads, nothing else.
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and fn.cls is not None
            and "Thread" in fn.cls.attr_types.get(recv.attr, "")
        ):
            return "thread join while holding the lock"
    return None


def _summaries(
    graph: CallGraph, cond_assoc
) -> "dict[str, FnSummary]":
    out: "dict[str, FnSummary]" = {}
    for qual, fn in graph.functions.items():
        s = FnSummary()
        for node in walk_cached(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    key = _lock_key_for(
                        item.context_expr, fn, cond_assoc
                    )
                    if key is not None:
                        s.acquires.append((key, node.lineno))
        for call in graph.calls_in(fn):
            reason = _blocking_reason(call, fn, set(), cond_assoc)
            if reason is not None:
                s.blocking.append((call.lineno, reason))
            for callee in graph.resolve_call(call, fn):
                s.callees.append((callee, call.lineno))
        out[qual] = s
    return out


def _walk_region(body: "list[ast.stmt]"):
    """Yield nodes in a with-region, not descending into nested defs."""
    stack: list = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def run(project: Project, graph: "CallGraph | None" = None) -> "list[Finding]":
    graph = graph or CallGraph(project)
    cond_assoc = _condition_assoc(graph)
    summaries = _summaries(graph, cond_assoc)
    findings: "list[Finding]" = []

    def reachable(
        fn: FunctionInfo, *, want: str, seen: "set[str]"
    ) -> "list[tuple[str, str]]":
        """(description, via-chain) for blocking calls / acquisitions
        reachable from ``fn`` inclusive. ``want`` is 'blocking' or
        'acquires'."""
        if fn.qualname in seen:
            return []
        seen.add(fn.qualname)
        s = summaries.get(fn.qualname)
        if s is None:
            return []
        hits: "list[tuple[str, str]]" = []
        if want == "blocking":
            for _line, why in s.blocking:
                hits.append((why, fn.qualname))
        else:
            for key, _line in s.acquires:
                hits.append((key, fn.qualname))  # type: ignore[arg-type]
        for callee, _line in s.callees:
            for why, via in reachable(callee, want=want, seen=seen):
                hits.append((why, via))
        return hits

    for mod in project.modules:
        if "/testing/" in mod.relpath or mod.relpath.endswith("demo.py"):
            continue
        for fn in [
            f for f in graph.functions.values() if f.module is mod
        ]:
            for node in walk_cached(fn.node):
                if not isinstance(node, ast.With):
                    continue
                keys = [
                    _lock_key_for(item.context_expr, fn, cond_assoc)
                    for item in node.items
                ]
                keys = [k for k in keys if k is not None]
                if not keys:
                    continue
                held_attrs = {k.attr for k in keys}
                for sub in _walk_region(node.body):
                    if not isinstance(sub, ast.Call):
                        continue
                    # Direct blocking call in the region.
                    why = _blocking_reason(sub, fn, held_attrs, cond_assoc)
                    if why is not None:
                        findings.append(
                            Finding(
                                NAME,
                                mod.relpath,
                                sub.lineno,
                                f"{why} ({keys[0].owner}.{keys[0].attr} "
                                f"held since line {node.lineno})",
                            )
                        )
                    for callee in graph.resolve_call(sub, fn):
                        # Transitive blocking.
                        for why2, via in reachable(
                            callee, want="blocking", seen=set()
                        ):
                            findings.append(
                                Finding(
                                    NAME,
                                    mod.relpath,
                                    sub.lineno,
                                    f"{why2} — reached via {via} while "
                                    f"{keys[0].owner}.{keys[0].attr} is "
                                    f"held (line {node.lineno})",
                                )
                            )
                        # Transitive ordering violations.
                        for key2, via in reachable(
                            callee, want="acquires", seen=set()
                        ):
                            _check_order(
                                findings, mod, sub.lineno, keys, key2, via
                            )
                    # Direct nested with handled when the walker reaches
                    # it as its own With node below (ordering only).
                # Nested with-stmts inside this region: ordering check.
                for sub in _walk_region(node.body):
                    if not isinstance(sub, ast.With):
                        continue
                    for item in sub.items:
                        key2 = _lock_key_for(
                            item.context_expr, fn, cond_assoc
                        )
                        if key2 is not None:
                            _check_order(
                                findings,
                                mod,
                                sub.lineno,
                                keys,
                                key2,
                                fn.qualname,
                            )
    # De-duplicate (the same reachable hit can surface through several
    # call expressions on one line).
    return sorted(set(findings), key=lambda f: (f.file, f.line, f.message))


def _check_order(findings, mod, line, held_keys, acquired, via) -> None:
    if not isinstance(acquired, LockKey) or acquired.level is None:
        return
    for held in held_keys:
        if held.level is None:
            continue
        if held.owner == acquired.owner:
            continue  # re-entry on the same component (RLocks)
        if LOCK_LEVELS[acquired.level] < LOCK_LEVELS[held.level]:
            findings.append(
                Finding(
                    NAME,
                    mod.relpath,
                    line,
                    f"lock-order violation: acquiring {acquired.level} "
                    f"lock ({acquired.owner}.{acquired.attr}, via {via}) "
                    f"while holding {held.level} lock ({held.owner}."
                    f"{held.attr}) — declared order is speculation -> "
                    "informer -> queue -> accountant -> gang -> metrics",
                )
            )
