"""Pass 6 — metrics-drift (migrated from tools/check_metrics.py,
ISSUE 9): every ``yoda_*`` series registered anywhere in the package
must be asserted in tests/test_observability.py and documented in
docs/OPERATIONS.md.

New metrics silently skipping the test suite or the operator docs is how
observability rots: the series exists, nobody knows what it means, and a
rename breaks dashboards without failing CI.

Registration sites are found syntactically — the first string argument
of ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` calls (the
Registry surface in yoda_tpu/observability.py) — so a metric cannot hide
behind an accumulator pattern or a lazily-attached family.
"""

from __future__ import annotations

import re

from tools.yodalint.core import Finding, Project

NAME = "metrics-drift"

REGISTRATION = re.compile(
    r'\b(?:counter|gauge|histogram)\(\s*["\'](yoda_[a-z0-9_]+)["\']'
)


def registered_names(project: Project) -> "dict[str, tuple[str, int]]":
    """Every registered ``yoda_*`` series -> (file, line) of its first
    registration site. Also consumed by tests/test_observability.py's
    pinned-list check."""
    names: "dict[str, tuple[str, int]]" = {}
    for mod in project.modules:
        for m in REGISTRATION.finditer(mod.text):
            line = mod.text.count("\n", 0, m.start()) + 1
            names.setdefault(m.group(1), (mod.relpath, line))
    return names


def run(project: Project, graph=None) -> "list[Finding]":
    findings: "list[Finding]" = []
    names = registered_names(project)
    if not names:
        return [
            Finding(
                NAME,
                f"{project.package}/observability.py",
                1,
                "found no registered yoda_* series — the registration "
                "regex no longer matches the code; re-pin this pass",
            )
        ]
    test_text = project.read_text(project.observability_test) or ""
    docs_text = project.read_text(project.operations_md) or ""
    for name in sorted(names):
        rel, line = names[name]
        if name not in test_text:
            findings.append(
                Finding(
                    NAME,
                    rel,
                    line,
                    f"metric {name} is not asserted in "
                    "tests/test_observability.py",
                )
            )
        if name not in docs_text:
            findings.append(
                Finding(
                    NAME,
                    rel,
                    line,
                    f"metric {name} is not documented in "
                    "docs/OPERATIONS.md",
                )
            )
    return findings
