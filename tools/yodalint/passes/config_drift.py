"""Pass 4 — config-drift: every SchedulerConfig knob is validated,
shipped in the deploy ConfigMap, and documented in OPERATIONS.md — and
vice versa (no ghost keys, no ghost docs).

A knob that exists in code but not in the ConfigMap is invisible to
operators; one documented but gone from code is a lie that breaks the
next deploy. The four checks:

1. **validated** — the knob's name appears in ``SchedulerConfig.
   from_dict``'s validation body (the file's convention: every knob is
   range/type-checked there with its name in the error message).
   ``weights`` / ``slo_targets`` members are validated as families by
   their own ``from_dict`` and are exempt per-name.
2. **shipped** — the knob appears as a key (commented examples count:
   a ``# knob: value`` line ships the recipe) in the scheduler
   ConfigMap's ``config.yaml`` block.
3. **documented** — the knob appears backticked in docs/OPERATIONS.md.
4. **no ghosts** — every ConfigMap key and every knob-shaped
   backticked token heading a Tuning-section bullet resolves to a real
   SchedulerConfig / Weights / SloTargets field.
"""

from __future__ import annotations

import ast
import re

from tools.yodalint.core import Finding, Project

NAME = "config-drift"

#: ``knob:`` or ``# knob:`` or ``#   - knob:`` inside the config block.
_KEY_RE = re.compile(r"^\s*#?\s*(?:-\s*)?([a-z_][a-z0-9_]*):")
#: Backticked lowercase tokens heading a Tuning bullet.
_DOC_HEAD_RE = re.compile(r"`([a-z_][a-z0-9_.]*)`")


def _dataclass_fields(mod, class_name: str) -> "dict[str, int]":
    """Annotated field name -> line for a dataclass in ``mod``."""
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return {
                item.target.id: item.lineno
                for item in node.body
                if isinstance(item, ast.AnnAssign)
                and isinstance(item.target, ast.Name)
                and not item.target.id.startswith("_")
            }
    return {}


def _method_source(mod, class_name: str, method: str) -> str:
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            for item in node.body:
                if (
                    isinstance(item, ast.FunctionDef)
                    and item.name == method
                ):
                    return ast.get_source_segment(mod.text, item) or ""
    return ""


def _configmap_block(text: str) -> "tuple[list[tuple[int, str]], bool]":
    """(line, key) pairs inside the ``config.yaml: |`` block."""
    keys: "list[tuple[int, str]]" = []
    inside = False
    found = False
    for i, line in enumerate(text.splitlines(), start=1):
        if re.match(r"^\s*config\.yaml:\s*\|", line):
            inside = True
            found = True
            continue
        if inside and (line.startswith("---") or re.match(r"^\S", line)):
            inside = False
        if inside:
            m = _KEY_RE.match(line)
            if m:
                keys.append((i, m.group(1)))
    return keys, found


def run(project: Project, graph=None) -> "list[Finding]":
    findings: "list[Finding]" = []
    cfg_mod = project.module("config.py")
    if cfg_mod is None:
        return [Finding(NAME, "yoda_tpu/config.py", 1, "config.py missing")]
    knobs = _dataclass_fields(cfg_mod, "SchedulerConfig")
    weight_fields = set(_dataclass_fields(cfg_mod, "Weights"))
    slo_mod = project.module("slo/engine.py")
    slo_fields = (
        set(_dataclass_fields(slo_mod, "SloTargets")) if slo_mod else set()
    )
    from_dict_src = _method_source(cfg_mod, "SchedulerConfig", "from_dict")

    # 1. validated ---------------------------------------------------
    family_validated = {"weights", "slo_targets", "profiles"}
    for knob, line in knobs.items():
        if knob in family_validated:
            continue  # validated by their own from_dict / recursion
        if not re.search(rf"\b{re.escape(knob)}\b", from_dict_src):
            findings.append(
                Finding(
                    NAME,
                    cfg_mod.relpath,
                    line,
                    f"knob {knob!r} is never validated in "
                    "SchedulerConfig.from_dict — add a type/range check "
                    "(every knob is checked there by convention)",
                )
            )

    # 2./4a. shipped + ghost ConfigMap keys --------------------------
    cm_text = project.read_text(project.configmap_yaml)
    if cm_text is None:
        findings.append(
            Finding(
                NAME,
                "deploy/yoda-tpu-scheduler.yaml",
                1,
                "scheduler ConfigMap missing",
            )
        )
    else:
        cm_rel = str(
            project.configmap_yaml.relative_to(project.root)
        )
        keys, block_found = _configmap_block(cm_text)
        if not block_found:
            findings.append(
                Finding(
                    NAME, cm_rel, 1, "no config.yaml block in ConfigMap"
                )
            )
        key_names = {k for _, k in keys}
        for knob, line in knobs.items():
            if knob not in key_names:
                findings.append(
                    Finding(
                        NAME,
                        cfg_mod.relpath,
                        line,
                        f"knob {knob!r} is not shipped in the deploy "
                        "ConfigMap (deploy/yoda-tpu-scheduler.yaml) — "
                        "add it, commented with its default if it is "
                        "not part of the default deployment",
                    )
                )
        known = set(knobs) | weight_fields | slo_fields
        for line, key in keys:
            if key not in known:
                findings.append(
                    Finding(
                        NAME,
                        cm_rel,
                        line,
                        f"ConfigMap key {key!r} is not a SchedulerConfig"
                        "/Weights/SloTargets field — ghost config",
                    )
                )

    # 3./4b. documented + ghost docs ---------------------------------
    ops_text = project.read_text(project.operations_md)
    if ops_text is None:
        findings.append(
            Finding(NAME, "docs/OPERATIONS.md", 1, "OPERATIONS.md missing")
        )
        return findings
    for knob, line in knobs.items():
        # `knob` or `knob:` (the docs write mapping-valued knobs with the
        # trailing colon, e.g. `profiles:`).
        if not re.search(rf"`{re.escape(knob)}:?`", ops_text):
            findings.append(
                Finding(
                    NAME,
                    cfg_mod.relpath,
                    line,
                    f"knob {knob!r} is not documented in "
                    "docs/OPERATIONS.md (Tuning section) — every knob "
                    "gets an operator-facing bullet",
                )
            )
    # Ghost docs: bullet-head tokens in the Tuning section.
    lines = ops_text.splitlines()
    try:
        start = next(
            i for i, l in enumerate(lines) if l.startswith("## Tuning")
        )
    except StopIteration:
        return findings
    end = next(
        (
            i
            for i in range(start + 1, len(lines))
            if lines[i].startswith("## ")
        ),
        len(lines),
    )
    known = set(knobs) | slo_fields
    for i in range(start, end):
        line = lines[i]
        if not line.startswith("- "):
            continue
        head = line.split("—")[0]
        if "--" in head:
            continue  # agent CLI flags, not config knobs
        for tok in _DOC_HEAD_RE.findall(head):
            parts = tok.split(".")
            ok = (
                parts[0] in known
                if len(parts) == 1
                else (
                    parts[0] == "weights" and parts[1] in weight_fields
                )
            )
            if not ok:
                findings.append(
                    Finding(
                        NAME,
                        "docs/OPERATIONS.md",
                        i + 1,
                        f"Tuning bullet documents {tok!r} which is not "
                        "a SchedulerConfig/Weights/SloTargets field — "
                        "ghost documentation",
                    )
                )
    return findings
