"""Pass 7 — verdict-taxonomy (migrated from the ISSUE 12 checker test):
every why-pending park site records a verdict class from the documented
taxonomy, every class is actually recorded somewhere, and every class is
documented in OPERATIONS.md.

The why-pending index is only explainable if its ``kind`` vocabulary is
closed: a park site shipping an unexplained verdict class gives the
operator a word the runbook has never seen. The taxonomy lives in
``tracing.VERDICT_CLASSES``; the one dynamic-kind site (the scheduler's
cycle-outcome passthrough) is pinned to the documented outcome subset by
a source guard this pass re-checks.

tests/test_yodalint.py drives this pass against planted fixtures and the
live tree; tests/test_tracing.py keeps the *runtime* half (driving real
park sites end-to-end) — one taxonomy, two enforcement layers.
"""

from __future__ import annotations

import ast

from tools.yodalint.core import Finding, Project, walk_cached

NAME = "verdict-taxonomy"

#: The dynamic-kind site's pinned guard (framework/scheduler.py): only
#: the documented outcome subset reaches ``pending.record(kind=<var>)``.
DYNAMIC_OK_FILES = {"framework/scheduler.py"}
DYNAMIC_GUARD = 'in ("unschedulable", "error", "nominated")'
DYNAMIC_KINDS = {"unschedulable", "error", "nominated"}


def _verdict_classes(project: Project) -> "tuple[set[str], str, int]":
    mod = project.module("tracing.py")
    if mod is None:
        return set(), "yoda_tpu/tracing.py", 1
    for node in mod.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "VERDICT_CLASSES"
        ):
            classes = {
                c.value
                for c in ast.walk(node.value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            }
            return classes, mod.relpath, node.lineno
    return set(), mod.relpath, 1


def run(project: Project, graph=None) -> "list[Finding]":
    findings: "list[Finding]" = []
    classes, classes_file, classes_line = _verdict_classes(project)
    if not classes:
        return [
            Finding(
                NAME,
                classes_file,
                classes_line,
                "tracing.VERDICT_CLASSES not found — the taxonomy "
                "anchor moved; re-pin this pass",
            )
        ]
    recorded: "set[str]" = set()
    sites = 0
    for mod in project.modules:
        for node in walk_cached(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "record"
            ):
                continue
            for kw in node.keywords:
                if kw.arg != "kind":
                    continue
                sites += 1
                if isinstance(kw.value, ast.Constant):
                    literal = kw.value.value
                    recorded.add(literal)
                    if literal not in classes:
                        findings.append(
                            Finding(
                                NAME,
                                mod.relpath,
                                node.lineno,
                                f"verdict class {literal!r} is not in "
                                "tracing.VERDICT_CLASSES — document it "
                                "there (and in OPERATIONS.md) or use an "
                                "existing class",
                            )
                        )
                elif not any(
                    mod.relpath.endswith(f) for f in DYNAMIC_OK_FILES
                ):
                    findings.append(
                        Finding(
                            NAME,
                            mod.relpath,
                            node.lineno,
                            "pending.record with a non-literal kind — "
                            "use a VERDICT_CLASSES literal (only the "
                            "scheduler's pinned outcome passthrough may "
                            "pass a variable)",
                        )
                    )
    if not sites:
        findings.append(
            Finding(
                NAME,
                classes_file,
                classes_line,
                "found no pending.record(kind=...) sites — the checker "
                "no longer matches the code; re-pin this pass",
            )
        )
        return findings
    # The dynamic site's guard must still pin its domain.
    sched = project.module("framework/scheduler.py")
    if sched is not None and DYNAMIC_GUARD not in sched.text:
        findings.append(
            Finding(
                NAME,
                sched.relpath,
                1,
                "the scheduler's dynamic-kind guard "
                f"({DYNAMIC_GUARD}) changed — re-pin the taxonomy",
            )
        )
    recorded |= DYNAMIC_KINDS
    for unused in sorted(classes - recorded):
        findings.append(
            Finding(
                NAME,
                classes_file,
                classes_line,
                f"verdict class {unused!r} is documented in "
                "VERDICT_CLASSES but recorded nowhere — dead taxonomy",
            )
        )
    ops_text = project.read_text(project.operations_md) or ""
    for cls in sorted(classes):
        if f"`{cls}`" not in ops_text:
            findings.append(
                Finding(
                    NAME,
                    classes_file,
                    classes_line,
                    f"verdict class {cls!r} is not documented in "
                    "docs/OPERATIONS.md",
                )
            )
    return findings
