"""Pass 8 — reload-safety (ISSUE 15): the hot-reload classification in
``yoda_tpu/config.py`` must be coherent, and every knob declared
RELOADABLE must actually be live.

A knob declared reloadable but captured into a serve-path local/attr at
build time is the worst kind of lie: the operator SIGHUPs a new value,
the reloader reports it applied, and the old value keeps serving. Four
checks:

1. **classification is real** — every name in ``RELOADABLE_KNOBS`` /
   ``RESIZE_KNOBS`` / ``IMMUTABLE_KNOBS`` is a ``SchedulerConfig``
   field, and the sets are pairwise disjoint (one knob, one class).
2. **reloadable knobs are re-applied** — each ``RELOADABLE_KNOBS`` name
   is read off the config object inside
   ``standalone.apply_reloadable`` (THE apply site the ConfigReloader
   drives); a declared-reloadable knob missing there would never reach
   its consumer on reload.
3. **nothing undeclared applies live** — a ``config.<knob>`` read in
   ``apply_reloadable`` whose knob is NOT declared reloadable is drift
   in the other direction (live semantics nobody classified).
4. **no build-time capture** — outside the assembly/reload layer
   (config.py, overload.py, standalone.py, cli.py, testing/), no module
   may read ``config.<knob>`` / ``cfg.<knob>`` for a reloadable knob:
   consumers must hold the live attribute the apply site writes, never
   a boot-time copy.
"""

from __future__ import annotations

import ast

from tools.yodalint.core import Finding, Project, walk_cached
from tools.yodalint.passes.config_drift import _dataclass_fields

NAME = "reload-safety"

#: Modules allowed to read reloadable knobs off a config object: the
#: assembly seeds initial values (re-applied on reload), the reload
#: layer applies them, and the testing harness builds configs freely.
ALLOWED_SUFFIXES = (
    "config.py",
    "overload.py",
    "standalone.py",
    "cli.py",
)
ALLOWED_DIRS = ("/testing/",)

_SET_NAMES = ("RELOADABLE_KNOBS", "RESIZE_KNOBS", "IMMUTABLE_KNOBS")


def _knob_sets(mod) -> "dict[str, tuple[set[str], int]]":
    """{set name: (names, line)} for the classification frozensets."""
    out: dict[str, tuple[set[str], int]] = {}
    for node in walk_cached(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Name)
                and target.id in _SET_NAMES
            ):
                names: set[str] = set()
                for const in ast.walk(node.value):
                    if isinstance(const, ast.Constant) and isinstance(
                        const.value, str
                    ):
                        names.add(const.value)
                out[target.id] = (names, node.lineno)
    return out


def _apply_fn(mod) -> "ast.FunctionDef | None":
    for node in mod.tree.body:
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == "apply_reloadable"
        ):
            return node
    return None


def _config_attr_reads(tree) -> "dict[str, int]":
    """Attribute names read off a variable named config/cfg -> first line."""
    reads: dict[str, int] = {}
    for node in walk_cached(tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("config", "cfg")
        ):
            reads.setdefault(node.attr, node.lineno)
    return reads


def run(project: Project, graph=None) -> "list[Finding]":
    findings: "list[Finding]" = []
    cfg_mod = project.module("config.py")
    if cfg_mod is None:
        return [Finding(NAME, "yoda_tpu/config.py", 1, "config.py missing")]
    knobs = set(_dataclass_fields(cfg_mod, "SchedulerConfig"))
    sets = _knob_sets(cfg_mod)
    for set_name in _SET_NAMES:
        if set_name not in sets:
            findings.append(
                Finding(
                    NAME,
                    cfg_mod.relpath,
                    1,
                    f"{set_name} not found in config.py — the hot-reload "
                    "classification sets are required",
                )
            )
    if any(s not in sets for s in _SET_NAMES):
        return findings
    # 1. real fields + disjoint.
    for set_name, (names, line) in sets.items():
        for name in sorted(names - knobs):
            findings.append(
                Finding(
                    NAME,
                    cfg_mod.relpath,
                    line,
                    f"{set_name} names {name!r} which is not a "
                    "SchedulerConfig field — ghost classification",
                )
            )
    for i, a in enumerate(_SET_NAMES):
        for b in _SET_NAMES[i + 1:]:
            overlap = sets[a][0] & sets[b][0]
            for name in sorted(overlap):
                findings.append(
                    Finding(
                        NAME,
                        cfg_mod.relpath,
                        sets[b][1],
                        f"knob {name!r} is classified in both {a} and "
                        f"{b} — one knob, one reload class",
                    )
                )
    reloadable = sets["RELOADABLE_KNOBS"][0] & knobs

    # 2./3. the apply site.
    sa_mod = project.module("standalone.py")
    apply_node = _apply_fn(sa_mod) if sa_mod is not None else None
    if apply_node is None:
        findings.append(
            Finding(
                NAME,
                "yoda_tpu/standalone.py",
                1,
                "standalone.apply_reloadable not found — the hot-reload "
                "apply site is required",
            )
        )
        return findings
    applied = _config_attr_reads(apply_node)
    for knob in sorted(reloadable - set(applied)):
        findings.append(
            Finding(
                NAME,
                sa_mod.relpath,
                apply_node.lineno,
                f"knob {knob!r} is declared RELOADABLE but never "
                "re-applied in apply_reloadable — a reload would report "
                "it applied while the old value keeps serving",
            )
        )
    for knob, line in sorted(applied.items()):
        if knob in knobs and knob not in reloadable:
            findings.append(
                Finding(
                    NAME,
                    sa_mod.relpath,
                    line,
                    f"apply_reloadable applies {knob!r} live but it is "
                    "not in RELOADABLE_KNOBS — classify it",
                )
            )

    # 4. no build-time capture outside the assembly/reload layer.
    for mod in project.modules:
        rel = mod.relpath.replace("\\", "/")
        if rel.endswith(ALLOWED_SUFFIXES) or any(
            d in rel for d in ALLOWED_DIRS
        ):
            continue
        for knob, line in _config_attr_reads(mod.tree).items():
            if knob in reloadable:
                findings.append(
                    Finding(
                        NAME,
                        mod.relpath,
                        line,
                        f"reloadable knob {knob!r} read off a config "
                        "object outside the assembly/reload layer — a "
                        "build-time capture a hot-reload cannot reach; "
                        "consume it through the live attribute "
                        "apply_reloadable writes",
                    )
                )
    return findings
