"""Pass 9 — speculation-safety: speculative plan consumption is always
behind the full validity chain, and the informer never reaches into the
cache.

The sub-millisecond serve fast path (ISSUE 17,
framework/speculation.py) binds a pod from a plan computed BETWEEN serve
cycles. Its whole safety argument is the consume-time chain — leader
fence, per-plan epoch check against both informer delta feeds, O(1)
staged-claim spot check — so a call site that consumes a plan without
the chain is a stale-bind (or split-brain bind) waiting for fleet churn
to expose it. Two rules:

**A. Guarded consumption.** Every ``.consume_plan(...)`` call site
outside the cache's own module must be dominated, within the enclosing
function, by BOTH a leader-fence read (the fence-before-write marker
set: ``_fenced`` / ``fence_fn`` / ...) and an epoch-validity read
(``epoch_valid``). The revalidate spot check is deliberately NOT a
marker: it is advisory ranking hygiene, while the fence and the epoch
feeds are the correctness half — and requiring exactly the load-bearing
pair keeps the rule enforceable without taint analysis.

**B. Pull-only invalidation.** ``cluster/informer.py`` must not call
speculation-cache methods (on any receiver whose spelling mentions
``spec``). Invalidation is pull-based off the delta feeds by design: an
informer→cache callback would run under the informer lock and acquire
the speculation lock BELOW it, inverting the lock DAG the
lock-discipline pass declares (speculation -> informer -> ...).
"""

from __future__ import annotations

import ast

from tools.yodalint.callgraph import CallGraph, FunctionInfo
from tools.yodalint.core import Finding, Project, walk_cached

NAME = "speculation-safety"

#: Same marker set as fence-before-write: evidence the enclosing function
#: checked leadership before the consume.
FENCE_MARKERS = {"_fenced", "fenced_fn", "fence_fn", "gate_fn", "is_leader"}

#: Evidence the plan's epochs were checked against the delta feeds.
EPOCH_MARKERS = {"epoch_valid"}

#: The cache's mutating/consuming surface, for Rule B.
SPEC_METHODS = {
    "lookup",
    "consume_plan",
    "reserve_rejected",
    "speculate_once",
    "sweep",
    "flush",
    "configure",
    "_invalidate",
}

#: The module that defines the cache: its internal consume logic is the
#: mechanism, not a call site.
DEFINING_SUFFIX = "framework/speculation.py"


def _marker_lines(fn: FunctionInfo, markers: "set[str]") -> "list[int]":
    lines = []
    for node in walk_cached(fn.node):
        if isinstance(node, ast.Attribute) and node.attr in markers:
            lines.append(node.lineno)
        elif isinstance(node, ast.Name) and node.id in markers:
            lines.append(node.lineno)
    return lines


def _receiver_mentions_spec(func: ast.Attribute) -> bool:
    parts: "list[str]" = []
    node: ast.expr = func.value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return any("spec" in p for p in parts)


def run(project: Project, graph: "CallGraph | None" = None) -> "list[Finding]":
    graph = graph or CallGraph(project)
    findings: "list[Finding]" = []
    for fn in graph.functions.values():
        rel = fn.module.relpath
        if rel.endswith(DEFINING_SUFFIX) or "/testing/" in rel:
            continue
        fence_lines = None  # computed lazily: most functions never consume
        epoch_lines = None
        for call in graph.calls_in(fn):
            func = call.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr == "consume_plan"
            ):
                continue
            if fence_lines is None:
                fence_lines = _marker_lines(fn, FENCE_MARKERS)
                epoch_lines = _marker_lines(fn, EPOCH_MARKERS)
            if not any(line <= call.lineno for line in fence_lines):
                findings.append(
                    Finding(
                        NAME,
                        rel,
                        call.lineno,
                        "speculative .consume_plan() with no leader-fence "
                        "check dominating it (no _fenced/fenced_fn/"
                        "fence_fn/gate_fn read before this line in "
                        f"{fn.qualname.split('::')[-1]}) — a fenced "
                        "ex-leader could bind a speculated placement",
                    )
                )
            if not any(line <= call.lineno for line in epoch_lines):
                findings.append(
                    Finding(
                        NAME,
                        rel,
                        call.lineno,
                        "speculative .consume_plan() with no epoch_valid "
                        "check dominating it in "
                        f"{fn.qualname.split('::')[-1]} — a plan stale "
                        "against the informer delta feeds could bind",
                    )
                )
    informer = project.module("cluster/informer.py")
    if informer is not None:
        for node in walk_cached(informer.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in SPEC_METHODS
                and _receiver_mentions_spec(node.func)
            ):
                continue
            findings.append(
                Finding(
                    NAME,
                    informer.relpath,
                    node.lineno,
                    f"informer calls speculation cache method "
                    f".{node.func.attr}() — invalidation is pull-based "
                    "off the delta feeds; an informer-side callback "
                    "acquires the speculation lock under the informer "
                    "lock, inverting the declared lock DAG",
                )
            )
    return findings
