"""yodalint pass registry. Each pass exports NAME and run(project)."""

from __future__ import annotations

from tools.yodalint.passes import (
    config_drift,
    fence_before_write,
    hook_order,
    journal_discipline,
    lock_discipline,
    metrics_drift,
    reload_safety,
    snapshot_immutability,
    speculation_safety,
    verdict_taxonomy,
)

#: Registration order is report order; names are the suppression keys.
ALL_PASSES = (
    lock_discipline,
    fence_before_write,
    snapshot_immutability,
    config_drift,
    hook_order,
    metrics_drift,
    verdict_taxonomy,
    reload_safety,
    speculation_safety,
    journal_discipline,
)

PASS_NAMES = {p.NAME for p in ALL_PASSES}
