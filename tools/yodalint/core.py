"""yodalint core: project loader, findings, suppression syntax, reporter.

The shared infrastructure under the seven project-invariant passes
(ISSUE 13). A pass is a function ``run(project) -> list[Finding]``
registered in :mod:`tools.yodalint.passes`; this module owns everything
the passes share:

- **Project** — one parse of the tree. Every ``yoda_tpu/**/*.py`` module
  is read and AST-parsed once (``Module``), and the handful of non-code
  surfaces the drift passes cross-check (docs/OPERATIONS.md, the deploy
  ConfigMap, tests/test_observability.py) are exposed as paths so passes
  never invent their own file discovery.
- **Suppression** — ``# yodalint: ok <pass> <reason>`` on the flagged
  line (or the line directly above it) silences that pass for that line.
  The reason is REQUIRED: a bare ``# yodalint: ok lock-discipline`` is
  itself reported as a finding, as is a suppression naming an unknown
  pass — an annotation that cannot say why it exists is drift waiting to
  happen.
- **Reporter** — ``file:line: [pass] message`` on stderr, sorted, stable.

Passes must be fast (the whole suite gates ``make lint`` at < 5 s) and
silent on a clean tree: zero findings is the contract tier-1 pins
(tests/test_yodalint.py runs every pass against the live tree).
"""

from __future__ import annotations

import ast
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path

#: ``# yodalint: ok <pass-name> <reason...>`` (reason validated separately
#: so a missing one can be reported with a precise message).
SUPPRESS_RE = re.compile(r"#\s*yodalint:\s*ok\b\s*(\S+)?[ \t]*(.*)$")


@dataclass(frozen=True)
class Finding:
    """One violation: where, which pass, and what went wrong."""

    pass_name: str
    file: str  # repo-relative path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.pass_name}] {self.message}"


@dataclass
class Suppression:
    pass_name: str | None  # None = malformed (no pass name at all)
    reason: str
    line: int
    used: bool = False


def walk_cached(node: ast.AST) -> "list[ast.AST]":
    """``list(ast.walk(node))``, memoized on the node. Passes re-walk the
    same (immutable) subtrees — module roots, function bodies — many
    times per run; the first walk pays, the rest iterate a list. Keeps
    the whole suite inside the lint budget as passes accumulate."""
    cached = getattr(node, "_yl_walk", None)
    if cached is None:
        cached = list(ast.walk(node))
        node._yl_walk = cached
    return cached


class Module:
    """One parsed source file: text, line list, and AST."""

    def __init__(self, path: Path, relpath: str) -> None:
        self.path = path
        self.relpath = relpath
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=relpath)
        self.suppressions: list[Suppression] = []
        for i, line in enumerate(self.lines, start=1):
            # Only comment text counts — a string literal mentioning the
            # marker (docs, this file) must not create suppressions.
            hash_pos = line.find("#")
            if hash_pos < 0:
                continue
            m = SUPPRESS_RE.search(line, hash_pos)
            if m:
                self.suppressions.append(
                    Suppression(
                        pass_name=m.group(1),
                        reason=(m.group(2) or "").strip(),
                        line=i,
                    )
                )

    def suppressed(self, pass_name: str, line: int) -> bool:
        """True when ``line`` (or the line above it) carries a well-formed
        suppression for ``pass_name``. Marks the suppression used."""
        for s in self.suppressions:
            if (
                s.pass_name == pass_name
                and s.reason
                and s.line in (line, line - 1)
            ):
                s.used = True
                return True
        return False


class Project:
    """The analysis root: the package's parsed modules plus the non-code
    surfaces the drift passes check against. ``root`` is the repo root;
    fixtures (tests/test_yodalint.py) point it at a temp tree with the
    same shape."""

    def __init__(self, root: "Path | str", package: str = "yoda_tpu") -> None:
        self.root = Path(root)
        self.package = package
        self.modules: list[Module] = []
        pkg_dir = self.root / package
        for path in sorted(pkg_dir.rglob("*.py")):
            rel = str(path.relative_to(self.root))
            self.modules.append(Module(path, rel))
        # Extra single files some passes read (present-or-not is the
        # pass's problem to report, not the loader's).
        self.operations_md = self.root / "docs" / "OPERATIONS.md"
        self.configmap_yaml = self.root / "deploy" / "yoda-tpu-scheduler.yaml"
        self.observability_test = (
            self.root / "tests" / "test_observability.py"
        )

    def module(self, relpath_suffix: str) -> "Module | None":
        """The unique module whose relpath ends with ``relpath_suffix``."""
        for m in self.modules:
            if m.relpath.endswith(relpath_suffix):
                return m
        return None

    def read_text(self, path: Path) -> "str | None":
        try:
            return path.read_text()
        except OSError:
            return None


@dataclass
class PassResult:
    name: str
    findings: list[Finding] = field(default_factory=list)


def apply_suppressions(
    project: Project, findings: "list[Finding]", known_passes: "set[str]"
) -> "list[Finding]":
    """Drop suppressed findings, then append the framework's own findings:
    suppressions without a reason, and suppressions naming unknown passes.
    (An *unused* but well-formed suppression is tolerated — annotations
    legitimately outlive the exact analysis that required them.)"""
    by_file = {m.relpath: m for m in project.modules}
    kept: list[Finding] = []
    for f in findings:
        mod = by_file.get(f.file)
        if mod is not None and mod.suppressed(f.pass_name, f.line):
            continue
        kept.append(f)
    for mod in project.modules:
        for s in mod.suppressions:
            if not s.pass_name or s.pass_name not in known_passes:
                kept.append(
                    Finding(
                        "suppression",
                        mod.relpath,
                        s.line,
                        "suppression names no known pass "
                        f"({s.pass_name!r}); use '# yodalint: ok <pass> "
                        f"<reason>' with one of {sorted(known_passes)}",
                    )
                )
            elif not s.reason:
                kept.append(
                    Finding(
                        "suppression",
                        mod.relpath,
                        s.line,
                        f"suppression for {s.pass_name!r} has no reason — "
                        "'# yodalint: ok <pass> <reason>' requires one",
                    )
                )
    return kept


def report(findings: "list[Finding]", out=sys.stderr) -> int:
    """Print findings sorted by location; return the process exit code."""
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.pass_name)):
        print(f.render(), file=out)
    return 1 if findings else 0
