"""yodalint — project-invariant static analysis for yoda-tpu (ISSUE 13).

Nine passes over one shared parse + call graph, gating ``make lint``:

1. lock-discipline        — no blocking work under a component lock;
                            lock acquisitions respect the declared DAG
2. fence-before-write     — every mutating cluster write is dominated by
                            a leader-fence check
3. snapshot-immutability  — no attribute assignment on Snapshot /
                            FleetArrays outside construction sites
4. config-drift           — knobs are validated + shipped (ConfigMap) +
                            documented (OPERATIONS.md), no ghosts
5. hook-registration-order — build_stack wires accountant -> gang ->
                            informer -> recorder
6. metrics-drift          — yoda_* series asserted in tests + documented
7. verdict-taxonomy       — why-pending kinds stay in the pinned set
8. reload-safety          — hot-reload classification is coherent and
                            every RELOADABLE knob is genuinely live
9. speculation-safety     — speculative plan consumption is dominated by
                            the leader fence AND the epoch check; the
                            informer never calls into the cache

Suppress a deliberate exception with ``# yodalint: ok <pass> <reason>``
on (or directly above) the flagged line; the reason is mandatory.

Run: ``python -m tools.yodalint [--root DIR] [--pass NAME ...]``.
tests/test_yodalint.py proves each pass catches a planted violation and
that the live tree is clean.
"""

from __future__ import annotations

from tools.yodalint.callgraph import CallGraph
from tools.yodalint.core import (
    Finding,
    Project,
    apply_suppressions,
    report,
)
from tools.yodalint.passes import ALL_PASSES, PASS_NAMES

# The framework's own findings (malformed suppressions) use this name.
KNOWN_PASS_NAMES = PASS_NAMES | {"suppression"}


def run_all(
    project: Project, only: "set[str] | None" = None
) -> "list[Finding]":
    """Run every (or the selected) pass; returns suppression-filtered
    findings. The call graph is built once and shared."""
    graph = CallGraph(project)
    findings: "list[Finding]" = []
    for p in ALL_PASSES:
        if only and p.NAME not in only:
            continue
        findings.extend(p.run(project, graph))
    return apply_suppressions(project, findings, PASS_NAMES)


__all__ = [
    "ALL_PASSES",
    "CallGraph",
    "Finding",
    "PASS_NAMES",
    "Project",
    "apply_suppressions",
    "report",
    "run_all",
]
