"""CLI: ``python -m tools.yodalint`` — run the suite, print findings,
exit 1 on any. Gated into ``make lint`` (< 5 s budget, zero findings on
a clean tree)."""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from tools.yodalint import ALL_PASSES, PASS_NAMES, Project, report, run_all


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(prog="yodalint")
    ap.add_argument(
        "--root",
        default=str(Path(__file__).resolve().parent.parent.parent),
        help="repo root (default: this checkout)",
    )
    ap.add_argument(
        "--pass",
        dest="passes",
        action="append",
        choices=sorted(PASS_NAMES),
        help="run only the named pass (repeatable)",
    )
    args = ap.parse_args(argv)
    t0 = time.monotonic()
    project = Project(args.root)
    findings = run_all(project, set(args.passes) if args.passes else None)
    rc = report(findings)
    n = len(findings)
    wall = time.monotonic() - t0
    print(
        f"yodalint: {len(project.modules)} modules, "
        f"{len(args.passes) if args.passes else len(ALL_PASSES)} passes, "
        f"{n} finding{'s' if n != 1 else ''} ({wall:.2f}s)",
        file=sys.stderr if n else sys.stdout,
    )
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
