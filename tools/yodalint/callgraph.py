"""Per-function call graph with method resolution over the package.

Built once per run and shared by the passes that reason about
reachability (lock-discipline's held-lock closure, fence-before-write's
helper chasing). Resolution is deliberately *under*-approximate — an
edge exists only when the callee is statically certain:

- ``self.m()``          -> method ``m`` on the enclosing class or a base
                           class defined in the package (bases resolved
                           by name; single-inheritance chains followed).
- ``name()``            -> a module-level function ``name`` in the same
                           module, or one imported from a package module
                           (``from yoda_tpu.x import name``).
- ``self.attr.m()``     -> method ``m`` of the class ``attr`` was
                           constructed as in ``__init__``
                           (``self.attr = ClassName(...)``).
- ``param.m()``         -> method ``m`` of the class a parameter name
                           conventionally carries (``PARAM_TYPES``: this
                           codebase wires components by name — a
                           parameter called ``informer`` is always the
                           InformerCache, etc.).

Everything else (callbacks like ``self.on_pod_pending``, duck-typed
cluster backends, lambdas) stays unresolved: the passes treat missing
edges as "nothing reachable", never as "anything possible", so added
precision here only ever *adds* findings. The planted-violation fixtures
in tests/test_yodalint.py pin the resolution rules this module promises.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.yodalint.core import Module, Project, walk_cached

#: Conventional parameter-name -> class typing (the wiring convention in
#: standalone.build_stack and every component constructor).
PARAM_TYPES = {
    "informer": "InformerCache",
    "queue": "SchedulingQueue",
    "accountant": "ChipAccountant",
    "gang": "GangPlugin",
    "metrics": "SchedulingMetrics",
    "scheduler": "Scheduler",
    "framework": "Framework",
    "tracer": "Tracer",
    "ledger": "TenantLedger",
    # Scheduler shard-out (ISSUE 14): the router's lock ranks with the
    # informer level — resolving `self.router.route(...)` lets the
    # lock-discipline pass see reaches into it from commit paths.
    "router": "ShardRouter",
    # Sub-millisecond serve (ISSUE 17): the speculative cache sits at
    # the BOTTOM of the lock DAG — resolving its conventional receivers
    # (`self.speculation`, the rebalancer's `self.speculator`, the serve
    # path's local `spec`) lets lock-discipline see reaches into its
    # lock from higher levels.
    "speculation": "SpeculativeCache",
    "speculator": "SpeculativeCache",
    "spec": "SpeculativeCache",
}


@dataclass
class ClassInfo:
    name: str
    module: Module
    node: ast.ClassDef
    base_names: "list[str]" = field(default_factory=list)
    #: method name -> FunctionInfo
    methods: "dict[str, FunctionInfo]" = field(default_factory=dict)
    #: ``self.<attr> = ClassName(...)`` assignments seen in any method
    attr_types: "dict[str, str]" = field(default_factory=dict)


@dataclass
class FunctionInfo:
    qualname: str  # "relpath::Class.method" or "relpath::func"
    module: Module
    node: "ast.FunctionDef | ast.AsyncFunctionDef"
    cls: "ClassInfo | None" = None


class CallGraph:
    def __init__(self, project: Project) -> None:
        self.project = project
        self.functions: "dict[str, FunctionInfo]" = {}
        self.classes_by_name: "dict[str, list[ClassInfo]]" = {}
        #: per-module: imported name -> (source module relpath suffix)
        self._imports: "dict[str, dict[str, str]]" = {}
        self._module_funcs: "dict[str, dict[str, FunctionInfo]]" = {}
        self._calls_cache: "dict[str, list[ast.Call]]" = {}
        for mod in project.modules:
            self._index_module(mod)
        self._infer_attr_types()

    # ------------------------------------------------------------- index

    def _index_module(self, mod: Module) -> None:
        funcs: "dict[str, FunctionInfo]" = {}
        imports: "dict[str, str]" = {}
        self._module_funcs[mod.relpath] = funcs
        self._imports[mod.relpath] = imports
        for node in mod.tree.body:
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module.split(".")[0] == self.project.package:
                    target = node.module.replace(".", "/") + ".py"
                    for alias in node.names:
                        imports[alias.asname or alias.name] = target
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FunctionInfo(f"{mod.relpath}::{node.name}", mod, node)
                funcs[node.name] = fi
                self.functions[fi.qualname] = fi
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(
                    node.name,
                    mod,
                    node,
                    base_names=[
                        b.id
                        for b in node.bases
                        if isinstance(b, ast.Name)
                    ],
                )
                self.classes_by_name.setdefault(node.name, []).append(ci)
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        fi = FunctionInfo(
                            f"{mod.relpath}::{node.name}.{item.name}",
                            mod,
                            item,
                            cls=ci,
                        )
                        ci.methods[item.name] = fi
                        self.functions[fi.qualname] = fi

    def _infer_attr_types(self) -> None:
        """``self.attr = ClassName(...)`` (any method, any known class)
        -> attr_types so ``self.attr.m()`` resolves."""
        for classes in self.classes_by_name.values():
            for ci in classes:
                for fi in ci.methods.values():
                    for node in walk_cached(fi.node):
                        if not (
                            isinstance(node, ast.Assign)
                            and len(node.targets) == 1
                            and isinstance(node.targets[0], ast.Attribute)
                            and isinstance(
                                node.targets[0].value, ast.Name
                            )
                            and node.targets[0].value.id == "self"
                            and isinstance(node.value, ast.Call)
                            and isinstance(node.value.func, ast.Name)
                            and node.value.func.id in self.classes_by_name
                        ):
                            continue
                        ci.attr_types[node.targets[0].attr] = (
                            node.value.func.id
                        )

    # ----------------------------------------------------------- resolve

    def _class_method(
        self, ci: ClassInfo, name: str, _seen: "frozenset[str]" = frozenset()
    ) -> "FunctionInfo | None":
        if name in ci.methods:
            return ci.methods[name]
        for base in ci.base_names:
            if base in _seen:
                continue
            for bci in self.classes_by_name.get(base, []):
                hit = self._class_method(
                    bci, name, _seen | {ci.name}
                )
                if hit is not None:
                    return hit
        return None

    def _methods_named(self, name: str, class_name: str) -> "FunctionInfo | None":
        for ci in self.classes_by_name.get(class_name, []):
            hit = self._class_method(ci, name)
            if hit is not None:
                return hit
        return None

    def resolve_call(
        self, call: ast.Call, caller: FunctionInfo
    ) -> "list[FunctionInfo]":
        func = call.func
        # name(...)
        if isinstance(func, ast.Name):
            local = self._module_funcs[caller.module.relpath].get(func.id)
            if local is not None:
                return [local]
            src = self._imports[caller.module.relpath].get(func.id)
            if src is not None:
                target_mod = self.project.module(src)
                if target_mod is not None:
                    hit = self._module_funcs[target_mod.relpath].get(func.id)
                    if hit is not None:
                        return [hit]
            return []
        if not isinstance(func, ast.Attribute):
            return []
        recv = func.value
        # self.m(...)
        if isinstance(recv, ast.Name) and recv.id == "self":
            if caller.cls is not None:
                hit = self._class_method(caller.cls, func.attr)
                if hit is not None:
                    return [hit]
            return []
        # param.m(...) via the naming convention
        if isinstance(recv, ast.Name) and recv.id in PARAM_TYPES:
            hit = self._methods_named(func.attr, PARAM_TYPES[recv.id])
            return [hit] if hit is not None else []
        # self.attr.m(...) via __init__-inferred attribute types
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and caller.cls is not None
        ):
            tname = caller.cls.attr_types.get(recv.attr)
            if tname is None and recv.attr in PARAM_TYPES:
                tname = PARAM_TYPES[recv.attr]
            if tname is not None:
                hit = self._methods_named(func.attr, tname)
                return [hit] if hit is not None else []
        return []

    def calls_in(self, fn: FunctionInfo) -> "list[ast.Call]":
        """Every Call node in ``fn``'s body, nested defs excluded (a
        nested function's body runs when *it* is called, not when the
        enclosing function is). Memoized — several passes ask for the
        same functions' calls against the one shared graph."""
        cached = self._calls_cache.get(fn.qualname)
        if cached is not None:
            return cached
        out: "list[ast.Call]" = []
        stack: list = list(ast.iter_child_nodes(fn.node))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        self._calls_cache[fn.qualname] = out
        return out
