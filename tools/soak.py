#!/usr/bin/env python
"""Randomized-seed concurrency soak: `make soak` (or `python tools/soak.py
[rounds]`).

The CI stress suite (tests/test_stress.py) runs FIXED seeds so failures
reproduce; this driver runs the same invariant scenarios under FRESH random
seeds — the cheap release-qualification sweep that has repeatedly been run
by hand. Each round: N gang-contention runs, M constraint-fleet runs, and
one mesh-sharded run. Any failure prints the seed so it can be pinned into
the suite.
"""

from __future__ import annotations

import os
import random
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# The axon site hook pins the platform via jax.config OVER the env var
# (.claude/skills/verify/SKILL.md gotcha) — re-pin before any backend init.
jax.config.update("jax_platforms", "cpu")


def main(rounds: int = 1) -> int:
    import importlib.util

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, here)
    spec = importlib.util.spec_from_file_location(
        "stressmod", os.path.join(here, "tests", "test_stress.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    rng = random.SystemRandom()
    for r in range(rounds):
        seeds = rng.sample(range(100, 1_000_000), 5)
        for s in seeds[:3]:
            mod.test_serve_forever_under_churn_and_gang_contention(s, None, 1)
            print(f"round {r}: gang-contention seed {s}: OK", flush=True)
        for s in seeds[3:]:
            mod.test_serve_forever_with_node_constraints(seed=s)
            print(f"round {r}: constraint-fleet seed {s}: OK", flush=True)
        mesh_seed = rng.randrange(100, 1_000_000)
        mod.test_serve_forever_under_churn_and_gang_contention(mesh_seed, 8, 1)
        print(f"round {r}: mesh-sharded seed {mesh_seed}: OK", flush=True)
        burst_seed = rng.randrange(100, 1_000_000)
        mod.test_serve_forever_under_churn_and_gang_contention(
            burst_seed, None, 16
        )
        print(f"round {r}: burst-dispatch seed {burst_seed}: OK", flush=True)
    print("SOAK_PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(int(sys.argv[1]) if len(sys.argv) > 1 else 1))
