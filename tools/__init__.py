# Repo tooling package (tools.yodalint et al.) — not shipped in the wheel.
