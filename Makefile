# Build targets — the analog of the reference's Makefile (reference
# Makefile:1-15: local / build / push / format / clean), adapted: the
# "binary" is the yoda_tpu package + the native metrics reader, and — unlike
# the reference's build-only CI (reference .github/workflows/ci.yaml:35-40,
# no tests) — `make test` is the default gate.

IMAGE ?= yoda-tpu/scheduler
TAG ?= latest
PY ?= python
CXX ?= g++

.PHONY: all test lint native native-asan bench bench-scale serve-bench rebalance-bench slo-bench shard-bench proc-bench failover-bench overload-bench smoke chaos demo soak image push format clean

all: native lint test

test:
	$(PY) -m pytest tests/ -q

# Static checks (ruff; rule config in pyproject.toml [tool.ruff]). The
# container image may not ship ruff — fall back to a byte-compile sweep so
# `make all` still gates on syntax-clean sources everywhere. yodalint
# (tools/yodalint, docs/OPERATIONS.md "Static analysis gates") runs the
# ten project-invariant passes — lock discipline, fence-before-write,
# snapshot immutability, config/metrics/doc drift, hook order, verdict
# taxonomy, reload safety, speculation safety, journal discipline — in
# < 5 s with zero findings required on a clean tree.
lint:
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check yoda_tpu tests bench.py __graft_entry__.py; \
	elif command -v ruff >/dev/null 2>&1; then \
		ruff check yoda_tpu tests bench.py __graft_entry__.py; \
	else \
		echo "lint: ruff not installed; running compileall syntax sweep only"; \
		$(PY) -m compileall -q yoda_tpu tests bench.py __graft_entry__.py; \
	fi
	$(PY) -m tools.yodalint

native:
	$(MAKE) -C native

# Sanitizer gate for the native metrics reader (ISSUE 13 satellite):
# rebuild native/ with ASan + UBSan and run the agent test suite against
# that build (YODA_TPUINFO_SO steers the test fixture). libasan must be
# preloaded (python itself is uninstrumented), and libstdc++ alongside it
# so the __cxa_throw interceptor resolves before jaxlib's C++ loads;
# detect_leaks=0 because CPython's arena allocator "leaks" by design at
# exit. Skips cleanly where the toolchain lacks sanitizer runtimes.
native-asan:
	@if echo 'int main(){return 0;}' | $(CXX) -xc++ -fsanitize=address,undefined - -o /dev/null 2>/dev/null; then \
		$(MAKE) -C native asan && \
		env YODA_TPUINFO_SO=native/libyoda_tpuinfo_asan.so \
			LD_PRELOAD="$$($(CXX) -print-file-name=libasan.so) $$($(CXX) -print-file-name=libstdc++.so)" \
			ASAN_OPTIONS=detect_leaks=0 JAX_PLATFORMS=cpu \
			$(PY) -m pytest tests/test_native_agent.py -q; \
	else \
		echo "native-asan: toolchain lacks -fsanitize=address,undefined; skipping"; \
	fi

bench: native
	$(PY) bench.py

# Seconds-scale contended-gang check (CPU-pinned, small fleet): guards the
# burst+gang hot-path rate without the full bench's minutes of scenarios.
smoke:
	$(PY) bench.py --smoke

# Synthetic 1k/10k/100k-node fleet sweeps (CPU-pinned, virtual 8-device
# mesh): device-resident delta-apply flatness at low churn + node-axis
# sharded joint-dispatch scaling, emitted as one bench JSON line.
bench-scale:
	env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		$(PY) bench.py --scale

# Sub-millisecond serve evidence (CPU-pinned): hot-shape singles served
# cold (speculation kill switch on — every arrival pays the fused
# filter/score dispatch) vs warm (the rebalancer-tick producer parks a
# validated plan between serves). Asserts every warm serve a cache hit,
# ZERO kernel dispatches across the warm phase, cache-hit decision p99
# < 1 ms, and the 1k-vs-100k-node warm decision-chain median flat
# (<= 2x). The reduced slice rides `make smoke`; the flatness sweep
# also rides `make bench-scale`. One JSON line.
serve-bench:
	env JAX_PLATFORMS=cpu $(PY) bench.py --serve

# Goodput-driven rebalancer evidence (CPU-pinned): the seeded long-churn
# replay (fragmentation-score series with the rebalancer on vs off over
# the SAME arrival/departure stream) plus the preemptive-admission
# scenario (parked high-priority gang admitted by unbinding cheapest
# victims; victims requeue whole, zero oversubscription). One JSON line.
rebalance-bench:
	env JAX_PLATFORMS=cpu $(PY) bench.py --rebalance

# Fleet SLO evidence (CPU-pinned): the trace-replay scenario matrix at
# the standard dev shape — >= 1M pod lifecycles through batched ingest
# across spot-tier / flash-crowd / rolling-upgrade / deadline-gang
# scenarios, per-tenant admission-wait p99 + zero starved windows
# asserted by the SLO engine itself — plus the engine on/off overhead
# pair (< 2% acceptance). One JSON line. The smoke slice of the same
# matrix rides `make smoke`.
slo-bench:
	env JAX_PLATFORMS=cpu $(PY) bench.py --slo

# Scheduler shard-out scaling evidence (CPU-pinned): 24 four-member
# gangs at 100 ms injected bind latency drained through 1/2/4/8-shard
# assemblies — aggregate pods/s, optimistic-commit conflict/rollback
# totals, and admission p99 per shard count. Asserts >= 3x aggregate
# pods/s at 4 shards vs the 1-shard baseline (same machinery, so the
# ratio isolates sharding itself). The 1-vs-2 smoke slice rides
# `make smoke`. One JSON line.
shard-bench:
	env JAX_PLATFORMS=cpu $(PY) bench.py --shards

# Multi-process shard serve evidence (CPU-pinned): the 8-shard shape
# drained by 8 worker PROCESSES over the commit RPC vs the SAME shape
# as 8 serve-loop threads, zero injected bind latency so the drain is
# pure scheduler CPU (the GIL-bound regime). Asserts >= 1.5x aggregate
# pods/s on multi-CPU hosts (the gate self-skips on one core, where
# threads lose nothing to the GIL); zero staged residue / chip leaks
# assert everywhere. The 2-worker slice rides `make smoke`. Also runs
# inside `make shard-bench`. One JSON line.
proc-bench:
	env JAX_PLATFORMS=cpu $(PY) bench.py --proc

# Multi-host control-plane failover evidence (CPU-pinned): a 100k-claim
# parent killed behind a journal-tailing standby — warm (mirror
# promotion) vs cold (disk replay) parent-kill -> first-worker-commit
# latency, < 1 s warm and >= 5x vs cold asserted — plus the AF_UNIX vs
# loopback-TCP commit p99 comparison (<= 2x asserted). The reduced
# slice rides `make smoke`. One JSON line.
failover-bench:
	env JAX_PLATFORMS=cpu $(PY) bench.py --failover

# Overload brownout ladder + live shard resize evidence (CPU-pinned):
# the seeded 10x flash-crowd replay with the ladder on vs off (prod
# admission p99 within its steady-state SLO while spot-tier sheds, vs
# degradation with the ladder off; zero oversubscription, whole gangs,
# shed = deferral never loss) plus a live shard_count resize under the
# same load (movement <= 1.5/N of routed pods, no dropped gangs, zero
# staged-claim leaks). The 0.5-scale slice rides `make smoke`. One
# JSON line.
overload-bench:
	env JAX_PLATFORMS=cpu $(PY) bench.py --overload

# Fault-injection suite (fixed seed, replayable): gang bind rollback,
# transient-error retry, dispatch fallback chain, leader fencing, the
# seeded stress sweep, the scheduler_crash failover sweep (leader killed
# mid-gang at a seeded bind, fresh scheduler promoted over the same
# cluster), and the federation partition sweep (cluster_partition /
# cluster_loss faults against a three-cluster federation: surviving
# serve loops keep placing, gangs spill whole or park whole, rejoins
# reconcile clean) — tests/test_chaos.py + tests/test_failover.py +
# tests/test_federation.py, slow tests included. The fast chaos/
# failover/federation tests also run in tier-1 (`make test` / the
# default gate), so rollback- and resync-path regressions fail CI
# without this target; this target adds the sweeps. Override the sweep
# seed via CHAOS_SEED (the test reads its default from the source; the
# seed is printed on failure for replay).
chaos:
	$(PY) -m pytest tests/test_chaos.py tests/test_failover.py tests/test_federation.py tests/test_rebalance.py tests/test_tenancy.py tests/test_node_health.py tests/test_shards.py tests/test_overload.py -q

demo:
	$(PY) -m yoda_tpu.cli --demo

# Randomized-seed concurrency sweep (the CI stress suite runs fixed
# seeds) plus the 24h-equivalent durable-journal endurance run: diurnal
# trace, restart, warm-start promotion, flat journal size — all
# asserted inside bench.run_soak.
soak:
	$(PY) tools/soak.py $(SOAK_ROUNDS)
	env JAX_PLATFORMS=cpu $(PY) bench.py --soak

# Real-cluster smoke test: kind + docker + kubectl required (optional in
# CI — runs where Docker exists). tools/kind-e2e.sh --keep to retain the
# cluster for inspection.
kind-e2e:
	tools/kind-e2e.sh

image:
	docker build -t $(IMAGE):$(TAG) .

push: image
	docker push $(IMAGE):$(TAG)

format:
	$(PY) -m black yoda_tpu tests bench.py __graft_entry__.py 2>/dev/null || true

clean:
	$(MAKE) -C native clean
	rm -rf .pytest_cache yoda_tpu/__pycache__ tests/__pycache__
