// libyoda_tpuinfo: host-side TPU metrics reader for the node agent.
//
// The reference's metric source was an external GPU "sniffer" DaemonSet
// feeding the SCV CRD (reference readme.md:9-15; SURVEY.md §1-L5). This is
// its TPU-native, in-tree equivalent: a small native library the agent
// (yoda_tpu/agent/native.py, via ctypes) calls to inventory the host's TPU
// chips. Native because it runs on every node at a tight interval and must
// not depend on a Python TPU runtime being importable on the host.
//
// Collection sources, in priority order (yoda_tpuinfo_source() reports
// which fired):
//   1. YODA_TPUINFO_SPEC env override — deterministic spec string for tests
//      and development clusters ("generation=v5e;chips=8;hbm_gib=16;...").
//   2. TPU device inventory: /dev/accel* (TPU VM runtime) or /dev/vfio/*
//      device nodes for the chip count, plus the GKE TPU environment
//      (TPU_ACCELERATOR_TYPE, TPU_WORKER_ID) for generation/topology, with
//      per-generation chip characteristics from a built-in table (the same
//      table as yoda_tpu/agent/fake_publisher.py CHIP_SPECS).
//   3. None: chip_count = 0 (the agent then publishes nothing, and the
//      scheduler filters the node out — "no TPU metrics").
//
// Free HBM is reported as total when no runtime counter is available: chip
// occupancy is tracked scheduler-side by the accountant
// (yoda_tpu/plugins/yoda/accounting.py), so over-reporting free HBM is safe
// (availability is clamped by reservations), while under-reporting would
// strand capacity.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

#include <dirent.h>

#define YODA_TPUINFO_MAX_CHIPS 16

extern "C" {

typedef struct {
  int32_t index;
  int32_t healthy;  // 1 = healthy
  int64_t hbm_free;
  int64_t hbm_total;
  int32_t clock_mhz;
  int32_t hbm_bandwidth_gbps;
  int32_t tflops_bf16;
  int32_t power_w;
} yoda_tpuinfo_chip;

typedef struct {
  char generation[8];
  char accel_type[32];
  char slice_id[64];
  int32_t coords[3];
  int32_t chip_count;
  yoda_tpuinfo_chip chips[YODA_TPUINFO_MAX_CHIPS];
} yoda_tpuinfo_host;

}  // extern "C"

namespace {

struct ChipSpec {
  const char* generation;
  int hbm_gib;
  int clock_mhz;
  int hbm_bandwidth_gbps;
  int tflops_bf16;
  int power_w;
  int default_chips_per_host;
};

// Keep in sync with CHIP_SPECS in yoda_tpu/agent/fake_publisher.py.
constexpr ChipSpec kSpecs[] = {
    {"v4", 32, 940, 1200, 275, 170, 4},
    {"v5e", 16, 940, 819, 197, 130, 8},
    {"v5p", 95, 1050, 2765, 459, 250, 4},
    {"v6e", 32, 1050, 1640, 918, 200, 8},
};

const ChipSpec* find_spec(const std::string& generation) {
  for (const auto& s : kSpecs) {
    if (generation == s.generation) return &s;
  }
  return nullptr;
}

const char* g_source = "none";

void fill_chips(yoda_tpuinfo_host* out, const ChipSpec& spec, int count,
                int64_t hbm_gib_override, int clock_override) {
  if (count > YODA_TPUINFO_MAX_CHIPS) count = YODA_TPUINFO_MAX_CHIPS;
  out->chip_count = count;
  const int64_t gib = 1ll << 30;
  const int64_t hbm =
      (hbm_gib_override > 0 ? hbm_gib_override : spec.hbm_gib) * gib;
  for (int i = 0; i < count; ++i) {
    yoda_tpuinfo_chip& c = out->chips[i];
    c.index = i;
    c.healthy = 1;
    c.hbm_free = hbm;
    c.hbm_total = hbm;
    c.clock_mhz = clock_override > 0 ? clock_override : spec.clock_mhz;
    c.hbm_bandwidth_gbps = spec.hbm_bandwidth_gbps;
    c.tflops_bf16 = spec.tflops_bf16;
    c.power_w = spec.power_w;
  }
}

// --- source 1: env spec override ---

// "generation=v5e;chips=8;hbm_gib=16;clock=940;slice=pool-a;coords=1,0,2;
//  accel_type=v5e-8" — unknown keys ignored, any order.
bool collect_from_env_spec(yoda_tpuinfo_host* out) {
  const char* spec_env = std::getenv("YODA_TPUINFO_SPEC");
  if (spec_env == nullptr || spec_env[0] == '\0') return false;

  std::string generation = "v5e";
  int chips = -1;
  int hbm_gib = -1;
  int clock = -1;
  std::string slice_id, accel_type;
  int coords[3] = {0, 0, 0};

  std::string s(spec_env);
  size_t pos = 0;
  while (pos < s.size()) {
    size_t end = s.find(';', pos);
    if (end == std::string::npos) end = s.size();
    std::string kv = s.substr(pos, end - pos);
    pos = end + 1;
    size_t eq = kv.find('=');
    if (eq == std::string::npos) continue;
    std::string key = kv.substr(0, eq), val = kv.substr(eq + 1);
    if (key == "generation") generation = val;
    else if (key == "chips") chips = std::atoi(val.c_str());
    else if (key == "hbm_gib") hbm_gib = std::atoi(val.c_str());
    else if (key == "clock") clock = std::atoi(val.c_str());
    else if (key == "slice") slice_id = val;
    else if (key == "accel_type") accel_type = val;
    else if (key == "coords")
      std::sscanf(val.c_str(), "%d,%d,%d", &coords[0], &coords[1], &coords[2]);
  }
  const ChipSpec* spec = find_spec(generation);
  if (spec == nullptr) return false;
  if (chips < 0) chips = spec->default_chips_per_host;

  std::snprintf(out->generation, sizeof(out->generation), "%s",
                generation.c_str());
  std::snprintf(out->accel_type, sizeof(out->accel_type), "%s",
                accel_type.empty()
                    ? (generation + "-" + std::to_string(chips)).c_str()
                    : accel_type.c_str());
  std::snprintf(out->slice_id, sizeof(out->slice_id), "%s", slice_id.c_str());
  std::memcpy(out->coords, coords, sizeof(coords));
  fill_chips(out, *spec, chips, hbm_gib, clock);
  g_source = "env";
  return true;
}

// --- source 2: device inventory + GKE TPU environment ---

int count_matching(const char* dir, const char* prefix) {
  DIR* d = opendir(dir);
  if (d == nullptr) return 0;
  int n = 0;
  while (dirent* e = readdir(d)) {
    if (std::strncmp(e->d_name, prefix, std::strlen(prefix)) == 0 &&
        std::strcmp(e->d_name, ".") != 0 && std::strcmp(e->d_name, "..") != 0) {
      ++n;
    }
  }
  closedir(d);
  return n;
}

// "v5p-16" -> generation "v5p"; "v5litepod-8" (GKE v5e name) -> "v5e".
std::string generation_from_accel_type(const std::string& accel) {
  size_t dash = accel.find('-');
  std::string head = dash == std::string::npos ? accel : accel.substr(0, dash);
  if (head == "v5litepod") return "v5e";
  return head;
}

bool collect_from_devices(yoda_tpuinfo_host* out) {
  // TPU VM runtime exposes one /dev/accel<N> per chip; VFIO setups expose
  // /dev/vfio/<group> per chip (plus the "vfio" control node).
  int chips = count_matching("/dev", "accel");
  if (chips == 0) {
    int vfio = count_matching("/dev/vfio", "");
    if (vfio > 1) chips = vfio - 1;  // minus the /dev/vfio/vfio control node
  }
  if (chips == 0) return false;

  const char* accel_env = std::getenv("TPU_ACCELERATOR_TYPE");
  std::string accel = accel_env ? accel_env : "";
  std::string generation =
      accel.empty() ? "v5e" : generation_from_accel_type(accel);
  const ChipSpec* spec = find_spec(generation);
  if (spec == nullptr) spec = &kSpecs[1];  // default v5e characteristics

  std::snprintf(out->generation, sizeof(out->generation), "%s",
                generation.c_str());
  std::snprintf(out->accel_type, sizeof(out->accel_type), "%s",
                accel.empty()
                    ? (generation + "-" + std::to_string(chips)).c_str()
                    : accel.c_str());
  // Multi-host slices: GKE sets TPU_WORKER_ID (host index within the slice)
  // and the agent passes the slice identity via YODA_TPUINFO_SLICE (derived
  // from the node pool); coords default to a 1-D layout by worker id — the
  // control plane's richer topology labels refine this in the agent.
  const char* slice = std::getenv("YODA_TPUINFO_SLICE");
  std::snprintf(out->slice_id, sizeof(out->slice_id), "%s", slice ? slice : "");
  const char* worker = std::getenv("TPU_WORKER_ID");
  out->coords[0] = worker ? std::atoi(worker) : 0;
  out->coords[1] = 0;
  out->coords[2] = 0;
  fill_chips(out, *spec, chips, -1, -1);
  g_source = "device-files";
  return true;
}

}  // namespace

extern "C" {

// Fills *out; returns the chip count (0 = no TPU found).
int yoda_tpuinfo_collect(yoda_tpuinfo_host* out) {
  std::memset(out, 0, sizeof(*out));
  if (collect_from_env_spec(out)) return out->chip_count;
  if (collect_from_devices(out)) return out->chip_count;
  g_source = "none";
  return 0;
}

const char* yoda_tpuinfo_source(void) { return g_source; }

int yoda_tpuinfo_max_chips(void) { return YODA_TPUINFO_MAX_CHIPS; }

}  // extern "C"
