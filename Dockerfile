# One image, two roles (scheduler Deployment / agent DaemonSet select via
# args) — the analog of the reference's Dockerfile (reference Dockerfile:1-7:
# debian-slim + prebuilt binary), except the native metrics reader is built
# in a proper builder stage instead of copying a host-built artifact.
FROM python:3.12-slim AS builder
RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*
COPY native/ /src/native/
RUN make -C /src/native

FROM python:3.12-slim
# The scheduler's fused scoring kernel runs JAX on CPU inside the pod;
# grpcio is the agent's transport for the libtpu metrics service
# (--libtpu-metrics, on by default in the DaemonSet).
RUN pip install --no-cache-dir "jax[cpu]" numpy pyyaml grpcio
COPY yoda_tpu/ /app/yoda_tpu/
COPY --from=builder /src/native/libyoda_tpuinfo.so /usr/local/lib/yoda_tpu/
ENV PYTHONPATH=/app
ENTRYPOINT ["python", "-m", "yoda_tpu.cli"]
