"""Overload brownout ladder + config hot-reload (ISSUE 15).

Fast tier-1 tests drive the ladder's strict order, the step-down
debounce, the shed-then-requeue whole-gang contract, the feature
pause/resume, and the reload classification/apply machinery directly;
the slow seeded ``overload_storm`` sweep (chaos mode) holds the
invariants under rounds of flood + calm."""

import os
import threading

import pytest

from yoda_tpu.api.types import PodSpec
from yoda_tpu.config import (
    IMMUTABLE_KNOBS,
    RELOADABLE_KNOBS,
    RESIZE_KNOBS,
    SchedulerConfig,
    classify_knob,
)
from yoda_tpu.overload import (
    BROWNOUT,
    ELEVATED,
    NOMINAL,
    SHED,
    ConfigReloader,
    LiveConfig,
    OverloadMonitor,
)
from yoda_tpu.standalone import apply_reloadable, build_stack
from yoda_tpu.testing.tracegen import ReplayClock


class _StubQueue:
    """Just enough queue for ladder unit tests: a settable depth and a
    reactivation recorder."""

    def __init__(self) -> None:
        self.depth = 0
        self.reactivations = 0

    def overload_depth(self) -> int:
        return self.depth

    def move_all_to_active(self, **_kw) -> None:
        self.reactivations += 1


def make_monitor(**kw):
    clock = ReplayClock()
    kw.setdefault("queue_high", 10)
    kw.setdefault("step_down_hold_s", 5.0)
    mon = OverloadMonitor(clock=clock, **kw)
    q = _StubQueue()
    mon.add_queue(q)
    return mon, q, clock


class TestLadder:
    def test_climbs_one_level_per_evaluation_in_strict_order(self):
        mon, q, _clock = make_monitor()
        q.depth = 100  # pressure 10 -> target SHED
        seen = [mon.evaluate() for _ in range(4)]
        assert seen == ["ELEVATED", "BROWNOUT", "SHED", "SHED"]
        assert mon.transitions == 3

    def test_step_down_requires_sustained_calm(self):
        mon, q, clock = make_monitor()
        q.depth = 100
        for _ in range(3):
            mon.evaluate()
        assert mon.level == "SHED"
        q.depth = 0
        # Calm, but not for long enough: the debounce holds the level.
        mon.evaluate()
        clock.now += 2.0
        mon.evaluate()
        assert mon.level == "SHED"
        clock.now += 5.0
        mon.evaluate()
        assert mon.level == "BROWNOUT"
        # Each downward step needs its own hold window.
        mon.evaluate()
        assert mon.level == "BROWNOUT"
        clock.now += 6.0
        mon.evaluate()
        clock.now += 6.0
        mon.evaluate()
        assert mon.level == "NOMINAL"

    def test_flapping_pressure_cannot_thrash_features(self):
        mon, q, clock = make_monitor()
        q.depth = 100
        for _ in range(2):
            mon.evaluate()
        assert mon.level == "BROWNOUT"
        before = mon.transitions
        # Pressure oscillates every tick: the calm windows never reach
        # the hold, so the level never steps down (and never exceeds
        # the pressure's own target on the way up).
        for i in range(20):
            q.depth = 0 if i % 2 else 100
            clock.now += 1.0
            mon.evaluate()
        assert mon.level in ("BROWNOUT", "SHED")
        # Only the possible single step up to SHED — no down-flaps.
        assert mon.transitions <= before + 1

    def test_step_down_reactivates_parked_queues(self):
        mon, q, clock = make_monitor()
        q.depth = 100
        mon.evaluate()
        q.depth = 0
        mon.evaluate()  # marks the calm window's start
        clock.now += 10.0
        mon.evaluate()  # hold elapsed: steps down + reactivates
        assert mon.level == "NOMINAL"
        assert q.reactivations == 1

    def test_burn_alert_is_brownout_grade_pressure(self):
        mon, _q, _clock = make_monitor()

        class _Slo:
            enabled = True
            burn_threshold = 2.0

            def burn_snapshot(self):
                return (3.0, 2.5)

        mon.attach(slo=_Slo())
        signals = mon.pressure()
        assert signals["burn"] == 2.0
        mon.evaluate()
        mon.evaluate()
        assert mon.level == "BROWNOUT"


class TestFeaturePauseResume:
    def test_elevated_pauses_repairs_and_tracing(self):
        stack = build_stack(
            config=SchedulerConfig(
                overload_queue_high=1, trace_sample_rate=1.0
            )
        )
        ov = stack.metrics.overload
        stack.reconciler.resynced.set()
        assert stack.rebalancer.gate_fn()
        assert stack.nodehealth.gate_fn()
        ov._transition_locked(ELEVATED)
        assert not stack.rebalancer.gate_fn()
        assert not stack.nodehealth.gate_fn()
        assert stack.metrics.tracer.sample_rate == 0.0
        ov._transition_locked(NOMINAL)
        assert stack.metrics.tracer.sample_rate == 1.0
        assert stack.rebalancer.gate_fn()

    def test_reload_during_pause_updates_the_restore_value(self):
        mon, _q, _clock = make_monitor()

        class _Tracer:
            sample_rate = 0.5

        mon.attach(tracer=_Tracer())
        mon._transition_locked(ELEVATED)
        assert mon.tracer.sample_rate == 0.0
        mon.set_base_sample_rate(0.25)  # hot-reload mid-pause
        assert mon.tracer.sample_rate == 0.0  # still paused
        mon._transition_locked(NOMINAL)
        assert mon.tracer.sample_rate == 0.25


class TestBrownoutCap:
    def test_token_bucket_caps_and_refills(self):
        mon, _q, clock = make_monitor(brownout_admit_per_s=2.0)
        mon._transition_locked(ELEVATED)
        mon._transition_locked(BROWNOUT)
        # Burst = one second's worth (2 tokens), then capped.
        assert mon.quota_verdict("team-a") is None
        assert mon.quota_verdict("team-a") is None
        why = mon.quota_verdict("team-a")
        assert why is not None and "brownout" in why
        # Another tenant has its own bucket.
        assert mon.quota_verdict("team-b") is None
        clock.now += 1.0
        assert mon.quota_verdict("team-a") is None

    def test_nominal_never_caps(self):
        mon, _q, _clock = make_monitor()
        for _ in range(100):
            assert mon.quota_verdict("t") is None


def _drain(stack, *, max_wall_s=10.0):
    stack.scheduler.run_until_idle(max_wall_s=max_wall_s)


class TestShedAndRequeue:
    def _stack(self, **cfg):
        from yoda_tpu.agent.fake_publisher import FakeTpuAgent

        clock = ReplayClock()
        cfg.setdefault("overload_queue_high", 2)
        cfg.setdefault("overload_step_down_hold_s", 5.0)
        cfg.setdefault("batch_requests", 8)
        stack = build_stack(config=SchedulerConfig(**cfg), clock=clock)
        agent = FakeTpuAgent(stack.cluster)
        agent.add_host("h0", generation="v5e", chips=8)
        agent.add_host("h1", generation="v5e", chips=8)
        agent.publish_all()
        return stack, clock

    def test_shed_parks_spot_serves_prod_then_requeues_on_step_down(self):
        stack, clock = self._stack()
        ov = stack.metrics.overload
        for lvl in (ELEVATED, BROWNOUT, SHED):
            ov._transition_locked(lvl)
        for i in range(4):
            stack.cluster.create_pod(
                PodSpec(
                    f"spot-{i}",
                    namespace="spot",
                    labels={"tpu/chips": "2", "tpu/priority": "0"},
                )
            )
        stack.cluster.create_pod(
            PodSpec(
                "prod-0",
                namespace="prod",
                labels={"tpu/chips": "2", "tpu/priority": "10"},
            )
        )
        _drain(stack)
        # Prod bound THROUGH shed; spot parked with overload-shed
        # verdicts, still alive on the cluster (shed never deletes).
        assert stack.cluster.get_pod("prod/prod-0").node_name
        for i in range(4):
            assert not stack.cluster.get_pod(f"spot/spot-{i}").node_name
        entry = stack.metrics.pending.explain("spot/spot-0")
        assert entry is not None and entry["kind"] == "overload-shed"
        assert ov.shed_total >= 4
        assert stack.queue.overload_depth() == 0  # shed applies no pressure
        # Ladder steps down (hold elapsed per step): each step's
        # reactivation requeues the shed pods, which bind as soon as the
        # level admits them (draining keeps the pressure calm — the
        # sawtooth guard in overload_depth is what makes this converge).
        for _ in range(6):
            ov.evaluate()
            _drain(stack)
            clock.now += 10.0
        assert ov.level == "NOMINAL"
        _drain(stack)
        for i in range(4):
            assert stack.cluster.get_pod(f"spot/spot-{i}").node_name, i
        # Bound pods retire their why-pending entries.
        assert stack.metrics.pending.explain("spot/spot-0") is None

    def test_spot_gang_sheds_whole_and_binds_whole_after(self):
        stack, clock = self._stack()
        ov = stack.metrics.overload
        for lvl in (ELEVATED, BROWNOUT, SHED):
            ov._transition_locked(lvl)
        labels = {
            "tpu/chips": "2",
            "tpu/priority": "0",
            "tpu/gang": "sg",
            "tpu/gang-size": "4",
        }
        for m in range(4):
            stack.cluster.create_pod(
                PodSpec(f"sg-{m}", namespace="spot", labels=dict(labels))
            )
        _drain(stack)
        bound = [
            m
            for m in range(4)
            if stack.cluster.get_pod(f"spot/sg-{m}").node_name
        ]
        assert bound == []  # whole gang shed, zero members mid-flight
        assert not stack.framework.waiting_pods()
        clock.now += 10.0
        for _ in range(3):
            ov.evaluate()
            clock.now += 10.0
        _drain(stack)
        bound = [
            m
            for m in range(4)
            if stack.cluster.get_pod(f"spot/sg-{m}").node_name
        ]
        assert bound == [0, 1, 2, 3]  # whole gang bound after the storm

    def test_mid_permit_gang_is_never_half_shed(self):
        stack, _clock = self._stack()
        ov = stack.metrics.overload
        labels = {
            "tpu/chips": "2",
            "tpu/priority": "0",
            "tpu/gang": "mg",
            "tpu/gang-size": "4",
        }
        # Three members arrive BEFORE the storm: they reserve and park
        # at the Permit barrier.
        for m in range(3):
            stack.cluster.create_pod(
                PodSpec(f"mg-{m}", namespace="spot", labels=dict(labels))
            )
        _drain(stack, max_wall_s=3.0)
        assert len(stack.framework.waiting_pods()) == 3
        for lvl in (ELEVATED, BROWNOUT, SHED):
            ov._transition_locked(lvl)
        # The last member arrives DURING shed: shedding it would strand
        # the barrier until the permit timeout — the guard admits it and
        # the gang completes whole instead.
        stack.cluster.create_pod(
            PodSpec("mg-3", namespace="spot", labels=dict(labels))
        )
        _drain(stack)
        bound = [
            m
            for m in range(4)
            if stack.cluster.get_pod(f"spot/mg-{m}").node_name
        ]
        assert bound == [0, 1, 2, 3]

    def test_healthz_semantics_queue_depth_excludes_shed(self):
        stack, _clock = self._stack()
        ov = stack.metrics.overload
        for lvl in (ELEVATED, BROWNOUT, SHED):
            ov._transition_locked(lvl)
        for i in range(10):
            stack.cluster.create_pod(
                PodSpec(
                    f"s-{i}",
                    namespace="spot",
                    labels={"tpu/chips": "2", "tpu/priority": "0"},
                )
            )
        _drain(stack)
        assert len(stack.queue) == 10
        assert stack.queue.overload_depth() == 0
        assert stack.queue.shed_parks >= 10


class TestReloadClassification:
    def test_every_knob_has_exactly_one_class(self):
        from dataclasses import fields

        names = {f.name for f in fields(SchedulerConfig)}
        assert RELOADABLE_KNOBS <= names
        assert RESIZE_KNOBS <= names
        assert IMMUTABLE_KNOBS <= names
        assert not RELOADABLE_KNOBS & IMMUTABLE_KNOBS
        assert not RELOADABLE_KNOBS & RESIZE_KNOBS
        assert not RESIZE_KNOBS & IMMUTABLE_KNOBS
        assert classify_knob("trace_sample_rate") == "reloadable"
        assert classify_knob("shard_count") == "resize"
        assert classify_knob("mode") == "immutable"
        assert classify_knob("tenant_fairness") == "requires-drain"

    def test_diff_classifies_changed_knobs(self):
        a = SchedulerConfig()
        b = SchedulerConfig(
            trace_sample_rate=0.5,
            tenant_fairness=True,
            scheduler_name="other",
            shard_count=4,
        )
        d = a.diff(b)
        assert d == {
            "trace_sample_rate": "reloadable",
            "tenant_fairness": "requires-drain",
            "scheduler_name": "immutable",
            "shard_count": "resize",
        }
        assert a.diff(a) == {}


class TestConfigReloader:
    def _reloader(self, configs, applied):
        it = iter(configs)
        live = LiveConfig(SchedulerConfig())
        return (
            ConfigReloader(
                lambda: next(it), live, applied.append
            ),
            live,
        )

    def test_reloadable_applies_and_immutable_kept(self):
        applied = []
        reloader, live = self._reloader(
            [
                SchedulerConfig(
                    trace_sample_rate=0.5, scheduler_name="evil"
                )
            ],
            applied,
        )
        report = reloader.reload()
        assert report["applied"] == ["trace_sample_rate"]
        assert report["immutable"] == ["scheduler_name"]
        assert live.current.trace_sample_rate == 0.5
        assert live.current.scheduler_name == "yoda-tpu"  # kept
        assert len(applied) == 1 and applied[0].trace_sample_rate == 0.5

    def test_requires_drain_reported_not_applied(self):
        applied = []
        reloader, live = self._reloader(
            [SchedulerConfig(tenant_fairness=True)], applied
        )
        report = reloader.reload()
        assert report["requires_drain"] == ["tenant_fairness"]
        assert live.current.tenant_fairness is False
        assert applied == []  # nothing reloadable changed

    def test_bad_load_keeps_running_config(self):
        applied = []
        live = LiveConfig(SchedulerConfig())

        def boom():
            raise ValueError("bad yaml")

        reloader = ConfigReloader(boom, live, applied.append)
        report = reloader.reload()
        assert report["error"] == "bad yaml"
        assert live.current == SchedulerConfig()
        assert applied == []

    def test_shard_count_routes_through_resize_fn(self):
        applied = []
        resized = []
        live = LiveConfig(SchedulerConfig(shard_count=2))
        reloader = ConfigReloader(
            lambda: SchedulerConfig(shard_count=4),
            live,
            applied.append,
            resize_fn=lambda n: resized.append(n) or {"shards": n},
        )
        report = reloader.reload()
        assert resized == [4]
        assert report["resized"] == {"shards": 4}
        assert live.current.shard_count == 4

    def test_shard_count_without_resize_fn_requires_drain(self):
        live = LiveConfig(SchedulerConfig())
        reloader = ConfigReloader(
            lambda: SchedulerConfig(shard_count=2), live, lambda c: None
        )
        report = reloader.reload()
        assert "shard_count" in report["requires_drain"]
        assert live.current.shard_count == 1

    def test_end_to_end_from_yaml_file(self, tmp_path):
        from yoda_tpu.cli import _load_config

        path = tmp_path / "config.yaml"
        path.write_text("trace_sample_rate: 1.0\n")
        applied = []
        live = LiveConfig(_load_config(str(path)))
        reloader = ConfigReloader(
            lambda: _load_config(str(path)), live, applied.append
        )
        path.write_text(
            "trace_sample_rate: 0.25\nrebalance_min_gain: 0.2\n"
        )
        report = reloader.reload()
        assert sorted(report["applied"]) == [
            "rebalance_min_gain",
            "trace_sample_rate",
        ]
        # A malformed rewrite changes nothing.
        path.write_text("mode: [broken\n")
        report = reloader.reload()
        assert report["error"]
        assert live.current.trace_sample_rate == 0.25


class TestApplyReloadable:
    def test_applies_to_live_components(self):
        stack = build_stack(config=SchedulerConfig())
        new = SchedulerConfig(
            trace_sample_rate=0.5,
            slo_enabled=False,
            slo_burn_threshold=5.0,
            immediate_retry_attempts=9,
            bind_retry_attempts=7,
            rebalance_min_gain=0.2,
            rebalance_max_moves=3,
            rebalance_max_victims=2,
            rebalance_preemption=False,
            rebalance_elastic=False,
            node_repair=False,
            node_drain_deadline_s=77.0,
            overload_queue_high=5,
            overload_brownout_admit_per_s=3.0,
            overload_shed_priority=42,
            pending_index_max=64,
        )
        apply_reloadable([stack], new)
        m = stack.metrics
        assert m.tracer.sample_rate == 0.5
        assert m.slo.enabled is False
        assert m.slo.burn_threshold == 5.0
        assert m.pending.capacity == 64
        assert m.overload.queue_high == 5
        assert m.overload.brownout_admit_per_s == 3.0
        assert m.overload.shed_priority_floor == 42
        assert stack.queue.immediate_retry_attempts == 9
        assert stack.binder.policy.attempts == 7
        assert stack.rebalancer.min_gain == 0.2
        assert stack.rebalancer.max_moves == 3
        assert stack.rebalancer.max_victims == 2
        assert stack.rebalancer.enable_preemption is False
        assert stack.rebalancer.enable_elastic is False
        assert stack.nodehealth.repair is False
        assert stack.nodehealth.drain_deadline_s == 77.0


class TestRunForever:
    def test_period_is_live_and_loop_stops(self):
        mon, q, _clock = make_monitor()
        mon.clock = __import__("time").monotonic  # real waits for the thread
        mon.period_s = 0.01
        q.depth = 100
        stop = threading.Event()
        t = threading.Thread(
            target=mon.run_forever, args=(stop,), daemon=True
        )
        t.start()
        deadline = __import__("time").monotonic() + 5.0
        while (
            mon.level != "SHED"
            and __import__("time").monotonic() < deadline
        ):
            __import__("time").sleep(0.01)
        stop.set()
        t.join(timeout=5)
        assert not t.is_alive()
        assert mon.level == "SHED"


@pytest.mark.slow
class TestOverloadStormSweep:
    """The seeded overload_storm chaos mode: rounds of spot flood + prod
    trickle on a virtual clock. Invariants per round: no
    oversubscription, no split gang, prod admission never starved by
    the flood; at the end: the ladder visited SHED, shed pods all bound
    (zero lost), features restored."""

    SEED = int(os.environ.get("CHAOS_SEED", "1337"))

    def test_storm_sheds_spot_protects_prod_and_recovers(self):
        from yoda_tpu.testing.chaos import build_overload_storm, storm_stream

        seed = self.SEED
        stack, _agent, clock = build_overload_storm(seed)
        ov = stack.metrics.overload
        cluster = stack.cluster
        created: list[str] = []
        prod_created: dict[str, int] = {}  # key -> arrival round
        bound_rounds: dict[str, int] = {}
        peak = 0
        storm_rounds = 8
        for r in range(storm_rounds):
            prod_pods, spot_pods = storm_stream(seed, r)
            for p in prod_pods + spot_pods:
                cluster.create_pod(p)
                created.append(p.key)
            for p in prod_pods:
                prod_created[p.key] = r
            clock.now += 2.0
            ov.evaluate()
            stack.scheduler.run_until_idle(max_wall_s=10.0)
            peak = max(peak, ov.level_idx)
            # Departures: pods bound 2+ rounds ago finish.
            for key in list(bound_rounds):
                if r - bound_rounds[key] >= 2:
                    cluster.delete_pod(key)
                    created.remove(key)
                    del bound_rounds[key]
            for key in created:
                pod = cluster.get_pod(key)
                if pod is not None and pod.node_name:
                    bound_rounds.setdefault(key, r)
            # Invariant: never oversubscribed.
            for ni in stack.informer.snapshot().infos():
                assert stack.accountant.chips_in_use(ni.name) <= len(
                    ni.tpu.healthy_chips()
                ), ni.name
            # Prod-tier protection: mid-storm, a prod pod waits at most
            # for one departure wave (2 rounds) — priority ordering +
            # shed keep the flood from fencing it out of freed capacity.
            for key, r0 in prod_created.items():
                pod = cluster.get_pod(key)
                if pod is not None and r - r0 >= 2:
                    assert pod.node_name, (r, key, ov.level)
        assert peak == SHED, f"the storm never reached SHED (peak {peak})"
        assert ov.shed_total > 0
        # Calm: arrivals stop, the ladder steps down, shed work binds.
        # The drain sawtooths (each step-down releases backlog, which
        # re-pressures the ladder until enough of it has bound) — ~60
        # virtual-time rounds at this shape; 100 bounds the flake risk.
        for _ in range(100):
            clock.now += 5.0
            ov.evaluate()
            stack.scheduler.run_until_idle(max_wall_s=10.0)
            for key in list(bound_rounds):
                cluster.delete_pod(key)
                created.remove(key)
                del bound_rounds[key]
            for key in created:
                pod = cluster.get_pod(key)
                if pod is not None and pod.node_name:
                    bound_rounds.setdefault(key, 99)
            if ov.level_idx == NOMINAL and not created:
                break
        assert ov.level_idx == NOMINAL, ov.level
        assert not created, f"{len(created)} pod(s) never bound: {created[:8]}"
        # Feature restore: tracing sampling came back with the ladder.
        assert stack.metrics.tracer.sample_rate == 1.0
        # No split gangs ever landed: every gang's bound members are
        # all-or-nothing at the end.
        assert not stack.accountant.staged_uids()
        stack.gang.close()