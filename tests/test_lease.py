"""Leader election over the coordination.k8s.io/v1 Lease API.

The reference inherits leader election wholesale from upstream
kube-scheduler (reference deploy/yoda-scheduler.yaml:11-14); here the
mechanism is first-party (yoda_tpu/cluster/lease.py) and testable against
the fake API server: acquire, renew, expiry takeover, orderly release, and
the two-replica failover scenario end to end through the CLI.
"""

from __future__ import annotations

import functools
import threading

import pytest

from yoda_tpu.api.types import PodSpec, make_node
from yoda_tpu.cluster import KubeApiClient, KubeApiConfig, KubeCluster, LeaderElector
from yoda_tpu.testing import FakeKubeApiServer
from yoda_tpu.testing import wait_until as _wait_until

wait_until = functools.partial(_wait_until, timeout_s=15.0)


@pytest.fixture()
def server(monkeypatch):
    with FakeKubeApiServer() as srv:
        monkeypatch.setenv("YODA_KUBE_API_URL", srv.base_url)
        yield srv


@pytest.fixture()
def api(server):
    return KubeApiClient(KubeApiConfig(base_url=server.base_url))


def elector(api, identity, clock=None, **kw):
    kw.setdefault("namespace", "kube-system")
    kw.setdefault("name", "test-lease")
    if clock is not None:
        kw["clock"] = clock
    return LeaderElector(api, identity=identity, **kw)


class TestAcquireRenew:
    def test_acquires_absent_lease(self, api):
        a = elector(api, "a")
        assert a.try_acquire_or_renew()
        view = a.observe()
        assert view.holder == "a"
        assert view.duration_s == 15
        assert view.transitions == 0

    def test_second_candidate_stays_standby(self, api):
        a, b = elector(api, "a"), elector(api, "b")
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()
        assert a.observe().holder == "a"

    def test_holder_renews(self, api):
        t = [100.0]
        a = elector(api, "a", clock=lambda: t[0])
        assert a.try_acquire_or_renew()
        first = a.observe().renew_unix
        t[0] = 105.0
        assert a.try_acquire_or_renew()
        assert a.observe().renew_unix == pytest.approx(105.0)
        assert a.observe().renew_unix > first

    def test_takeover_after_expiry(self, api):
        a = elector(api, "a", clock=lambda: 0.0)
        b = elector(api, "b", clock=lambda: 1000.0)  # lease long expired
        assert a.try_acquire_or_renew()
        assert b.try_acquire_or_renew()
        view = b.observe()
        assert view.holder == "b"
        assert view.transitions == 1

    def test_no_takeover_of_valid_lease(self, api):
        a = elector(api, "a", clock=lambda: 0.0)
        b = elector(api, "b", clock=lambda: 10.0)  # within 15s duration
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()

    def test_release_lets_standby_acquire(self, api):
        a, b = elector(api, "a"), elector(api, "b")
        assert a.try_acquire_or_renew()
        assert not b.try_acquire_or_renew()
        a.release()
        assert a.observe().holder == ""
        assert b.try_acquire_or_renew()
        assert b.observe().holder == "b"

    def test_release_of_foreign_lease_is_noop(self, api):
        a, b = elector(api, "a"), elector(api, "b")
        assert a.try_acquire_or_renew()
        b.release()
        assert a.observe().holder == "a"

    def test_identity_required(self, api):
        with pytest.raises(ValueError, match="identity"):
            LeaderElector(api, identity="")

    def test_acquire_time_survives_renewal(self, api):
        # PUT replaces the whole spec on real API servers; the elector must
        # carry acquireTime through every renew.
        t = [100.0]
        a = elector(api, "a", clock=lambda: t[0])
        assert a.try_acquire_or_renew()
        acquired = a.observe().acquire_time
        assert acquired
        t[0] = 105.0
        assert a.try_acquire_or_renew()
        assert a.observe().acquire_time == acquired

    def test_margin_validation(self, api):
        with pytest.raises(ValueError, match="renew"):
            LeaderElector(
                api, identity="a", lease_duration_s=15.0, renew_deadline_s=20.0
            )
        with pytest.raises(ValueError, match="renew"):
            # Detection granularity must fit inside the safety margin.
            LeaderElector(
                api,
                identity="a",
                lease_duration_s=15.0,
                renew_deadline_s=14.5,
                renew_period_s=2.0,
            )

    def test_renew_deadline_stands_down_before_lease_expiry(self, api):
        # The holder must report loss once renew_deadline_s passes without a
        # successful renew — strictly before a standby could acquire at
        # lease_duration_s.
        t = [0.0]
        a = elector(api, "a", clock=lambda: t[0])
        assert a.try_acquire_or_renew()
        a._leading.set()
        # Simulate renew failures by advancing past the deadline only.
        t[0] = a.renew_deadline_s + 0.1
        assert t[0] < a.lease_duration_s
        # Standby cannot acquire yet at this clock...
        b = elector(api, "b", clock=lambda: t[0])
        assert not b.try_acquire_or_renew()
        # ...but the leader's loss condition is already met.
        assert t[0] - a._last_renew >= a.renew_deadline_s


class TestRunLoop:
    def _start(self, el, stop, started, stopped):
        t = threading.Thread(
            target=el.run,
            args=(stop,),
            kwargs={
                "on_started_leading": started.set,
                "on_stopped_leading": stopped.set,
            },
            daemon=True,
        )
        t.start()
        return t

    def test_failover_on_orderly_stop(self, api):
        a = elector(api, "a", renew_period_s=0.05)
        b = elector(api, "b", renew_period_s=0.05)
        stop_a, stop_b = threading.Event(), threading.Event()
        a_up, a_down = threading.Event(), threading.Event()
        b_up, b_down = threading.Event(), threading.Event()
        ta = self._start(a, stop_a, a_up, a_down)
        assert a_up.wait(5), "first candidate acquired"
        self._start(b, stop_b, b_up, b_down)
        assert not b_up.wait(0.5), "standby must not lead while lease is held"
        stop_a.set()
        ta.join(timeout=5)
        assert b_up.wait(5), "standby took over after release"
        assert a.observe().holder == "b"
        stop_b.set()

    def test_loss_reported_when_lease_stolen(self, api):
        from yoda_tpu.cluster.lease import lease_path

        a = elector(api, "a", renew_period_s=0.05)
        stop = threading.Event()
        up, down = threading.Event(), threading.Event()
        self._start(a, stop, up, down)
        assert up.wait(5)
        # Another controller force-takes the lease (valid, far-future
        # renew). The elector renews every 50 ms, so the observed
        # resourceVersion can go stale between observe() and PUT — retry
        # the write on 409 like any real controller would.
        from yoda_tpu.cluster.kube import KubeApiError

        for _ in range(50):
            view = a.observe()
            try:
                api.request(
                    "PUT",
                    lease_path("kube-system", "test-lease"),
                    body={
                        "metadata": {
                            "name": "test-lease",
                            "namespace": "kube-system",
                            "resourceVersion": view.resource_version,
                        },
                        "spec": {
                            "holderIdentity": "intruder",
                            "leaseDurationSeconds": 9999,
                            "renewTime": "2999-01-01T00:00:00.000000Z",
                        },
                    },
                )
                break
            except KubeApiError as e:
                if e.status != 409:
                    raise
        else:
            pytest.fail("intruder PUT lost the write race 50 times")
        assert down.wait(5), "loss callback fired after takeover observed"
        assert not a.is_leader()
        stop.set()


class TestCliFailover:
    """VERDICT item 3's done-criterion: two stacks against one fake API
    server — exactly one schedules; kill the holder, the other takes over."""

    def _run_cli(self, argv):
        from yoda_tpu.cli import main

        stop = threading.Event()
        t = threading.Thread(
            target=main, args=(argv,), kwargs={"stop": stop}, daemon=True
        )
        t.start()
        return stop, t

    def test_two_replicas_one_schedules_then_failover(self, server):
        seed = KubeCluster(
            KubeApiClient(KubeApiConfig(base_url=server.base_url, watch_timeout_s=2))
        )
        seed.put_tpu_metrics(make_node("n1", chips=8))

        def holder():
            lease = server.get_object("Lease", "kube-system/yoda-tpu-scheduler")
            return (lease or {}).get("spec", {}).get("holderIdentity")

        argv = ["--metrics-port", "-1", "--leader-elect", "--lease-identity"]
        stop_a, ta = self._run_cli(argv + ["replica-a"])
        wait_until(lambda: holder() == "replica-a", msg="replica-a acquired")
        stop_b, tb = self._run_cli(argv + ["replica-b"])

        try:
            seed.create_pod(PodSpec("ha-pod-1", labels={"tpu/chips": "1"}))
            wait_until(
                lambda: (server.get_object("Pod", "default/ha-pod-1") or {})
                .get("spec", {})
                .get("nodeName")
                == "n1",
                msg="leader bound the first pod",
            )
            assert holder() == "replica-a", "standby must not have taken the lease"

            stop_a.set()
            ta.join(timeout=10)
            wait_until(lambda: holder() == "replica-b", msg="failover to replica-b")

            seed.create_pod(PodSpec("ha-pod-2", labels={"tpu/chips": "1"}))
            wait_until(
                lambda: (server.get_object("Pod", "default/ha-pod-2") or {})
                .get("spec", {})
                .get("nodeName")
                == "n1",
                msg="new leader bound the second pod",
            )
        finally:
            stop_a.set()
            stop_b.set()
            ta.join(timeout=10)
            tb.join(timeout=10)
            seed.stop()
