"""Gang scheduling + ICI topology tests: BASELINE config 4 (atomic multi-host
slice placement) plus admission, rollback, timeout, and livelock-release
scenarios — the hard parts ranked #1-2 in SURVEY.md §7."""

import pytest

from yoda_tpu.agent import FakeTpuAgent
from yoda_tpu.api.types import PodSpec
from yoda_tpu.config import SchedulerConfig
from yoda_tpu.plugins.yoda.topology import find_subblock, normalize_dims
from yoda_tpu.standalone import build_stack


def make_stack(mode="batch", **cfg):
    stack = build_stack(config=SchedulerConfig(mode=mode, **cfg))
    return stack, FakeTpuAgent(stack.cluster)


def gang_pods(name, n, chips=4, extra=None):
    labels = {"tpu/gang": name, "tpu/gang-size": str(n), "tpu/chips": str(chips)}
    labels.update(extra or {})
    return [PodSpec(f"{name}-{i}", labels=dict(labels)) for i in range(n)]


def topo_pods(name, topology, chips=4, extra=None):
    labels = {"tpu/gang": name, "tpu/topology": topology, "tpu/chips": str(chips)}
    labels.update(extra or {})
    import math

    n = math.prod(int(d) for d in topology.split("x"))
    return [PodSpec(f"{name}-{i}", labels=dict(labels)) for i in range(n)]


class TestTopologyMatching:
    def test_normalize(self):
        assert normalize_dims((4,)) == (4, 1, 1)
        assert normalize_dims((2, 2)) == (2, 2, 1)

    def test_find_subblock_exact(self):
        free = {(x, y, z) for x in range(2) for y in range(2) for z in range(2)}
        block = find_subblock(free, (2, 2, 2))
        assert block is not None and len(block) == 8

    def test_find_subblock_within_larger(self):
        free = {(x, y, 0) for x in range(4) for y in range(4)}
        block = find_subblock(free, (2, 2, 1))
        assert block == [(0, 0, 0), (0, 1, 0), (1, 0, 0), (1, 1, 0)]

    def test_find_subblock_axis_permutation(self):
        free = {(0, y, z) for y in range(2) for z in range(4)}  # 1x2x4 region
        assert find_subblock(free, (4, 2, 1)) is not None

    def test_find_subblock_respects_holes(self):
        free = {(x, y, 0) for x in range(2) for y in range(2)} - {(0, 1, 0)}
        assert find_subblock(free, (2, 2, 1)) is None
        assert find_subblock(free, (2, 1, 1)) is not None

    def test_find_subblock_must_include(self):
        free = {(x, y, 0) for x in range(4) for y in range(2)}
        # Without pins the lowest-origin 2x2 wins; a pin at (2,0,0) forces
        # the block that contains it.
        block = find_subblock(
            free - {(2, 0, 0)}, (2, 2, 1), must_include={(2, 0, 0)}
        )
        assert block is not None and (2, 0, 0) in block
        # Pin outside any feasible block -> no plan.
        assert (
            find_subblock({(0, 0, 0)}, (2, 1, 1), must_include={(3, 0, 0)}) is None
        )

    def test_fragmented_no_contiguous_block(self):
        free = {(0, 0, 0), (2, 0, 0), (0, 2, 0), (2, 2, 0)}  # checkerboard
        assert find_subblock(free, (2, 1, 1)) is None


@pytest.mark.parametrize("mode", ["batch", "loop"])
class TestGangAtomicity:
    def test_gang_binds_together(self, mode):
        stack, agent = make_stack(mode)
        for i in range(4):
            agent.add_host(f"host-{i}", generation="v5p", chips=4)
        agent.publish_all()
        for pod in gang_pods("job-a", 4, chips=4):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=10)
        bound = {p.name: p.node_name for p in stack.cluster.list_pods()}
        assert all(v is not None for v in bound.values()), bound
        assert stack.gang.gang_status("job-a") == (4, 0, 4)

    def test_incomplete_gang_binds_nothing(self, mode):
        # Only 3 of 4 members created: nothing must bind, no chips leak.
        stack, agent = make_stack(mode, gang_permit_timeout_s=0.3)
        for i in range(4):
            agent.add_host(f"host-{i}", generation="v5p", chips=4)
        agent.publish_all()
        for pod in gang_pods("job-a", 4)[:3]:
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=2)
        assert all(p.node_name is None for p in stack.cluster.list_pods())
        # After timeout + cascade, reservations must be fully rolled back.
        assert all(
            stack.accountant.chips_in_use(f"host-{i}") == 0 for i in range(4)
        )

    def test_late_member_completes_gang(self, mode):
        stack, agent = make_stack(mode)
        for i in range(2):
            agent.add_host(f"host-{i}", generation="v5p", chips=4)
        agent.publish_all()
        pods = gang_pods("job-b", 2)
        stack.cluster.create_pod(pods[0])
        stack.scheduler.run_until_idle(max_wall_s=2)
        assert stack.cluster.get_pod(f"default/{pods[0].name}").node_name is None
        stack.cluster.create_pod(pods[1])
        stack.scheduler.run_until_idle(max_wall_s=5)
        assert all(p.node_name for p in stack.cluster.list_pods())

    def test_no_partial_reservation_when_gang_cannot_fit(self, mode):
        # Admission check: a 4-member gang on a 2-host fleet (1 slot each)
        # must not reserve anything.
        stack, agent = make_stack(mode)
        agent.add_host("host-0", generation="v5p", chips=4)
        agent.add_host("host-1", generation="v5p", chips=4)
        agent.publish_all()
        for pod in gang_pods("too-big", 4, chips=4):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=2)
        assert all(p.node_name is None for p in stack.cluster.list_pods())
        assert stack.accountant.chips_in_use("host-0") == 0
        assert stack.accountant.chips_in_use("host-1") == 0

    def test_gang_members_can_share_host(self, mode):
        # Non-topology gang: 4 members x 2 chips fit one v5e-8 host.
        stack, agent = make_stack(mode)
        agent.add_host("big-host", generation="v5e", chips=8)
        agent.publish_all()
        for pod in gang_pods("packed", 4, chips=2):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=5)
        assert all(p.node_name == "big-host" for p in stack.cluster.list_pods())


@pytest.mark.parametrize("mode", ["batch", "loop"])
class TestBaselineConfig4Topology:
    def test_v5p_slice_gang_with_ici_affinity(self, mode):
        # Config 4: gang-scheduled v5p slice — 4 pods, topology 2x2x1,
        # atomically across the 4 hosts of one slice.
        stack, agent = make_stack(mode)
        agent.add_slice("v5p-a", generation="v5p", host_topology=(2, 2, 1))
        agent.publish_all()
        for pod in topo_pods("train", "2x2x1", chips=4):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=10)
        placements = {p.name: p.node_name for p in stack.cluster.list_pods()}
        assert all(v for v in placements.values()), placements
        assert len(set(placements.values())) == 4  # one member per host
        assert all(v.startswith("v5p-a") for v in placements.values())

    def test_topology_gang_picks_slice_with_room(self, mode):
        # Slice A is half-occupied; the 2x2x1 gang must land on slice B.
        stack, agent = make_stack(mode)
        agent.add_slice("slice-a", generation="v5p", host_topology=(2, 2, 1))
        agent.add_slice("slice-b", generation="v5p", host_topology=(2, 2, 1))
        agent.publish_all()
        blocker = PodSpec("blocker", labels={"tpu/chips": "4"})
        stack.cluster.create_pod(blocker)
        stack.scheduler.run_until_idle(max_wall_s=5)
        blocked_host = stack.cluster.get_pod("default/blocker").node_name
        blocked_slice = "slice-a" if blocked_host.startswith("slice-a") else "slice-b"
        other = "slice-b" if blocked_slice == "slice-a" else "slice-a"
        for pod in topo_pods("t", "2x2x1", chips=4):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=10)
        gang_hosts = {
            p.node_name for p in stack.cluster.list_pods() if p.name.startswith("t-")
        }
        assert all(h and h.startswith(other) for h in gang_hosts), gang_hosts

    def test_topology_gang_unschedulable_without_contiguous_block(self, mode):
        # 2x2x1 wanted; only fragmented hosts are free.
        stack, agent = make_stack(mode, gang_permit_timeout_s=0.3)
        agent.add_slice("s", generation="v5p", host_topology=(2, 2, 1))
        agent.publish_all()
        # Occupy two diagonal hosts -> no contiguous 2x2 block remains free.
        for name, host in [("b0", "s-0"), ("b1", "s-3")]:
            # s-0 is (0,0,0), s-3 is (1,1,0) per itertools.product order
            p = PodSpec(name, labels={"tpu/chips": "4"})
            p.node_name = host
            p.phase = "Running"
            stack.cluster.create_pod(p)
        agent.publish_all()
        for pod in topo_pods("t", "2x2x1", chips=4):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=2)
        gang = [p for p in stack.cluster.list_pods() if p.name.startswith("t-")]
        assert all(p.node_name is None for p in gang)


class TestGangConsistency:
    def test_mismatched_gang_size_is_unresolvable(self):
        stack, agent = make_stack()
        agent.add_host("h", generation="v5p", chips=4)
        agent.publish_all()
        stack.cluster.create_pod(
            PodSpec("a", labels={"tpu/gang": "g", "tpu/gang-size": "2"})
        )
        stack.cluster.create_pod(
            PodSpec("b", labels={"tpu/gang": "g", "tpu/gang-size": "3"})
        )
        stack.scheduler.run_until_idle(max_wall_s=2)
        results = {r.pod_key: r for r in stack.scheduler.stats.results}
        assert any("size/topology" in r.message for r in results.values())

    def test_two_gangs_contending_one_completes(self):
        # Livelock scenario (SURVEY.md §7 hard part 1): two 2-member gangs,
        # capacity for one. With admission seeing reservations plus timeout
        # rollback, exactly one gang must fully bind.
        stack, agent = make_stack(gang_permit_timeout_s=0.5)
        agent.add_host("h0", generation="v5p", chips=4)
        agent.add_host("h1", generation="v5p", chips=4)
        agent.publish_all()
        for pod in gang_pods("gang-a", 2, chips=4) + gang_pods("gang-b", 2, chips=4):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=5)
        bound_by_gang = {"gang-a": 0, "gang-b": 0}
        for p in stack.cluster.list_pods():
            if p.node_name:
                bound_by_gang[p.labels["tpu/gang"]] += 1
        assert sorted(bound_by_gang.values()) == [0, 2], bound_by_gang

    def test_bind_failure_self_heals(self):
        # Regression: a bind that fails AFTER Permit released the gang must
        # not wedge it — the gang optimistically counts the member bound at
        # resolution; PreFilter drops the stale entry on the retry.
        from yoda_tpu.framework.interfaces import BindPlugin, Code, Status

        class FlakyBinder(BindPlugin):
            name = "flaky-binder"

            def __init__(self):
                self.tripped = False

            def bind(self, state, pod, node_name):
                if not self.tripped and pod.name == "job-f-1":
                    self.tripped = True
                    return Status.error("transient bind failure")
                return Status(code=Code.SKIP)

        from yoda_tpu.config import SchedulerConfig
        from yoda_tpu.standalone import build_stack as build

        flaky = FlakyBinder()
        stack = build(
            config=SchedulerConfig(mode="batch"), extra_plugins=[flaky]
        )
        agent = FakeTpuAgent(stack.cluster)
        for i in range(4):
            agent.add_host(f"host-{i}", generation="v5p", chips=4)
        agent.publish_all()
        for pod in gang_pods("job-f", 4, chips=4):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=10)
        assert flaky.tripped
        bound = {p.name: p.node_name for p in stack.cluster.list_pods()}
        assert all(v is not None for v in bound.values()), bound
        assert stack.gang.gang_status("job-f") == (4, 0, 4)

    def test_topology_gang_reconstructed_after_restart(self):
        # Regression: a topology gang with a pre-bound member (scheduler
        # restart) must replan AROUND that member's host, not wedge.
        stack, agent = make_stack()
        agent.add_slice("s", generation="v5p", host_topology=(2, 2, 1))
        agent.publish_all()
        pods = topo_pods("resume", "2x2x1", chips=4)
        pods[0].node_name = "s-1"
        pods[0].phase = "Running"
        stack.cluster.create_pod(pods[0])
        agent.publish_all()  # metrics now show s-1's chips consumed

        from yoda_tpu.standalone import build_stack as rebuild

        stack2 = rebuild(cluster=stack.cluster)
        assert stack2.gang.gang_status("resume") == (4, 0, 1)
        for p in pods[1:]:
            stack2.cluster.create_pod(p)
        stack2.scheduler.run_until_idle(max_wall_s=10)
        placements = {p.name: p.node_name for p in stack2.cluster.list_pods()}
        assert all(placements.values()), placements
        assert len(set(placements.values())) == 4

    def test_gang_reconstructed_after_restart(self):
        # Half a gang bound, scheduler restarts: the new stack must count the
        # bound members and complete the gang when the rest arrive.
        stack, agent = make_stack()
        for i in range(2):
            agent.add_host(f"h{i}", generation="v5p", chips=4)
        agent.publish_all()
        pods = gang_pods("resume", 2)
        # Simulate pre-bound member (as if bound before restart).
        pods[0].node_name = "h0"
        pods[0].phase = "Running"
        stack.cluster.create_pod(pods[0])

        from yoda_tpu.standalone import build_stack as rebuild

        stack2 = rebuild(cluster=stack.cluster)
        assert stack2.gang.gang_status("resume") == (2, 0, 1)
        stack2.cluster.create_pod(pods[1])
        stack2.scheduler.run_until_idle(max_wall_s=5)
        assert stack2.cluster.get_pod(f"default/{pods[1].name}").node_name is not None


class TestGangBatchedDispatch:
    """VERDICT r2 #5: ONE YodaBatch kernel dispatch places the whole gang —
    siblings are served host-side from the dispatch's claimable-chips plan,
    shrinking the inter-member atomicity window to a single evaluation."""

    @staticmethod
    def _batch(stack):
        from yoda_tpu.plugins.yoda import YodaBatch

        return next(
            p for p in stack.framework.batch_plugins if isinstance(p, YodaBatch)
        )

    @staticmethod
    def _warm(stack):
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=60.0)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=5.0)

    def test_one_dispatch_per_topology_gang(self):
        stack, agent = make_stack()
        agent.add_slice("s", host_topology=(2, 2, 1))
        agent.add_slice("t", host_topology=(2, 2, 1))
        agent.publish_all()
        self._warm(stack)
        batch = self._batch(stack)
        d0 = batch.dispatch_count
        pods = topo_pods("tg", "2x2x1", chips=4)
        for p in pods:
            stack.cluster.create_pod(p)
        stack.scheduler.run_until_idle(max_wall_s=15.0)
        placed = {p.name: p.node_name for p in stack.cluster.list_pods()}
        assert all(placed.values()), placed
        hosts = set(placed.values())
        assert len(hosts) == 4
        assert len({h.rsplit("-", 1)[0] for h in hosts}) == 1
        assert batch.dispatch_count == d0 + 1
        # All four members (gathered co-queued) answered from the single
        # gang-fused dispatch; the lazy plan path never needed to build.
        assert batch.gang_burst_served == 4
        assert not batch._gang_bursts  # fully-served set released
        assert not batch._gang_plans
        # The counters are scraped via /metrics as counter-typed series.
        rendered = stack.metrics.registry.render_prometheus()
        assert "# TYPE yoda_gang_fused_served_total counter" in rendered
        assert "# TYPE yoda_kernel_dispatches_total counter" in rendered
        served = next(
            m
            for m in stack.metrics.registry._metrics
            if m.name == "yoda_gang_fused_served_total"
        )
        assert served.value() == 4

    def test_one_dispatch_per_plain_gang_sharing_hosts(self):
        stack, agent = make_stack()
        agent.add_host("h0", generation="v5p", chips=4)
        agent.add_host("h1", generation="v5p", chips=4)
        agent.publish_all()
        self._warm(stack)
        batch = self._batch(stack)
        d0 = batch.dispatch_count
        for p in gang_pods("pg", 4, chips=2):  # 2 members per 4-chip host
            stack.cluster.create_pod(p)
        stack.scheduler.run_until_idle(max_wall_s=15.0)
        placed = {p.name: p.node_name for p in stack.cluster.list_pods()}
        assert all(placed.values()), placed
        # The host-side claimable decrement must not oversubscribe a host.
        from collections import Counter

        per_host = Counter(placed.values())
        assert all(c <= 2 for c in per_host.values()), per_host
        assert batch.dispatch_count == d0 + 1

    def test_foreign_interference_invalidates_plan(self):
        """A foreign pod reserving onto a planned node between member
        cycles must invalidate the plan (reserved_fn validation) — the
        siblings fall back to fresh dispatches and the gang still binds
        correctly with no oversubscription."""
        stack, agent = make_stack(gang_permit_timeout_s=300.0)
        hosts = [f"h{i}" for i in range(4)]
        for h in hosts:
            agent.add_host(h, generation="v5p", chips=4)
        agent.publish_all()
        self._warm(stack)
        batch = self._batch(stack)
        # Member 0 schedules alone: plan for all 3 members is built.
        pods = gang_pods("fg", 3, chips=4)
        stack.cluster.create_pod(pods[0])
        stack.scheduler.run_until_idle(max_wall_s=5.0)
        assert stack.gang.gang_status("fg")[1] == 1
        assert "fg" in batch._gang_plans
        # A foreign pod lands on the node planned for member 1 (same
        # argmax tie-break over the same free set).
        planned = batch._gang_plans["fg"].picks[1]
        stack.cluster.create_pod(
            PodSpec("foreign", labels={"tpu/chips": "4", "tpu/priority": "9"})
        )
        stack.scheduler.run_until_idle(max_wall_s=5.0)
        foreign = stack.cluster.get_pod("default/foreign")
        assert foreign is not None and foreign.node_name == planned
        # Remaining members arrive: the plan must NOT serve the taken node.
        for p in pods[1:]:
            stack.cluster.create_pod(p)
        stack.scheduler.run_until_idle(max_wall_s=15.0)
        placed = {
            p.name: p.node_name
            for p in stack.cluster.list_pods()
            if p.name.startswith("fg")
        }
        assert all(placed.values()), placed
        from collections import Counter

        per_host = Counter(placed.values())
        assert all(c <= 1 for c in per_host.values()), per_host
        assert planned not in placed.values()  # the taken node was not served
        # No host holds more than its 4 chips.
        for h in hosts:
            assert stack.accountant.chips_in_use(h) <= 4, h


class TestDeleteEventFastPath:
    """Satellite of the crash-safe failover PR: a watch ``deleted`` for a
    queued / backoff / Permit-parked pod takes effect AT EVENT TIME —
    before this, only host deletions cancelled gang waits, and a deleted
    member left its siblings holding reservations for the full 120 s
    permit timeout."""

    def test_deleting_parked_member_cancels_gang_wait_immediately(self):
        stack, agent = make_stack()
        for i in range(4):
            agent.add_host(f"h{i}", generation="v5p", chips=4)
        agent.publish_all()
        for pod in gang_pods("g", 3)[:2]:
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=5)
        assert len(stack.framework.waiting_pods()) == 2
        assert sum(stack.accountant.chips_by_node().values()) == 8
        # The deletion alone — no expiry sweep, no scheduling cycle —
        # resolves the deleted member's wait and cascades the sibling,
        # releasing every reservation synchronously with the event.
        stack.cluster.delete_pod("default/g-0")
        assert stack.framework.waiting_pods() == []
        assert sum(stack.accountant.chips_by_node().values()) == 0
        # The surviving sibling is re-queued, not lost: a third member's
        # arrival later completes the (now 2-member-short) gang normally.
        for pod in gang_pods("g", 3)[1:]:
            if stack.cluster.get_pod(f"default/{pod.name}") is None:
                stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=10)
        bound = [p for p in stack.cluster.list_pods() if p.node_name]
        # g-0 is gone; g-1 and g-2 alone cannot complete a size-3 gang.
        assert bound == []

    def test_deleting_backoff_member_removes_queue_entry(self):
        stack, agent = make_stack()
        agent.add_host("tiny", generation="v5p", chips=2)
        agent.publish_all()
        # One member of a gang the fleet cannot admit: parks in backoff.
        stack.cluster.create_pod(gang_pods("big", 4)[0])
        stack.scheduler.run_until_idle(max_wall_s=5)
        assert len(stack.queue) == 1
        cycles = len(stack.scheduler.stats.results)
        stack.cluster.delete_pod("default/big-0")
        # Removed at event time: no phantom depth, no "gone" cycle later.
        assert len(stack.queue) == 0
        stack.scheduler.run_until_idle(max_wall_s=5)
        assert len(stack.scheduler.stats.results) == cycles

    def test_deleting_queued_member_fuses_remaining_gang(self):
        # A deleted ACTIVE-queue member must not wedge its siblings: the
        # entry disappears with the event and the others schedule on
        # their own barrier when the replacement arrives.
        stack, agent = make_stack()
        for i in range(2):
            agent.add_host(f"h{i}", generation="v5p", chips=4)
        agent.publish_all()
        pods = gang_pods("q", 2)
        stack.cluster.create_pod(pods[0])
        # Delete while still queued (no cycle has run).
        stack.cluster.delete_pod("default/q-0")
        assert len(stack.queue) == 0
        # A fresh copy of the gang completes whole.
        for pod in gang_pods("q", 2):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=10)
        placements = {
            p.name: p.node_name
            for p in stack.cluster.list_pods()
            if p.node_name
        }
        assert sorted(placements) == ["q-0", "q-1"]


class TestNodeFailureMidGang:
    """SURVEY.md §5 fault-injection: a planned host dies while members wait
    at the Permit barrier. The waitlist must expire, the cascade must roll
    back EVERY reservation (including those on surviving hosts), and the
    gang must re-plan onto an intact slice and complete."""

    def test_host_death_during_permit_wait(self):
        # Permit timeout far beyond the test budget: recovery must be
        # EVENT-driven (the host-death handler), not the timeout backstop.
        stack, agent = make_stack(gang_permit_timeout_s=300.0)
        a_hosts = agent.add_slice("slice-a", host_topology=(2, 2, 1))
        b_hosts = agent.add_slice("slice-b", host_topology=(2, 2, 1))
        agent.publish_all()

        # Pay the kernel compile before the permit-timeout-sensitive phase.
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=60.0)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=5.0)

        pods = topo_pods("g", "2x2", chips=4)
        for p in pods[:3]:
            stack.cluster.create_pod(p)
        stack.scheduler.run_until_idle(max_wall_s=0.4)  # < permit timeout
        status = stack.gang.gang_status("g")
        assert status is not None and status[1] == 3, f"want 3 waiting: {status}"
        reserved_hosts = [
            h for h in a_hosts + b_hosts if stack.accountant.chips_in_use(h) > 0
        ]
        assert len(reserved_hosts) == 3
        (planned_slice,) = {h.rsplit("-", 1)[0] for h in reserved_hosts}

        # Fault injection: one reserved host dies (agent deletes its CR).
        agent.remove_host(reserved_hosts[0])

        # The 4th member arrives; the dead host blocks the old plan, the
        # waitlist expires, the cascade rolls everything back, and the gang
        # re-plans onto the intact slice.
        stack.cluster.create_pod(pods[3])
        stack.scheduler.run_until_idle(max_wall_s=15.0)

        bound = [
            stack.cluster.get_pod(p.key)
            for p in pods
        ]
        hosts = {p.node_name for p in bound if p and p.node_name}
        assert all(p is not None and p.node_name for p in bound), (
            f"gang did not complete after host death: "
            f"{[(p.name, p.node_name) for p in bound if p]}"
        )
        other_slice = {"slice-a": "slice-b", "slice-b": "slice-a"}[planned_slice]
        assert len(hosts) == 4
        assert {h.rsplit("-", 1)[0] for h in hosts} == {other_slice}
        # No leaked reservations on the first slice's survivors.
        for h in a_hosts + b_hosts:
            if h.rsplit("-", 1)[0] == planned_slice and h in hosts:
                continue
            if h == reserved_hosts[0]:
                continue
            if h.rsplit("-", 1)[0] == planned_slice:
                assert stack.accountant.chips_in_use(h) == 0, h

    def test_free_planned_host_death_cancels_waiters(self):
        """The dying host holds NO reservation (it is the plan's still-free
        slot): the broken plan must cancel the waiting members so the gang
        re-plans — not strand their reservations until the permit timeout."""
        stack, agent = make_stack(gang_permit_timeout_s=300.0)
        agent.add_slice("slice-a", host_topology=(2, 2, 1))
        agent.add_slice("slice-b", host_topology=(2, 2, 1))
        agent.publish_all()
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=60.0)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=5.0)

        pods = topo_pods("g", "2x2", chips=4)
        for p in pods[:3]:
            stack.cluster.create_pod(p)
        stack.scheduler.run_until_idle(max_wall_s=0.4)
        assert stack.gang.gang_status("g")[1] == 3
        free = stack.gang.planned_unassigned_hosts("g")
        assert free is not None and len(free) == 1

        agent.remove_host(free[0])  # the un-reserved planned slot dies

        stack.cluster.create_pod(pods[3])
        stack.scheduler.run_until_idle(max_wall_s=15.0)
        bound = [stack.cluster.get_pod(p.key) for p in pods]
        assert all(p is not None and p.node_name for p in bound), (
            f"{[(p.name, p.node_name) for p in bound if p]}"
        )
        hosts = {p.node_name for p in bound}
        assert len(hosts) == 4
        assert len({h.rsplit("-", 1)[0] for h in hosts}) == 1  # one slice

    def test_plain_gang_recovers_when_dead_host_returns(self):
        """A host that dies mid-wait and then REJOINS must be usable again:
        the dead-host blacklist clears on the host's re-publish (plain
        gangs never hit the topology replan path's clear site)."""
        stack, agent = make_stack(gang_permit_timeout_s=300.0)
        agent.add_host("h1", generation="v5p", chips=4)
        agent.add_host("h2", generation="v5p", chips=4)
        agent.publish_all()
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=60.0)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=5.0)

        pods = gang_pods("pg", 2, chips=4)
        stack.cluster.create_pod(pods[0])
        stack.scheduler.run_until_idle(max_wall_s=0.4)
        assert stack.gang.gang_status("pg")[1] == 1  # waiting
        host = next(
            h for h in ("h1", "h2") if stack.accountant.chips_in_use(h) > 0
        )
        agent.remove_host(host)           # dies mid-wait -> cascade
        stack.scheduler.run_until_idle(max_wall_s=5.0)
        agent.add_host(host, generation="v5p", chips=4)
        agent.publish_all()               # host rejoins -> un-blacklisted

        stack.cluster.create_pod(pods[1])
        stack.scheduler.run_until_idle(max_wall_s=15.0)
        bound = [stack.cluster.get_pod(p.key) for p in pods]
        assert all(p is not None and p.node_name for p in bound), (
            f"{[(p.name, p.node_name) for p in bound if p]}"
        )

    def test_dead_mark_cleared_only_by_same_kind(self):
        """A Node-object deletion mark survives the agent's CR republish
        (the node is still gone); only a Node re-add clears it."""
        from yoda_tpu.api.requests import GangSpec
        from yoda_tpu.api.types import K8sNode, make_node
        from yoda_tpu.cluster.fake import Event
        from yoda_tpu.plugins.yoda.gang import GangPlugin, _GangState

        g = GangPlugin()
        g._gangs["x"] = _GangState(spec=GangSpec(name="x", size=2, topology=None))
        g.handle(Event("deleted", "Node", K8sNode("h1")))
        assert "h1" in g._gangs["x"].dead_hosts
        g.handle(Event("modified", "TpuNodeMetrics", make_node("h1")))
        assert "h1" in g._gangs["x"].dead_hosts  # CR republish: still dead
        g.handle(Event("added", "Node", K8sNode("h1")))
        assert "h1" not in g._gangs["x"].dead_hosts

    def test_zombie_pod_watch_event_cannot_resurrect_membership(self):
        """A watch event for a lost member's still-existing pod (e.g. the
        node controller updating its status) must NOT re-add it to the
        gang: the Permit barrier would count a dead member toward
        completion, and a later replan would wedge pinning its dead host."""
        from yoda_tpu.api.requests import GangSpec
        from yoda_tpu.cluster.fake import Event
        from yoda_tpu.plugins.yoda.gang import GangPlugin, _GangState

        g = GangPlugin()
        gs = _GangState(spec=GangSpec(name="z", size=2, topology=None))
        g._gangs["z"] = gs
        zombie = PodSpec("z-0", labels={"tpu/gang": "z", "tpu/gang-size": "2"})
        zombie.node_name = "h-dead"
        gs.bound.add(zombie.key)
        gs.assigned[zombie.key] = "h-dead"
        g._on_host_gone("h-dead", "Node")
        # Simulate the replan-time drop, then the zombie's status update.
        gs.bound.discard(zombie.key)
        gs.assigned.pop(zombie.key, None)
        g.handle(Event("modified", "Pod", zombie))
        assert zombie.key not in gs.bound  # not resurrected
        # Once the host truly returns, reconstruction works again.
        from yoda_tpu.api.types import K8sNode

        g.handle(Event("added", "Node", K8sNode("h-dead")))
        g.handle(Event("modified", "Pod", zombie))
        assert zombie.key in gs.bound

    def test_bound_member_host_death_unwedges_replan(self):
        """ADVICE r2: a host holding a BOUND member (restart-reconstructed
        gang) dies. The lost membership must be dropped at the host-death
        event so the surviving members re-plan a fresh block immediately —
        not wedge every cycle pinning a dead host until pod GC."""
        stack, agent = make_stack(gang_permit_timeout_s=300.0)
        a_hosts = agent.add_slice("slice-a", host_topology=(2, 2, 1))
        agent.add_slice("slice-b", host_topology=(2, 2, 1))
        agent.publish_all()
        pods = topo_pods("resume", "2x2x1", chips=4)
        pods[0].node_name = a_hosts[1]
        pods[0].phase = "Running"
        stack.cluster.create_pod(pods[0])
        agent.publish_all()  # metrics show the bound member's chips consumed

        from yoda_tpu.standalone import build_stack as rebuild

        stack2 = rebuild(cluster=stack.cluster)
        assert stack2.gang.gang_status("resume") == (4, 0, 1)
        # Pay the kernel compile before the timing-sensitive phase.
        stack2.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack2.scheduler.run_until_idle(max_wall_s=60.0)
        stack2.cluster.delete_pod("default/warm")
        stack2.scheduler.run_until_idle(max_wall_s=5.0)

        agent.remove_host(a_hosts[1])  # the bound member's host dies
        for p in pods[1:]:
            stack2.cluster.create_pod(p)
        stack2.scheduler.run_until_idle(max_wall_s=3.0)
        # The lost member was dropped and the survivors planned a fresh
        # block: they park at the permit barrier. (Pre-fix: bound stayed 1
        # and every replan wedged on the dead pinned host.)
        assert stack2.gang.gang_status("resume") == (4, 3, 0)

        # Node-lifecycle GC deletes the lost pod; its controller recreates.
        stack2.cluster.delete_pod(pods[0].key)
        stack2.cluster.create_pod(
            PodSpec("resume-0r", labels=dict(pods[1].labels))
        )
        stack2.scheduler.run_until_idle(max_wall_s=15.0)
        placements = {
            p.name: p.node_name for p in stack2.cluster.list_pods()
        }
        assert all(placements.values()), placements
        hosts = set(placements.values())
        assert len(hosts) == 4
        assert {h.rsplit("-", 1)[0] for h in hosts} == {"slice-b"}


class TestMultislice:
    """tpu/multislice: one gang spanning M disjoint topology blocks — the
    Multislice pattern (ICI within each block, DCN between). All-or-
    nothing across ALL blocks; blocks pack into one big slice or spread
    across slices."""

    def test_label_parsing(self):
        from yoda_tpu.api.requests import LabelParseError, parse_request

        req = parse_request(
            {"tpu/gang": "m", "tpu/topology": "2x2", "tpu/multislice": "2"}
        )
        assert req.gang.slices == 2 and req.gang.size == 8
        with pytest.raises(LabelParseError, match="requires tpu/topology"):
            parse_request({"tpu/gang": "m", "tpu/gang-size": "4", "tpu/multislice": "2"})
        with pytest.raises(LabelParseError, match="implies 8"):
            parse_request(
                {
                    "tpu/gang": "m",
                    "tpu/topology": "2x2",
                    "tpu/multislice": "2",
                    "tpu/gang-size": "4",
                }
            )
        with pytest.raises(LabelParseError, match="must be >= 1"):
            parse_request(
                {"tpu/gang": "m", "tpu/topology": "2x2", "tpu/multislice": "0"}
            )

    def test_planner_two_blocks_across_slices(self):
        from yoda_tpu.plugins.yoda.topology import plan_multislice_placement

        stack, agent = make_stack()
        agent.add_slice("s-a", host_topology=(2, 2, 1))
        agent.add_slice("s-b", host_topology=(2, 2, 1))
        agent.publish_all()
        snap = stack.informer.snapshot()
        plan = plan_multislice_placement(
            snap, want_dims=(2, 2, 1), slices=2, host_ok=lambda ni: True
        )
        assert plan is not None and len(plan) == 8
        assert {h.rsplit("-", 1)[0] for h in plan} == {"s-a", "s-b"}

    def test_planner_two_blocks_pack_one_big_slice(self):
        from yoda_tpu.plugins.yoda.topology import plan_multislice_placement

        stack, agent = make_stack()
        agent.add_slice("big", host_topology=(4, 2, 1))  # 8 hosts
        agent.publish_all()
        snap = stack.informer.snapshot()
        plan = plan_multislice_placement(
            snap, want_dims=(2, 2, 1), slices=2, host_ok=lambda ni: True
        )
        assert plan is not None and len(plan) == 8  # both blocks fit inside

    def test_pack_blocks_backtracks_past_greedy_traps(self):
        """Review repro: an L-shaped free region fits two 2x1 blocks only
        if the first pick is NOT the lowest-origin block — greedy packing
        reported feasible placements as unschedulable."""
        from yoda_tpu.plugins.yoda.topology import pack_blocks

        free = {(0, 0, 0), (1, 0, 0), (2, 0, 0), (0, 1, 0)}
        blocks = pack_blocks(free, (2, 1, 1), 2)
        assert blocks is not None
        used = [c for b in blocks for c in b]
        assert sorted(used) == sorted(free)
        assert pack_blocks(free, (2, 1, 1), 3) is None

    def test_planner_multi_pin_blocks_in_one_slice(self):
        """Review repro: a restart can pin members of BOTH blocks inside
        one big slice with more pins than fit one block — the anchor
        fallback must keep the other pins usable, not wedge."""
        from yoda_tpu.plugins.yoda.topology import plan_multislice_placement

        stack, agent = make_stack()
        hosts = agent.add_slice("wide", host_topology=(4, 2, 1))
        agent.publish_all()
        snap = stack.informer.snapshot()
        by_coord = {
            snap.get(h).tpu.topology_coords: h for h in hosts
        }
        pinned = {
            by_coord[(0, 0, 0)]: (0, 0, 0),
            by_coord[(0, 1, 0)]: (0, 1, 0),
            by_coord[(2, 0, 0)]: (2, 0, 0),
        }
        plan = plan_multislice_placement(
            snap,
            want_dims=(2, 2, 1),
            slices=2,
            host_ok=lambda ni: ni.name not in pinned,
            pinned=pinned,
        )
        assert plan is not None and len(plan) == 8
        for h, c in pinned.items():
            assert plan.get(h) == c  # every pinned member kept its host

    def test_planner_insufficient_blocks(self):
        from yoda_tpu.plugins.yoda.topology import plan_multislice_placement

        stack, agent = make_stack()
        agent.add_slice("only", host_topology=(2, 2, 1))
        agent.publish_all()
        snap = stack.informer.snapshot()
        assert (
            plan_multislice_placement(
                snap, want_dims=(2, 2, 1), slices=2, host_ok=lambda ni: True
            )
            is None
        )

    def test_multislice_gang_binds_atomically_one_dispatch(self):
        from yoda_tpu.plugins.yoda import YodaBatch

        stack, agent = make_stack()
        agent.add_slice("ms-a", host_topology=(2, 2, 1))
        agent.add_slice("ms-b", host_topology=(2, 2, 1))
        agent.add_host("edge-0", chips=8)
        agent.publish_all()
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=60.0)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=5.0)
        batch = next(
            p for p in stack.framework.batch_plugins if isinstance(p, YodaBatch)
        )
        d0 = batch.dispatch_count
        labels = {
            "tpu/gang": "ms",
            "tpu/topology": "2x2x1",
            "tpu/multislice": "2",
            "tpu/chips": "4",
        }
        for i in range(8):
            stack.cluster.create_pod(PodSpec(f"ms-{i}", labels=dict(labels)))
        stack.scheduler.run_until_idle(max_wall_s=20.0)
        placed = {
            p.name: p.node_name
            for p in stack.cluster.list_pods()
            if p.labels.get("tpu/gang") == "ms"
        }
        assert all(placed.values()), placed
        hosts = set(placed.values())
        assert len(hosts) == 8  # one member per host
        slices = {h.rsplit("-", 1)[0] for h in hosts}
        assert slices == {"ms-a", "ms-b"}  # both blocks, never the edge host
        assert batch.dispatch_count == d0 + 1  # ONE dispatch for all 8

    def test_two_multislice_gangs_contend_atomically(self):
        """2 gangs x 2 blocks over 3 slices: only one gang can complete;
        the loser holds nothing (all-or-nothing), then completes after the
        winner tears down."""
        stack, agent = make_stack(gang_permit_timeout_s=1.0)
        for s in ("c-a", "c-b", "c-c"):
            agent.add_slice(s, host_topology=(2, 2, 1))
        agent.publish_all()
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=60.0)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=5.0)

        def gang(name):
            labels = {
                "tpu/gang": name,
                "tpu/topology": "2x2x1",
                "tpu/multislice": "2",
                "tpu/chips": "4",
            }
            return [PodSpec(f"{name}-{i}", labels=dict(labels)) for i in range(8)]

        for p in gang("g1") + gang("g2"):
            stack.cluster.create_pod(p)
        stack.scheduler.run_until_idle(max_wall_s=30.0)
        bound = {
            g: [
                p
                for p in stack.cluster.list_pods()
                if p.labels.get("tpu/gang") == g and p.node_name
            ]
            for g in ("g1", "g2")
        }
        counts = sorted(len(v) for v in bound.values())
        assert counts == [0, 8], counts  # exactly one gang fully bound
        winner = next(g for g, v in bound.items() if len(v) == 8)
        for p in bound[winner]:
            stack.cluster.delete_pod(p.key)
        stack.scheduler.run_until_idle(max_wall_s=30.0)
        loser = "g2" if winner == "g1" else "g1"
        loser_bound = [
            p
            for p in stack.cluster.list_pods()
            if p.labels.get("tpu/gang") == loser and p.node_name
        ]
        assert len(loser_bound) == 8  # the loser completed after teardown

    def test_multislice_on_mesh_sharded_kernel(self):
        """mesh_devices mode: the sharded kernel's claimable row feeds the
        same one-dispatch multislice plan."""
        from yoda_tpu.plugins.yoda import YodaBatch

        stack, agent = make_stack(mesh_devices=8)
        agent.add_slice("mm-a", host_topology=(2, 2, 1))
        agent.add_slice("mm-b", host_topology=(2, 2, 1))
        agent.publish_all()
        batch = next(
            p for p in stack.framework.batch_plugins if isinstance(p, YodaBatch)
        )
        labels = {
            "tpu/gang": "mm",
            "tpu/topology": "2x2x1",
            "tpu/multislice": "2",
            "tpu/chips": "4",
        }
        for i in range(8):
            stack.cluster.create_pod(PodSpec(f"mm-{i}", labels=dict(labels)))
        stack.scheduler.run_until_idle(max_wall_s=60.0)
        placed = {
            p.name: p.node_name
            for p in stack.cluster.list_pods()
            if p.labels.get("tpu/gang") == "mm"
        }
        assert all(placed.values()), placed
        assert len(set(placed.values())) == 8
        # All members served from the single gang-fused dispatch (the
        # sharded kernel's evaluate_burst feeds the same claimable rows).
        assert batch.gang_burst_served == 8

    def test_multislice_restart_reconstruction(self):
        """Bound members replayed after a restart pin their blocks; the
        remaining members complete around them."""
        stack, agent = make_stack()
        a_hosts = agent.add_slice("rs-a", host_topology=(2, 2, 1))
        agent.add_slice("rs-b", host_topology=(2, 2, 1))
        agent.publish_all()
        labels = {
            "tpu/gang": "rms",
            "tpu/topology": "2x2x1",
            "tpu/multislice": "2",
            "tpu/chips": "4",
        }
        pods = [PodSpec(f"rms-{i}", labels=dict(labels)) for i in range(8)]
        pods[0].node_name = a_hosts[0]
        pods[0].phase = "Running"
        stack.cluster.create_pod(pods[0])
        agent.publish_all()

        from yoda_tpu.standalone import build_stack as rebuild

        stack2 = rebuild(cluster=stack.cluster)
        assert stack2.gang.gang_status("rms") == (8, 0, 1)
        for p in pods[1:]:
            stack2.cluster.create_pod(p)
        stack2.scheduler.run_until_idle(max_wall_s=20.0)
        placed = {
            p.name: p.node_name
            for p in stack2.cluster.list_pods()
            if p.labels.get("tpu/gang") == "rms"
        }
        assert all(placed.values()), placed
        assert len(set(placed.values())) == 8
        assert placed["rms-0"] == a_hosts[0]  # the pinned member stayed put


@pytest.mark.parametrize("mode", ["batch", "loop"])
class TestCoschedulingCompat:
    def test_pod_group_labels_gang_binds_atomically(self, mode):
        # Workloads written for the sig-scheduling coscheduling plugin
        # (PodGroup lite labels) gang-schedule unmodified.
        stack, agent = make_stack(mode)
        agent.add_host("h1", chips=8)
        agent.add_host("h2", chips=8)
        agent.publish_all()
        labels = {
            "pod-group.scheduling.sigs.k8s.io/name": "pg",
            "pod-group.scheduling.sigs.k8s.io/min-available": "3",
            "tpu/chips": "4",
        }
        for i in range(2):
            stack.cluster.create_pod(PodSpec(f"pg-{i}", labels=dict(labels)))
        stack.scheduler.run_until_idle(max_wall_s=5)
        # Two of three members present: nothing binds (all-or-nothing).
        assert all(
            stack.cluster.get_pod(f"default/pg-{i}").node_name is None
            for i in range(2)
        )
        stack.cluster.create_pod(PodSpec("pg-2", labels=dict(labels)))
        stack.scheduler.run_until_idle(max_wall_s=5)
        bound = [
            stack.cluster.get_pod(f"default/pg-{i}").node_name
            for i in range(3)
        ]
        assert all(bound), bound

    def test_alias_only_member_deletion_cascades(self, mode):
        # Regression: the watch handler resolved gang membership from the
        # raw tpu/gang label, so deleting a member of an alias-only gang
        # left a ghost in `waiting` that could satisfy the Permit barrier.
        stack, agent = make_stack(mode)
        agent.add_host("h1", chips=8)
        agent.publish_all()
        labels = {
            "pod-group.scheduling.sigs.k8s.io/name": "pg-del",
            "pod-group.scheduling.sigs.k8s.io/min-available": "3",
            "tpu/chips": "1",
        }
        for i in range(2):
            stack.cluster.create_pod(PodSpec(f"pgd-{i}", labels=dict(labels)))
        stack.scheduler.run_until_idle(max_wall_s=5)
        # Two members parked at Permit, holding reservations.
        assert stack.accountant.chips_in_use("h1") == 2
        stack.cluster.delete_pod("default/pgd-0")
        stack.scheduler.run_until_idle(max_wall_s=5)
        # The deletion must be SEEN (alias-aware handler): the deleted
        # member's reservation releases and no ghost remains in the gang's
        # waiting set to satisfy the barrier early — same steady state as a
        # tpu/gang-labeled gang (survivor re-parks with its own chip).
        assert stack.accountant.chips_in_use("h1") == 1
        gs = stack.gang._gangs.get("pg-del")
        assert gs is not None and "default/pgd-0" not in gs.waiting
        for name in ("pgd-0b", "pgd-2"):
            stack.cluster.create_pod(PodSpec(name, labels=dict(labels)))
        stack.scheduler.run_until_idle(max_wall_s=5)
        pods = [p for p in stack.cluster.list_pods()]
        bound = [p for p in pods if p.node_name]
        assert len(bound) == 3, [(p.name, p.node_name) for p in pods]


class TestParallelRelease:
    """The pipelined waitlist-release path (gang.py parallel_release +
    the bind executor — wired for remote-bind / latency-injected
    backends, forced on here so the fan-out branch keeps test coverage):
    lazy worker creation, every member released, and the flaky-bind
    self-heal through overlapping releases."""

    def _stack(self):
        from yoda_tpu.config import SchedulerConfig
        from yoda_tpu.standalone import build_stack

        stack = build_stack(config=SchedulerConfig(mode="batch"))
        assert stack.gang.parallel_release is False  # in-process default
        stack.gang.parallel_release = True
        return stack

    def test_gang_binds_through_the_pool(self):
        stack = self._stack()
        agent = FakeTpuAgent(stack.cluster)
        for i in range(4):
            agent.add_host(f"host-{i}", generation="v5p", chips=4)
        agent.publish_all()
        # Workers are lazy: nothing submitted, no pool, until a release.
        assert stack.bind_executor is not None
        assert stack.bind_executor._pool is None
        for pod in gang_pods("par", 4, chips=4):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=10)
        pods = stack.cluster.list_pods()
        assert all(p.node_name for p in pods)
        assert len({p.node_name for p in pods}) == 4
        assert stack.gang.gang_status("par") == (4, 0, 4)
        # The fan-out path engaged: all 4 member releases went through
        # the executor and have settled.
        assert stack.bind_executor.submitted == 4
        assert stack.bind_executor.inflight() == 0

    def test_two_gangs_reuse_the_pool(self):
        stack = self._stack()
        agent = FakeTpuAgent(stack.cluster)
        for i in range(4):
            agent.add_host(f"host-{i}", generation="v5p", chips=8)
        agent.publish_all()
        for tag in ("g1", "g2"):
            for pod in gang_pods(tag, 4, chips=4):
                stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=20)
        # One persistent executor served both gangs' releases.
        assert stack.bind_executor.submitted == 8
        assert stack.bind_executor._pool is not None
        assert all(p.node_name for p in stack.cluster.list_pods())
        assert stack.gang.gang_status("g1") == (4, 0, 4)
        assert stack.gang.gang_status("g2") == (4, 0, 4)

    def test_flaky_bind_self_heals_through_the_pool(self):
        """A bind failing DURING a concurrent release must roll that
        member back and retry while its siblings bind — the every-future-
        observed contract."""
        from yoda_tpu.framework.interfaces import BindPlugin, Code, Status
        from yoda_tpu.config import SchedulerConfig
        from yoda_tpu.standalone import build_stack

        class FlakyBinder(BindPlugin):
            name = "flaky-binder"

            def __init__(self):
                self.tripped = False

            def bind(self, state, pod, node_name):
                if not self.tripped and pod.name == "pf-1":
                    self.tripped = True
                    return Status.error("transient bind failure")
                return Status(code=Code.SKIP)

        flaky = FlakyBinder()
        stack = build_stack(
            config=SchedulerConfig(mode="batch"), extra_plugins=[flaky]
        )
        stack.gang.parallel_release = True
        agent = FakeTpuAgent(stack.cluster)
        for i in range(4):
            agent.add_host(f"host-{i}", generation="v5p", chips=4)
        agent.publish_all()
        for pod in gang_pods("pf", 4, chips=4):
            stack.cluster.create_pod(pod)
        stack.scheduler.run_until_idle(max_wall_s=10)
        assert flaky.tripped
        bound = {p.name: p.node_name for p in stack.cluster.list_pods()}
        assert all(v is not None for v in bound.values()), bound
        assert stack.gang.gang_status("pf") == (4, 0, 4)
