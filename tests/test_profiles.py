"""Scheduler profiles: one process serving several schedulerNames with
different plugin configs (upstream KubeSchedulerConfiguration profiles).
Each profile's stack shares the cluster watch streams; pods route to the
profile whose scheduler_name matches their spec.schedulerName."""

import threading

import pytest

from yoda_tpu.agent import FakeTpuAgent
from yoda_tpu.api.types import PodSpec
from yoda_tpu.cluster import FakeCluster
from yoda_tpu.config import SchedulerConfig
from yoda_tpu.standalone import build_profile_stacks


class TestProfileConfig:
    def test_profiles_inherit_base_and_override(self):
        c = SchedulerConfig.from_dict(
            {
                "mode": "batch",
                "max_metrics_age_s": 30.0,
                "weights": {"hbm_free": 5},
                "profiles": [
                    {
                        "scheduler_name": "yoda-tpu-batch",
                        "scoring_strategy": "most-allocated",
                    }
                ],
            }
        )
        (p,) = c.profiles
        assert p.scheduler_name == "yoda-tpu-batch"
        assert p.scoring_strategy == "most-allocated"
        assert p.max_metrics_age_s == 30.0       # inherited
        assert p.weights.hbm_free == 5           # inherited weights
        assert c.scoring_strategy == "least-allocated"

    def test_profile_requires_scheduler_name(self):
        with pytest.raises(ValueError, match="scheduler_name"):
            SchedulerConfig.from_dict(
                {"profiles": [{"scoring_strategy": "most-allocated"}]}
            )

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError, match="distinct"):
            SchedulerConfig.from_dict(
                {"profiles": [{"scheduler_name": "yoda-tpu"}]}
            )


class TestProfilesE2E:
    def test_pods_route_to_their_profile(self):
        cluster = FakeCluster()
        config = SchedulerConfig.from_dict(
            {
                "profiles": [
                    {
                        "scheduler_name": "yoda-tpu-batch",
                        "scoring_strategy": "most-allocated",
                    }
                ]
            }
        )
        stacks = build_profile_stacks(cluster, config)
        agent = FakeTpuAgent(cluster)
        agent.add_host("h1", chips=8)
        agent.add_host("h2", chips=8)
        agent.publish_all()
        cluster.create_pod(PodSpec("base-pod", labels={"tpu/chips": "1"}))
        cluster.create_pod(
            PodSpec(
                "batch-pod",
                labels={"tpu/chips": "1"},
                scheduler_name="yoda-tpu-batch",
            )
        )
        cluster.create_pod(
            PodSpec(
                "foreign",
                labels={"tpu/chips": "1"},
                scheduler_name="default-scheduler",
            )
        )
        for st in stacks:
            st.scheduler.run_until_idle(max_wall_s=10)
        assert cluster.get_pod("default/base-pod").node_name is not None
        assert cluster.get_pod("default/batch-pod").node_name is not None
        # Neither profile touches a foreign schedulerName.
        assert cluster.get_pod("default/foreign").node_name is None
        # Each profile scheduled exactly its own pod.
        assert stacks[0].scheduler.stats.binds == 1
        assert stacks[1].scheduler.stats.binds == 1

    def test_profiles_see_each_others_reservations(self):
        # Accounting counts every TPU-holding pod regardless of profile,
        # so one profile cannot double-book chips the other placed.
        cluster = FakeCluster()
        config = SchedulerConfig.from_dict(
            {"profiles": [{"scheduler_name": "yoda-tpu-b"}]}
        )
        stacks = build_profile_stacks(cluster, config)
        agent = FakeTpuAgent(cluster)
        agent.add_host("only", chips=2)
        agent.publish_all()
        cluster.create_pod(PodSpec("a", labels={"tpu/chips": "2"}))
        stacks[0].scheduler.run_until_idle(max_wall_s=10)
        assert cluster.get_pod("default/a").node_name == "only"
        cluster.create_pod(
            PodSpec(
                "b", labels={"tpu/chips": "2"}, scheduler_name="yoda-tpu-b"
            )
        )
        stacks[1].scheduler.run_until_idle(max_wall_s=10)
        assert cluster.get_pod("default/b").node_name is None
        assert stacks[1].accountant.chips_in_use("only") == 2

    def test_concurrent_profile_loops(self):
        # Both profiles serving concurrently against one fleet: no
        # oversubscription, every pod lands with its own profile.
        cluster = FakeCluster()
        config = SchedulerConfig.from_dict(
            {"profiles": [{"scheduler_name": "yoda-tpu-b"}]}
        )
        stacks = build_profile_stacks(cluster, config)
        agent = FakeTpuAgent(cluster)
        for i in range(4):
            agent.add_host(f"h{i}", chips=4)
        agent.publish_all()
        stop = threading.Event()
        threads = [
            threading.Thread(
                target=st.scheduler.serve_forever,
                args=(stop,),
                kwargs={"poll_s": 0.005},
                daemon=True,
            )
            for st in stacks
        ]
        for t in threads:
            t.start()
        for i in range(8):
            name = "yoda-tpu" if i % 2 == 0 else "yoda-tpu-b"
            cluster.create_pod(
                PodSpec(
                    f"p{i}", labels={"tpu/chips": "2"}, scheduler_name=name
                )
            )
        import time as _t

        deadline = _t.monotonic() + 20
        while _t.monotonic() < deadline:
            if all(p.node_name for p in cluster.list_pods()):
                break
            _t.sleep(0.02)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        pods = cluster.list_pods()
        assert all(p.node_name for p in pods)
        used = {}
        for p in pods:
            used[p.node_name] = used.get(p.node_name, 0) + 2
        for m in cluster.list_tpu_metrics():
            assert used.get(m.name, 0) <= m.chip_count


class TestProfileWiring:
    """Review regressions: shared accountant/metrics/victim-rules."""

    def _stacks(self):
        cluster = FakeCluster()
        config = SchedulerConfig.from_dict(
            {"profiles": [{"scheduler_name": "yoda-tpu-b"}]}
        )
        return build_profile_stacks(cluster, config)

    def test_metrics_registry_is_shared_and_aggregates(self):
        stacks = self._stacks()
        assert stacks[0].metrics is stacks[1].metrics
        rendered = stacks[0].metrics.registry.render_prometheus()
        # One family, summed over BOTH profiles' batch plugins.
        assert rendered.count("# TYPE yoda_kernel_dispatches_total") == 1
        assert len(stacks[0].metrics._batch_plugins) == 2

    def test_preemption_recognizes_all_profile_names(self):
        stacks = self._stacks()
        for st in stacks:
            assert st.preemption.scheduler_names == {
                "yoda-tpu", "yoda-tpu-b",
            }
        assert stacks[0].accountant is stacks[1].accountant
        assert stacks[0].accountant.scheduler_names == {
            "yoda-tpu", "yoda-tpu-b",
        }

    def test_cycle_lock_is_shared_and_released_during_permit_wait(self):
        # A gang member parks at Permit (outcome "waiting"); the shared
        # cycle lock must already be free or every other profile stalls
        # behind the barrier.
        stacks = self._stacks()
        assert (
            stacks[0].scheduler.cycle_lock is stacks[1].scheduler.cycle_lock
        )
        agent = FakeTpuAgent(stacks[0].cluster)
        agent.add_host("h1", chips=8)
        agent.publish_all()
        stacks[0].cluster.create_pod(
            PodSpec(
                "g-0",
                labels={
                    "tpu/gang": "g", "tpu/gang-size": "2", "tpu/chips": "1"
                },
            )
        )
        # Drain member 0 into the Permit waitlist.
        qpi = stacks[0].queue.pop(timeout=2)
        r = stacks[0].scheduler.schedule_one(qpi)
        assert r.outcome == "waiting"
        assert stacks[0].scheduler.cycle_lock.acquire(timeout=0.5)
        stacks[0].scheduler.cycle_lock.release()

    def test_pending_visibility_spans_profiles(self):
        # A gang member of profile B parked at Permit must repel an
        # anti-affinity pod scheduled by profile A (the pending feed is
        # aggregated over every profile's gang plugin).
        from yoda_tpu.api.affinity import LabelSelector, PodAffinityTerm
        from yoda_tpu.api.types import K8sNode

        HOSTNAME = "kubernetes.io/hostname"
        cluster = FakeCluster()
        config = SchedulerConfig.from_dict(
            {"profiles": [{"scheduler_name": "yoda-tpu-b"}]}
        )
        stacks = build_profile_stacks(cluster, config)
        agent = FakeTpuAgent(cluster)
        for n in ("h1", "h2"):
            agent.add_host(n, chips=8)
            cluster.put_node(K8sNode(n, labels={HOSTNAME: n}))
        agent.publish_all()
        # Profile B: a 2-member gang; member 0 parks at Permit.
        cluster.create_pod(
            PodSpec(
                "g-0",
                labels={
                    "tpu/gang": "g", "tpu/gang-size": "2",
                    "tpu/chips": "1", "app": "g",
                },
                scheduler_name="yoda-tpu-b",
            )
        )
        stacks[1].scheduler.run_until_idle(max_wall_s=5)
        pending = stacks[1].gang.pending_placements()
        assert len(pending) == 1
        parked_host = pending[0][0]
        # Profile A: an anti-affinity pod against app=g must avoid the
        # parked member's host.
        cluster.create_pod(
            PodSpec(
                "loner",
                labels={"tpu/chips": "1"},
                pod_anti_affinity=(
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        selector=LabelSelector(match_labels=(("app", "g"),)),
                    ),
                ),
            )
        )
        stacks[0].scheduler.run_until_idle(max_wall_s=5)
        loner = cluster.get_pod("default/loner")
        assert loner.node_name is not None
        assert loner.node_name != parked_host

    def test_pallas_profile_ignores_inherited_platform_pin(self):
        # Base pins kernel_platform: cpu; a pallas profile that never set
        # the knob must validate (the inherited pin does not apply), while
        # an EXPLICIT pin on the pallas profile still rejects.
        c = SchedulerConfig.from_dict(
            {
                "kernel_platform": "cpu",
                "profiles": [
                    {"scheduler_name": "yoda-tpu-p", "kernel_backend": "pallas"}
                ],
            }
        )
        assert c.profiles[0].kernel_backend == "pallas"
        assert c.profiles[0].kernel_platform == "auto"
        with pytest.raises(ValueError, match="kernel_platform"):
            SchedulerConfig.from_dict(
                {
                    "profiles": [
                        {
                            "scheduler_name": "yoda-tpu-p",
                            "kernel_backend": "pallas",
                            "kernel_platform": "cpu",
                        }
                    ]
                }
            )

    def test_cross_profile_preemption(self):
        # Profile A's high-priority pod evicts profile B's low-priority
        # victim: the victim rules recognize every profile's schedulerName
        # (a single-name rule would make B's pods invisible, never-evictable
        # capacity).
        cluster = FakeCluster()
        config = SchedulerConfig.from_dict(
            {"profiles": [{"scheduler_name": "yoda-tpu-b"}]}
        )
        stacks = build_profile_stacks(cluster, config)
        agent = FakeTpuAgent(cluster)
        agent.add_host("only", chips=2)
        agent.publish_all()
        cluster.create_pod(
            PodSpec(
                "infer",
                labels={"tpu/chips": "2", "tpu/priority": "1"},
                scheduler_name="yoda-tpu-b",
            )
        )
        stacks[1].scheduler.run_until_idle(max_wall_s=5)
        assert cluster.get_pod("default/infer").node_name == "only"
        cluster.create_pod(
            PodSpec("train", labels={"tpu/chips": "2", "tpu/priority": "10"})
        )
        stacks[0].scheduler.run_until_idle(max_wall_s=5)
        assert cluster.get_pod("default/infer") is None  # evicted
        stacks[0].scheduler.run_until_idle(max_wall_s=5)
        assert cluster.get_pod("default/train").node_name == "only"

    def test_pallas_profile_ignores_inherited_mesh(self):
        c = SchedulerConfig.from_dict(
            {
                "mesh_devices": 4,
                "profiles": [
                    {"scheduler_name": "yoda-tpu-p", "kernel_backend": "pallas"}
                ],
            }
        )
        assert c.profiles[0].mesh_devices is None
        assert c.mesh_devices == 4  # base keeps its mesh
