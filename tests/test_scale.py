"""Large-fleet scale: the fused kernel at 1024 nodes.

The TPU-native design's claim is that one XLA dispatch evaluates the whole
fleet regardless of size (SURVEY.md §3.2★ — the reference paid O(nodes)
API round trips per pod). This suite pins that down at three orders of
magnitude above the kind-cluster tests: correctness against the per-node
Python predicates on a sample, end-to-end binding through the full stack,
and a loose steady-state latency bound that would catch an accidental
per-node device round trip sneaking back onto the hot path.
"""

from __future__ import annotations

import random
import time

import pytest

from yoda_tpu.api.requests import parse_request
from yoda_tpu.api.types import make_node
from yoda_tpu.framework.interfaces import NodeInfo, Snapshot
from yoda_tpu.ops.arrays import FleetArrays
from yoda_tpu.ops.kernel import KernelRequest, fused_filter_score
from yoda_tpu.plugins.yoda.filter_plugin import available_chips

GIB = 1 << 30
N_NODES = 1024


def big_snapshot(n=N_NODES) -> Snapshot:
    rng = random.Random(7)
    nodes = {}
    for i in range(n):
        free = rng.choice([2, 4, 8, 16]) * GIB
        name = f"n{i:04d}"
        nodes[name] = NodeInfo(
            name,
            tpu=make_node(
                name,
                chips=8,
                hbm_free_per_chip=free,
                generation=rng.choice(["v5e", "v5p", "v6e"]),
                slice_id=f"s{i // 16}" if i % 4 == 0 else "",
            ),
        )
    return Snapshot(nodes)


class TestKernelAtScale:
    def test_matches_python_predicates_on_sample(self):
        snapshot = big_snapshot()
        req = parse_request({"tpu/chips": "4", "tpu/hbm": "8Gi"})
        arrays = FleetArrays.from_snapshot(snapshot)
        result = fused_filter_score(arrays, KernelRequest.from_request(req))
        rng = random.Random(11)
        sample = rng.sample(range(len(arrays.names)), 50)
        for i in sample:
            ni = snapshot.get(arrays.names[i])
            # reserved=None (no accounting) in both paths.
            expect = available_chips(ni.tpu, req, None) >= 4
            assert bool(result.feasible[i]) == expect, arrays.names[i]
        assert result.best_index >= 0

    def test_steady_state_latency_is_fleet_size_independent(self):
        """After compile, one evaluation over 1024 nodes must stay far
        below the per-node-round-trip regime (loose bound: the reference's
        design was ~1 API call x 1024 nodes x 2 phases per pod)."""
        snapshot = big_snapshot()
        req = KernelRequest.from_request(
            parse_request({"tpu/chips": "2", "tpu/hbm": "4Gi"})
        )
        arrays = FleetArrays.from_snapshot(snapshot)
        fused_filter_score(arrays, req)  # compile at this bucket
        t0 = time.monotonic()
        iters = 10
        for _ in range(iters):
            fused_filter_score(arrays, req)
        per_eval_ms = (time.monotonic() - t0) / iters * 1e3
        assert per_eval_ms < 250, f"kernel eval {per_eval_ms:.1f} ms at 1024 nodes"


class TestStackAtScale:
    @pytest.mark.parametrize("n_nodes", [N_NODES, 4096])
    def test_pods_bind_at_scale(self, n_nodes):
        """Fleet-size independence at the headline scale and one size up:
        the burst must stay well under the 200 ms-per-pod BASELINE budget
        either way."""
        from yoda_tpu.agent import FakeTpuAgent
        from yoda_tpu.api.types import PodSpec
        from yoda_tpu.standalone import build_stack

        stack = build_stack()
        agent = FakeTpuAgent(stack.cluster)
        for i in range(n_nodes):
            agent.add_host(f"h{i:04d}", chips=8)
        agent.publish_all()
        # Warmup compile at this fleet bucket.
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=120)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=10)

        t0 = time.monotonic()
        for i in range(8):
            stack.cluster.create_pod(
                PodSpec(f"p{i}", labels={"tpu/chips": "4", "tpu/hbm": "2Gi"})
            )
        stack.scheduler.run_until_idle(max_wall_s=60)
        dt_ms = (time.monotonic() - t0) * 1e3
        pods = [p for p in stack.cluster.list_pods() if p.name.startswith("p")]
        assert len(pods) == 8 and all(p.node_name for p in pods)
        assert dt_ms < 8 * 200, f"burst took {dt_ms:.0f} ms at {n_nodes} nodes"

    def test_gang_at_scale_is_one_dispatch(self):
        """An 8-member gang against 1024 nodes: one kernel dispatch places
        the whole gang (the gang-fused pass must not degrade with fleet
        size), and the burst stays within the per-pod budget."""
        from yoda_tpu.agent import FakeTpuAgent
        from yoda_tpu.api.types import PodSpec
        from yoda_tpu.plugins.yoda import YodaBatch
        from yoda_tpu.standalone import build_stack

        stack = build_stack()
        agent = FakeTpuAgent(stack.cluster)
        for i in range(N_NODES):
            agent.add_host(f"h{i:04d}", chips=8)
        agent.publish_all()
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=120)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=10)
        batch = next(
            p for p in stack.framework.batch_plugins if isinstance(p, YodaBatch)
        )
        d0 = batch.dispatch_count

        t0 = time.monotonic()
        labels = {"tpu/gang": "big", "tpu/gang-size": "8", "tpu/chips": "8"}
        for i in range(8):
            stack.cluster.create_pod(PodSpec(f"g{i}", labels=dict(labels)))
        stack.scheduler.run_until_idle(max_wall_s=60)
        dt_ms = (time.monotonic() - t0) * 1e3
        pods = [p for p in stack.cluster.list_pods() if p.name.startswith("g")]
        assert len(pods) == 8 and all(p.node_name for p in pods)
        assert len({p.node_name for p in pods}) == 8  # 8 chips each: 1/host
        assert batch.dispatch_count == d0 + 1
        # Co-queued members are gathered and served from the one fused
        # dispatch; none fall back to the lazy per-gang plan.
        assert batch.gang_burst_served == 8
        assert batch.plan_served == 0
        assert dt_ms < 8 * 200, f"gang burst took {dt_ms:.0f} ms"


class TestConstrainedAtScale:
    def test_anti_affinity_pods_bind_against_1024_nodes(self):
        """Inter-pod evaluator cost at fleet scale: anti-affinity pods
        against 1024 labeled nodes must stay within the per-pod budget —
        the evaluator is O(bound pods) per cycle plus O(terms) per node,
        never O(nodes x pods)."""
        from yoda_tpu.agent import FakeTpuAgent
        from yoda_tpu.api.affinity import LabelSelector, PodAffinityTerm
        from yoda_tpu.api.types import K8sNode, PodSpec
        from yoda_tpu.standalone import build_stack

        HOSTNAME = "kubernetes.io/hostname"
        stack = build_stack()
        agent = FakeTpuAgent(stack.cluster)
        for i in range(N_NODES):
            name = f"h{i:04d}"
            agent.add_host(name, chips=8)
            stack.cluster.put_node(K8sNode(name, labels={HOSTNAME: name}))
        agent.publish_all()
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=120)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=10)

        anti = (
            PodAffinityTerm(
                topology_key=HOSTNAME,
                selector=LabelSelector(match_labels=(("app", "web"),)),
            ),
        )
        t0 = time.monotonic()
        for i in range(8):
            stack.cluster.create_pod(
                PodSpec(
                    f"aa{i}",
                    labels={"tpu/chips": "1", "app": "web"},
                    pod_anti_affinity=anti,
                )
            )
        stack.scheduler.run_until_idle(max_wall_s=60)
        dt_ms = (time.monotonic() - t0) * 1e3
        pods = [
            p for p in stack.cluster.list_pods() if p.name.startswith("aa")
        ]
        assert len(pods) == 8 and all(p.node_name for p in pods)
        assert len({p.node_name for p in pods}) == 8  # spread held
        assert dt_ms < 8 * 200, f"burst took {dt_ms:.0f} ms"


class TestBurstAtScale:
    def test_multi_pod_burst_at_scale(self):
        """32 pods against 1024 nodes with batch_requests=16: a couple of
        kernel dispatches place everything, no oversubscription, and the
        whole drain stays far under the per-pod budget."""
        from yoda_tpu.agent import FakeTpuAgent
        from yoda_tpu.api.types import PodSpec
        from yoda_tpu.config import SchedulerConfig
        from yoda_tpu.plugins.yoda import YodaBatch
        from yoda_tpu.standalone import build_stack

        stack = build_stack(config=SchedulerConfig(batch_requests=16))
        agent = FakeTpuAgent(stack.cluster)
        for i in range(N_NODES):
            agent.add_host(f"h{i:04d}", chips=8)
        agent.publish_all()
        # Warmup BOTH kernels at this fleet bucket: a lone pod cannot
        # burst (min 2 candidates), so it compiles the single-pod kernel;
        # the following pair compiles the burst kernel. A serve fallback
        # in the timed phase then never pays a first compile.
        stack.cluster.create_pod(PodSpec("warm0", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=120)
        for i in (1, 2):
            stack.cluster.create_pod(
                PodSpec(f"warm{i}", labels={"tpu/chips": "1"})
            )
        stack.scheduler.run_until_idle(max_wall_s=120)
        for i in range(3):
            stack.cluster.delete_pod(f"default/warm{i}")
        stack.scheduler.run_until_idle(max_wall_s=10)
        batch = next(
            p for p in stack.framework.batch_plugins if isinstance(p, YodaBatch)
        )
        d0 = batch.dispatch_count

        t0 = time.monotonic()
        for i in range(32):
            stack.cluster.create_pod(
                PodSpec(f"p{i}", labels={"tpu/chips": "2"})
            )
        stack.scheduler.run_until_idle(max_wall_s=120)
        dt_ms = (time.monotonic() - t0) * 1e3
        pods = [p for p in stack.cluster.list_pods() if p.name.startswith("p")]
        assert len(pods) == 32 and all(p.node_name for p in pods)
        per_node: dict[str, int] = {}
        for p in pods:
            per_node[p.node_name] = per_node.get(p.node_name, 0) + 2
        assert all(v <= 8 for v in per_node.values())
        # 32 pods / bursts of 16 -> 2 dispatches (plus at most a couple of
        # re-dispatches if a serve fell back).
        assert batch.dispatch_count - d0 <= 6
        assert batch.burst_served >= 26
        assert dt_ms < 32 * 200, f"{dt_ms:.0f} ms for 32 pods at {N_NODES} nodes"


class TestIncrementalAtScale:
    def test_16k_rolling_refreshes_stay_incremental(self):
        """16384 nodes (VERDICT r4 #9): rolling per-node agent refreshes
        must ride the incremental row update (plugins/yoda/batch.py
        ``_incremental_update`` — the SAME FleetArrays object, one O(C)
        row refill) instead of the O(N x C) full rebuild, and per-pod
        scheduling latency must hold the BASELINE budget at 4x the
        previous largest scale point."""
        from yoda_tpu.agent import FakeTpuAgent
        from yoda_tpu.api.types import PodSpec
        from yoda_tpu.plugins.yoda import YodaBatch
        from yoda_tpu.standalone import build_stack

        n = 16384
        stack = build_stack()
        agent = FakeTpuAgent(stack.cluster)
        for i in range(n):
            agent.add_host(f"h{i:05d}", chips=8)
        agent.publish_all()

        # Warmup: pay the kernel compile at the 16384-row bucket.
        stack.cluster.create_pod(PodSpec("warm", labels={"tpu/chips": "1"}))
        stack.scheduler.run_until_idle(max_wall_s=300)
        stack.cluster.delete_pod("default/warm")
        stack.scheduler.run_until_idle(max_wall_s=30)

        batch = next(
            p for p in stack.framework.batch_plugins if isinstance(p, YodaBatch)
        )
        static0 = batch._static
        assert static0 is not None

        # Rolling refreshes: one node's values change per round (the
        # steady-state shape of a real fleet — one agent publishing at a
        # time), each followed by a pod needing a dispatch.
        t0 = time.monotonic()
        rounds = 8
        for k in range(rounds):
            agent.set_chip_health(f"h{k:05d}", chip_index=0, healthy=False)
            agent.refresh(f"h{k:05d}")  # single-CR value change
            stack.cluster.create_pod(
                PodSpec(f"p{k}", labels={"tpu/chips": "4", "tpu/hbm": "2Gi"})
            )
            stack.scheduler.run_until_idle(max_wall_s=60)
        dt_ms = (time.monotonic() - t0) * 1e3

        pods = [p for p in stack.cluster.list_pods() if p.name.startswith("p")]
        assert len(pods) == rounds and all(p.node_name for p in pods)
        # The refreshes were absorbed in place: same arrays object (a full
        # rebuild would have replaced it), with the dirtied rows refilled.
        assert batch._static is static0
        assert not static0.chip_healthy[0, 0]  # h00000's flipped chip
        # Queue latency stays flat vs the 4096 point: same per-pod budget
        # (BASELINE 200 ms) with a per-round single-node refresh in the
        # loop. A regression to full rebuilds costs ~250 ms extra per
        # round at this scale and blows the bound.
        assert dt_ms < rounds * 200, (
            f"rolling refresh+bind took {dt_ms:.0f} ms over {rounds} rounds "
            f"at {n} nodes"
        )
